"""Gang-supervised cluster runtime — multi-host failure recovery.

The reference's pserver tier survived worker loss by restarting trainers
against the latest pass checkpoint (SURVEY.md §5, ``paddle/pserver``); the
TPU-native analog is a **gang supervisor** in the spirit of TorchElastic's
agent model: every rank of a distributed job is launched and monitored as
one gang, and ANY failure — a rank dying, or a rank *hanging* (the common
TPU mode: JAX collectives deadlock rather than error once a peer is gone)
— kills the whole gang and relaunches it, with bounded restarts and
exponential backoff.  Recovery rides the existing ``--resume=auto`` path,
so a killed-and-relaunched run reproduces an uninterrupted run's losses.

Two halves:

- **worker side** — :class:`GangContext` (``current_gang()``): rank
  identity plus the coordination primitives the resilience tier needs to
  be multi-host-correct — per-rank **heartbeat** files (written at batch
  boundaries from the MAIN thread, so a rank stuck in a collective stops
  heartbeating), a sequence-numbered **barrier** (all ranks agree a
  checkpoint is complete before rank 0 rename-publishes it, the
  t5x/Orbax commit protocol), an OR-reduced **preemption** flag (a
  SIGTERM delivered to one host checkpoints everyone consistently), and
  a coordinator **broadcast** (``latest_valid_pass`` resolves on rank 0,
  not just locally).  The file protocol needs only a directory shared
  with the supervisor; on live ``jax.distributed`` pods without one, the
  same API degrades to DCN collectives (:class:`_JaxGang`).
- **supervisor side** — :class:`GangSupervisor`: launches the gang
  through :class:`~paddle_tpu.parallel.launcher.ClusterLauncher`, polls
  for rank death, watches heartbeat staleness against the watchdog
  budget (``--gang_watchdog_s``), and drives the restart loop.  Budget
  exhausted raises :class:`~paddle_tpu.resilience.errors.GangFailedError`
  with per-rank exit attribution.

Supervisor state machine (docs/resilience.md "Multi-host recovery")::

    LAUNCH -> MONITOR --all ranks exit 0--------------------> DONE
                 |  \\--rank died / heartbeat stale--> KILL GANG
                 |                                        |
                 +--deadline exceeded--> GangFailedError  |
                                                          v
              restarts left?  --no--> GangFailedError   BACKOFF
                     ^--yes------------------------------/
"""

from __future__ import annotations

import json
import os
import shutil
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from paddle_tpu.resilience.errors import GangError, GangFailedError
from paddle_tpu.utils import FLAGS, logger

__all__ = [
    "GangContext",
    "GangSupervisor",
    "GangResult",
    "RankReport",
    "current_gang",
]

# Env wiring injected by GangSupervisor (alongside the launcher's
# PADDLE_TPU_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID):
_ENV_DIR = "PADDLE_TPU_GANG_DIR"          # per-ATTEMPT shared directory
_ENV_SIZE = "PADDLE_TPU_GANG_SIZE"
_ENV_RANK = "PADDLE_TPU_GANG_RANK"        # falls back to _PROCESS_ID
_ENV_HEARTBEAT = "PADDLE_TPU_GANG_HEARTBEAT_S"

_POLL_S = 0.02


def _atomic_write(path: str, data: str) -> None:
    tmp = f"{path}.tmp-{uuid.uuid4().hex[:8]}"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)


class GangContext:
    """Worker-side gang coordination over a shared directory.

    The directory is per-ATTEMPT (the supervisor creates a fresh one for
    every relaunch), so no state — barrier arrivals, preemption flags,
    published decisions — can leak from a previous incarnation of the
    gang into the next.
    """

    def __init__(self, gang_dir: str, rank: int, size: int,
                 heartbeat_s: Optional[float] = None,
                 barrier_timeout_s: float = 600.0) -> None:
        self.gang_dir = gang_dir
        self.rank = int(rank)
        self.size = int(size)
        self.heartbeat_s = (FLAGS.gang_heartbeat_s if heartbeat_s is None
                            else float(heartbeat_s))
        self.barrier_timeout_s = float(barrier_timeout_s)
        self._barrier_seq = 0
        self._hb_count = 0
        self._hb_last = 0.0
        self._preempt_flagged = False

    @property
    def is_coordinator(self) -> bool:
        return self.rank == 0

    # -- heartbeat -------------------------------------------------------

    def heartbeat(self, *, force: bool = False) -> None:
        """Touch this rank's heartbeat file.  Called from the TRAINING
        loop's main thread at batch boundaries — deliberately NOT from a
        background thread, so a rank wedged inside a collective stops
        heartbeating and the supervisor's watchdog can see it."""
        now = time.monotonic()
        if not force and now - self._hb_last < self.heartbeat_s:
            return
        self._hb_count += 1
        try:
            _atomic_write(os.path.join(self.gang_dir, f"hb-rank{self.rank}"),
                          str(self._hb_count))
        except OSError as e:  # gang dir swept mid-write: supervisor owns it
            logger.warning("gang heartbeat failed: %s", e)
            return
        self._hb_last = now

    # -- barrier ---------------------------------------------------------

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        """Sequence-numbered all-ranks barrier.

        Every rank executes the SAME sequence of barrier calls (the saves
        of a deterministic training loop), so a plain per-process counter
        names each rendezvous.  Waiting ranks keep heartbeating — a slow
        checkpoint write on rank 0 must not read as a hang."""
        n = self._barrier_seq
        self._barrier_seq += 1
        me = os.path.join(self.gang_dir, f"barrier-{n:05d}-rank{self.rank}")
        _atomic_write(me, "1")
        deadline = time.monotonic() + (self.barrier_timeout_s
                                       if timeout_s is None else timeout_s)
        want = [os.path.join(self.gang_dir, f"barrier-{n:05d}-rank{r}")
                for r in range(self.size)]
        while True:
            if all(os.path.exists(p) for p in want):
                return
            if time.monotonic() > deadline:
                raise GangError(
                    f"rank {self.rank}: barrier {n} timed out after "
                    f"{self.barrier_timeout_s:.0f}s — a peer likely died "
                    "(the supervisor will relaunch the gang)")
            self.heartbeat()
            time.sleep(_POLL_S)

    # -- preemption OR-reduce -------------------------------------------

    def agree_preempt(self, local: bool) -> bool:
        """Gang-wide OR of the per-rank preemption request, evaluated at
        the batch boundary: a SIGTERM delivered to ONE host makes every
        rank checkpoint at its next boundary, so the published mid-pass
        checkpoint is consistent across the gang."""
        if local and not self._preempt_flagged:
            _atomic_write(
                os.path.join(self.gang_dir, f"preempt-rank{self.rank}"), "1")
            self._preempt_flagged = True
        if self._preempt_flagged:
            return True
        try:
            names = os.listdir(self.gang_dir)
        except OSError:
            return local
        return any(n.startswith("preempt-rank") for n in names)

    # -- coordinator broadcast ------------------------------------------

    def broadcast_json(self, obj: Optional[Any], *, name: str = "decision",
                       timeout_s: Optional[float] = None) -> Any:
        """Rank 0 publishes ``obj`` (JSON) under ``name``; every other
        rank blocks (heartbeating) until it appears and returns it.  The
        resume-decision plane: ``latest_valid_pass`` resolves on the
        coordinator and the gang follows, never a locally-newer pass a
        peer cannot see."""
        path = os.path.join(self.gang_dir, f"pub-{name}.json")
        if self.is_coordinator:
            _atomic_write(path, json.dumps(obj))
            return obj
        deadline = time.monotonic() + (self.barrier_timeout_s
                                       if timeout_s is None else timeout_s)
        while True:
            try:
                with open(path) as f:
                    return json.load(f)
            except (FileNotFoundError, json.JSONDecodeError):
                pass
            if time.monotonic() > deadline:
                raise GangError(
                    f"rank {self.rank}: no coordinator decision {name!r} "
                    f"within {self.barrier_timeout_s:.0f}s")
            self.heartbeat()
            time.sleep(_POLL_S)


class _JaxGang:
    """GangContext API over live ``jax.distributed`` collectives — the
    path for platform-launched pods (GKE/xpk) that share no filesystem
    with a supervisor.  Heartbeats are a no-op (the platform's own agent
    watches liveness there)."""

    def __init__(self) -> None:
        import jax

        self.rank = jax.process_index()
        self.size = jax.process_count()
        self._seq = 0

    @property
    def is_coordinator(self) -> bool:
        return self.rank == 0

    def heartbeat(self, *, force: bool = False) -> None:
        pass

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        from jax.experimental import multihost_utils

        n = self._seq
        self._seq += 1
        multihost_utils.sync_global_devices(f"paddle_tpu_gang_barrier_{n}")

    def agree_preempt(self, local: bool) -> bool:
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([bool(local)], dtype=np.bool_))
        return bool(np.any(flags))

    def broadcast_json(self, obj: Optional[Any], *, name: str = "decision",
                       timeout_s: Optional[float] = None) -> Any:
        import numpy as np
        from jax.experimental import multihost_utils

        cap = 4096
        buf = np.zeros((cap,), np.uint8)
        if self.is_coordinator:
            raw = json.dumps(obj).encode()
            if len(raw) > cap - 8:
                raise GangError(f"broadcast payload {name!r} exceeds {cap}B")
            buf[:8] = np.frombuffer(
                len(raw).to_bytes(8, "little"), np.uint8)
            buf[8:8 + len(raw)] = np.frombuffer(raw, np.uint8)
        out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
        n = int.from_bytes(out[:8].tobytes(), "little")
        return json.loads(out[8:8 + n].tobytes().decode())


def current_gang():
    """The active gang context for THIS process, or ``None``.

    Supervisor-launched ranks (``PADDLE_TPU_GANG_DIR`` set) get the
    shared-directory protocol; a live multi-process ``jax.distributed``
    run without one gets the collective-backed equivalent; single-process
    runs get ``None`` and every gang hook no-ops.
    """
    gang_dir = os.environ.get(_ENV_DIR)
    if gang_dir:
        rank = int(os.environ.get(_ENV_RANK,
                                  os.environ.get("PADDLE_TPU_PROCESS_ID", "0")))
        size = int(os.environ.get(_ENV_SIZE, "1"))
        hb = os.environ.get(_ENV_HEARTBEAT)
        return GangContext(gang_dir, rank, size,
                           heartbeat_s=float(hb) if hb else None)
    import sys

    jax = sys.modules.get("jax")
    if jax is not None and jax.process_count() > 1:
        return _JaxGang()
    return None


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


@dataclass
class RankReport:
    """Attribution for one rank's part in a failed attempt."""

    attempt: int
    rank: int
    pid: int
    exit_code: Optional[int]       # None = still alive when the gang died
    reason: str                    # 'exit' | 'hung' | 'gang-killed' | ...
    stale_s: Optional[float] = None  # heartbeat age at hang detection

    def describe(self) -> str:
        tail = (f" (heartbeat stale {self.stale_s:.1f}s)"
                if self.stale_s is not None else "")
        code = "alive" if self.exit_code is None else f"exit={self.exit_code}"
        return f"attempt {self.attempt} rank {self.rank}: {self.reason}, {code}{tail}"


@dataclass
class GangResult:
    """Outcome of a successful ``GangSupervisor.run()``."""

    attempts: int
    reports: List[RankReport] = field(default_factory=list)


class GangSupervisor:
    """Launch, watch, and gang-restart an N-rank job.

    ``hosts`` follows :class:`ClusterLauncher` (``["localhost"]*2`` for a
    local CPU gang); every rank runs ``python script args...`` with the
    distributed wiring AND the gang wiring (shared attempt directory,
    heartbeat cadence) injected.  ``run()`` returns a :class:`GangResult`
    once an attempt sees every rank exit 0, and raises
    :class:`GangFailedError` when ``max_restarts`` relaunches are burned
    (or ``deadline_s`` passes) — carrying per-rank attribution for every
    failed attempt.

    ``on_restart(supervisor, attempt)`` runs between a gang kill and the
    next launch — the chaos harness corrupts checkpoints there; ``tick``
    runs every monitor poll (tests inject mid-pass faults through it).
    """

    def __init__(
        self,
        hosts: Sequence[str],
        script: str,
        args: Sequence[str] = (),
        *,
        env: Optional[Dict[str, str]] = None,
        cwd: Optional[str] = None,
        gang_dir: Optional[str] = None,
        max_restarts: Optional[int] = None,
        heartbeat_s: Optional[float] = None,
        watchdog_s: Optional[float] = None,
        startup_grace_s: Optional[float] = None,
        backoff_s: float = 1.0,
        max_backoff_s: float = 30.0,
        poll_s: float = 0.05,
        coordinator_port: Optional[Callable[[], int]] = None,
        sleep: Callable[[float], None] = time.sleep,
        on_restart: Optional[Callable[["GangSupervisor", int], None]] = None,
        tick: Optional[Callable[["GangSupervisor", int, float], None]] = None,
    ) -> None:
        self.hosts = list(hosts)
        self.script = script
        self.args = list(args)
        self.env = dict(env or {})
        self.cwd = cwd
        self.gang_dir = gang_dir or os.path.join(
            os.getcwd(), f".gang-{uuid.uuid4().hex[:8]}")
        self.max_restarts = (FLAGS.gang_max_restarts if max_restarts is None
                             else int(max_restarts))
        self.heartbeat_s = (FLAGS.gang_heartbeat_s if heartbeat_s is None
                            else float(heartbeat_s))
        self.watchdog_s = (FLAGS.gang_watchdog_s if watchdog_s is None
                           else float(watchdog_s))
        # ranks need import + first compile before the first heartbeat can
        # exist; until then liveness is judged against this grace window
        self.startup_grace_s = (max(60.0, self.watchdog_s)
                                if startup_grace_s is None
                                else float(startup_grace_s))
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.poll_s = float(poll_s)
        self._port = coordinator_port
        self._sleep = sleep
        self._on_restart = on_restart
        self._tick = tick
        self.reports: List[RankReport] = []
        self.launcher = None           # live ClusterLauncher, for chaos hooks
        self.attempt_dir: Optional[str] = None
        self._created_dirs: List[str] = []

    # -- one attempt -----------------------------------------------------

    def _launch(self, attempt: int):
        from paddle_tpu.parallel.launcher import ClusterLauncher

        self.attempt_dir = os.path.join(self.gang_dir, f"attempt-{attempt:03d}")
        os.makedirs(self.attempt_dir, exist_ok=True)
        self._created_dirs.append(self.attempt_dir)
        kw = {}
        if self._port is not None:
            kw["coordinator_port"] = self._port()
        launcher = ClusterLauncher(hosts=self.hosts, **kw)
        env = {
            **self.env,
            _ENV_DIR: self.attempt_dir,
            _ENV_SIZE: str(len(self.hosts)),
            _ENV_HEARTBEAT: str(self.heartbeat_s),
        }
        launcher.launch(self.script, self.args, env=env, cwd=self.cwd)
        self.launcher = launcher
        return launcher

    def _hb_age(self, rank: int, now: float) -> Optional[float]:
        """Seconds since rank's last heartbeat, or None if none yet."""
        try:
            mtime = os.path.getmtime(
                os.path.join(self.attempt_dir, f"hb-rank{rank}"))
        except OSError:
            return None
        return max(0.0, now - mtime)

    def _monitor(self, launcher, attempt: int,
                 deadline: Optional[float]) -> Optional[List[RankReport]]:
        """Poll until success (returns None) or failure (rank reports)."""
        t0 = time.monotonic()
        drain_since = None   # first time we saw a partial zero-exit gang
        while True:
            codes = launcher.poll()
            if all(c == 0 for c in codes):
                return []
            dead = [(r, c) for r, c in enumerate(codes)
                    if c is not None and c != 0]
            if dead:
                return [
                    RankReport(attempt, r, launcher.procs[r].pid, c, "exit")
                    for r, c in dead
                ]
            now = time.monotonic()
            elapsed = now - t0
            # straggler drain: some ranks exited 0 while peers run on.  A
            # peer blocked in a barrier whose partner is gone heartbeats
            # while it waits (slow saves must not read as hangs), so
            # neither the death poll nor the staleness watchdog would ever
            # fire — bound the inconsistency with the same watchdog budget
            if any(c == 0 for c in codes):
                if drain_since is None:
                    drain_since = now
                elif now - drain_since > self.watchdog_s:
                    return [RankReport(
                        attempt, r, launcher.procs[r].pid, None,
                        "straggler (peers already exited)",
                        stale_s=now - drain_since)
                        for r, c in enumerate(codes) if c is None]
            else:
                drain_since = None
            wall = time.time()
            hung = []
            for r, c in enumerate(codes):
                if c is not None:      # exited 0, waiting on peers
                    continue
                age = self._hb_age(r, wall)
                if age is None:
                    if elapsed > self.startup_grace_s:
                        hung.append(RankReport(
                            attempt, r, launcher.procs[r].pid, None,
                            "hung (no heartbeat after startup grace)",
                            stale_s=elapsed))
                elif age > self.watchdog_s:
                    hung.append(RankReport(
                        attempt, r, launcher.procs[r].pid, None, "hung",
                        stale_s=age))
            if hung:
                return hung
            if deadline is not None and now > deadline:
                raise GangFailedError(
                    f"gang did not complete within the deadline "
                    f"({elapsed:.0f}s into attempt {attempt})",
                    reports=self.reports)
            if self._tick is not None:
                self._tick(self, attempt, elapsed)
            self._sleep(self.poll_s)

    # -- the restart loop ------------------------------------------------

    def run(self, *, deadline_s: Optional[float] = None) -> GangResult:
        os.makedirs(self.gang_dir, exist_ok=True)
        deadline = (time.monotonic() + deadline_s) if deadline_s else None
        attempt = 0
        while True:
            launcher = self._launch(attempt)
            logger.info("gang attempt %d: %d ranks launched", attempt,
                        len(self.hosts))
            try:
                failed = self._monitor(launcher, attempt, deadline)
            except BaseException:
                launcher.kill_gang()
                raise
            if not failed:
                launcher.wait(timeout=60)
                logger.info("gang attempt %d: all %d ranks exited 0",
                            attempt, len(self.hosts))
                self._scrub_attempt_dirs()
                return GangResult(attempts=attempt + 1, reports=self.reports)
            # attribute the peers that the gang kill takes down with it
            culprits = {f.rank for f in failed}
            self.reports.extend(failed)
            for r, c in enumerate(launcher.poll()):
                if r not in culprits:
                    self.reports.append(RankReport(
                        attempt, r, launcher.procs[r].pid, c, "gang-killed"))
            logger.warning("gang attempt %d failed: %s", attempt,
                           "; ".join(f.describe() for f in failed))
            launcher.kill_gang()
            if attempt >= self.max_restarts:
                raise GangFailedError(
                    f"gang failed {attempt + 1} times "
                    f"(max_restarts={self.max_restarts}); per-rank: "
                    + "; ".join(f.describe() for f in self.reports),
                    reports=self.reports)
            if self._on_restart is not None:
                self._on_restart(self, attempt)
            delay = min(self.backoff_s * (2.0 ** attempt), self.max_backoff_s)
            logger.info("gang restart %d/%d in %.1fs", attempt + 1,
                        self.max_restarts, delay)
            self._sleep(delay)
            attempt += 1

    def _scrub_attempt_dirs(self) -> None:
        """Success path: drop the attempt dirs THIS run created (heartbeat
        / barrier / flag scratch — never checkpoints) so supervised runs
        don't accumulate debris; the gang dir itself goes only if empty
        (it may be user-supplied and shared).  Failed runs keep their
        attempt dirs for post-mortem."""
        for d in self._created_dirs:
            shutil.rmtree(d, ignore_errors=True)
        self._created_dirs.clear()
        try:
            os.rmdir(self.gang_dir)
        except OSError:
            pass

    def cleanup(self) -> None:
        """Remove the gang scratch directory (attempt state only — never
        checkpoints; those live under the job's own save_dir)."""
        shutil.rmtree(self.gang_dir, ignore_errors=True)
