"""Atomic, verified checkpoints — the durable tier of the resilience plane.

Reference lineage: the per-pass directories of ``ParamUtil.cpp`` (one
``pass-%05d`` dir per pass).  The reference's writer was not atomic — a
kill mid-save left a half-written directory that ``--start_pass`` would
happily resume from.  Production TPU training is preemption-dominated, so
here every checkpoint is:

- **written atomically**: arrays land in a dot-prefixed temp directory
  (invisible to ``latest_pass``), every file is fsynced, and the temp dir
  is ``os.replace``d into its final ``pass-%05d`` name in one rename;
- **verified**: ``manifest.json`` records a CRC32 per stored array, the
  original dtype of every leaf (npz cannot represent ml_dtypes — see
  ``npz_safe``), array shapes, wall-clock time, and caller metadata;
  ``load_checkpoint``/``latest_pass`` re-hash on read and skip/refuse
  corrupt directories;
- **bounded**: a ``keep_last_n`` retention policy prunes the oldest pass
  dirs after each successful save.

Checkpoints remain plain npz + JSON — host-side and device-layout
independent, so a checkpoint taken on an 8-chip mesh restores on 1 chip.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
import uuid
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from paddle_tpu.resilience.errors import CheckpointError
from paddle_tpu.utils import FLAGS, logger

__all__ = [
    "MANIFEST_VERSION",
    "QUARANTINE_MARKER",
    "npz_safe",
    "save_pytree",
    "load_pytree",
    "save_checkpoint",
    "load_checkpoint",
    "read_manifest",
    "validate_checkpoint",
    "quarantine_checkpoint",
    "quarantine_reason",
    "failing_member",
    "latest_pass",
    "latest_valid_pass",
    "prune_checkpoints",
    "pass_dir",
]

MANIFEST_VERSION = 1

# pass ids are rendered %05d but GROW past 5 digits (pass 100000 renders as
# 6); the pattern must accept the overflow or resume silently stops finding
# checkpoints after ~11 years of hourly passes
_PASS_RE = re.compile(r"pass-(\d{5,})")

_TMP_PREFIX = ".tmp-"

# written by the SDC scrubber (resilience/integrity.py) into a dir whose
# payload no longer re-hashes: validation refuses the dir from then on
# (demoted out of latest_pass eligibility) while the forensic evidence
# stays on disk for the postmortem
QUARANTINE_MARKER = "QUARANTINED"

# a temp dir younger than this is treated as an IN-FLIGHT save by a
# concurrent writer and left alone by prune_checkpoints; older ones are
# debris from a crashed save and get swept
_TMP_GRACE_S = 900.0


def pass_dir(save_dir: str, pass_id: int) -> str:
    return os.path.join(save_dir, f"pass-{pass_id:05d}")


# ---------------------------------------------------------------------------
# pytree <-> npz with a verification manifest
# ---------------------------------------------------------------------------


def npz_safe(a) -> np.ndarray:
    """npz cannot represent ml_dtypes (bfloat16 etc. round-trip as raw void
    bytes and fail to load) — store such arrays as float32; the manifest
    records the original dtype so loaders restore it exactly (bf16 -> f32
    is lossless)."""
    arr = np.asarray(a)
    if arr.dtype.kind == "V" or arr.dtype.name not in np.sctypeDict:
        return arr.astype(np.float32)
    return arr


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _flatten(tree) -> Dict[str, Tuple[np.ndarray, str]]:
    """tree -> {key: (storable array, original dtype name)}."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        flat[key] = (npz_safe(leaf), str(np.asarray(leaf).dtype))
    return flat


def save_pytree(path: str, tree: Any) -> Dict[str, Dict[str, Any]]:
    """Write one compressed npz; returns the manifest ``arrays`` section:
    per-key CRC32 of the stored bytes, original/stored dtype, shape."""
    flat = _flatten(tree)
    np.savez_compressed(path, **{k: a for k, (a, _) in flat.items()})
    entries: Dict[str, Dict[str, Any]] = {}
    for key, (arr, orig) in flat.items():
        entries[key] = {
            "crc32": zlib.crc32(np.ascontiguousarray(arr).tobytes()),
            "orig_dtype": orig,
            "stored_dtype": str(arr.dtype),
            "shape": [int(d) for d in arr.shape],
        }
    return entries


def load_pytree(path: str, like: Any,
                dtypes: Optional[Dict[str, str]] = None) -> Any:
    """Restore into the structure of ``like`` (same treedef).

    ``dtypes`` is the manifest's ``{key: orig_dtype}`` map; when present it
    wins over the dtype of the ``like`` leaf, so a bf16 parameter stored as
    f32 round-trips to bf16 even if the receiving tree was built f32."""
    data = np.load(path, allow_pickle=False)
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for path_k, leaf in paths_leaves:
        key = jax.tree_util.keystr(path_k)
        if key not in data:
            raise KeyError(f"checkpoint missing {key}")
        arr = data[key]
        target = (dtypes or {}).get(key)
        dt = _np_dtype(target) if target else np.asarray(leaf).dtype
        leaves.append(np.asarray(arr).astype(dt))
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# atomic save
# ---------------------------------------------------------------------------


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse directory fsync
    finally:
        os.close(fd)


def save_checkpoint(save_dir: str, pass_id: int, *, params, state=None,
                    opt_state=None, extra: Optional[Dict[str, Any]] = None,
                    meta: Optional[dict] = None,
                    keep_last_n: Optional[int] = None,
                    barrier: Optional[Callable[[], None]] = None) -> str:
    """Atomically write ``save_dir/pass-%05d``.

    The write goes to a dot-prefixed temp dir (never matched by
    ``latest_pass``), each npz plus the manifest is fsynced, then one
    ``os.replace`` publishes the checkpoint; a crash at ANY point leaves
    either the previous checkpoint or a garbage temp dir — never a
    half-written ``pass-%05d``.

    ``extra`` maps extra npz file stems to pytrees (e.g. averaged params);
    ``meta`` lands verbatim under manifest ``meta``; ``keep_last_n``
    (default ``FLAGS.keep_last_n``; 0 = unlimited) prunes the oldest pass
    dirs after the save succeeds.

    ``barrier`` (multi-host commit protocol, t5x/Orbax style) is invoked
    after every file is written and fsynced but BEFORE the rename-publish:
    in a gang, rank 0 passes the gang barrier here while every other rank
    calls the matching ``gang.barrier()``, so a checkpoint only becomes
    visible once ALL ranks have reached the commit point — no rank can
    later resume past a checkpoint a peer never saw.  If the barrier
    raises (peer died), the temp dir is discarded and the previous
    checkpoint stays in place.
    """
    if keep_last_n is None:
        keep_last_n = FLAGS.keep_last_n
    os.makedirs(save_dir, exist_ok=True)
    final = pass_dir(save_dir, pass_id)
    tmp = os.path.join(
        save_dir, f"{_TMP_PREFIX}pass-{pass_id:05d}-{uuid.uuid4().hex[:8]}")
    os.makedirs(tmp)
    aside = None
    try:
        files: Dict[str, Dict[str, Any]] = {}
        trees = {"params.npz": params}
        if state is not None:
            trees["state.npz"] = state
        if opt_state is not None:
            trees["opt_state.npz"] = opt_state
        for stem, tree in (extra or {}).items():
            trees[f"{stem}.npz"] = tree
        for fname, tree in trees.items():
            fpath = os.path.join(tmp, fname)
            files[fname] = {"arrays": save_pytree(fpath, tree)}
            _fsync_file(fpath)
        manifest = {
            "version": MANIFEST_VERSION,
            "pass_id": pass_id,
            "time": time.time(),
            "has_state": state is not None,
            "has_opt": opt_state is not None,
            "files": files,
            "meta": dict(meta or {}),
        }
        mpath = os.path.join(tmp, "manifest.json")
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        if barrier is not None:
            barrier()  # gang commit point: all ranks agree the save is done
        # publish: replace() is atomic for the rename.  An existing dir from
        # an earlier save of the same pass (e.g. a preemption checkpoint
        # being overwritten by the completed pass) is moved ASIDE first, not
        # deleted — a crash in this window must never destroy the only
        # checkpoint; the aside copy is removed only after the new one is
        # in place (and swept by retention if we die before that).
        if os.path.isdir(final):
            aside = os.path.join(
                save_dir, f"{_TMP_PREFIX}old-{pass_id:05d}-{uuid.uuid4().hex[:8]}")
            os.replace(final, aside)
        os.replace(tmp, final)
        _fsync_dir(save_dir)
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        if aside is not None and not os.path.isdir(final):
            os.replace(aside, final)  # put the previous checkpoint back
        raise
    if keep_last_n and keep_last_n > 0:
        prune_checkpoints(save_dir, keep_last_n)
    return final


def _newest_mtime(tmp_dir: str) -> float:
    """Freshest mtime of a temp dir OR anything inside it.  The dir's own
    mtime only advances on entry create/rename — a writer streaming one
    huge npz for longer than the grace window would look abandoned by the
    dir timestamp alone while its file mtime keeps moving."""
    newest = os.path.getmtime(tmp_dir)
    try:
        with os.scandir(tmp_dir) as it:
            for entry in it:
                try:
                    newest = max(newest, entry.stat().st_mtime)
                except OSError:
                    continue
    except OSError:
        pass
    return newest


def prune_checkpoints(save_dir: str, keep_last_n: int) -> List[str]:
    """Delete all but the newest ``keep_last_n`` pass dirs (by pass id);
    also sweeps abandoned temp dirs from crashed saves.  Returns removed
    paths.

    Concurrency-safe against a peer writer/pruner sharing ``save_dir``
    (two gang attempts overlapping during a restart, or retention racing
    a preemption save): temp dirs modified within ``_TMP_GRACE_S`` are an
    IN-FLIGHT save and are skipped, and every stat/remove tolerates
    ENOENT — an entry a concurrent prune already removed is simply
    counted as gone, never raised mid-retention."""
    removed = []
    try:
        names = os.listdir(save_dir)
    except OSError:
        return removed
    ids = []
    now = time.time()
    for name in names:
        m = _PASS_RE.fullmatch(name)
        if m:
            ids.append(int(m.group(1)))
        elif name.startswith(_TMP_PREFIX):
            p = os.path.join(save_dir, name)
            try:
                if now - _newest_mtime(p) < _TMP_GRACE_S:
                    continue  # a concurrent save owns this dir
            except OSError:
                continue      # vanished under us: a peer swept it
            shutil.rmtree(p, ignore_errors=True)
            removed.append(p)
    for pid in sorted(ids)[:-keep_last_n] if keep_last_n > 0 else []:
        p = pass_dir(save_dir, pid)
        shutil.rmtree(p, ignore_errors=True)
        removed.append(p)
    return removed


# ---------------------------------------------------------------------------
# validation
# ---------------------------------------------------------------------------


def read_manifest(ckpt_dir: str) -> Dict[str, Any]:
    mpath = os.path.join(ckpt_dir, "manifest.json")
    with open(mpath) as f:
        return json.load(f)


def validate_checkpoint(ckpt_dir: str, *, verify_crc: bool = True) -> Optional[str]:
    """None if the checkpoint is loadable, else a human-readable reason.

    Legacy (pre-manifest-v1) directories — a flat manifest with no
    ``files`` section, or bare npz files — are accepted when their
    ``params.npz`` parses; they simply cannot be CRC-verified."""
    if not os.path.isdir(ckpt_dir):
        return "not a directory"
    q = quarantine_reason(ckpt_dir)
    if q is not None:
        return q
    try:
        manifest = read_manifest(ckpt_dir)
    except FileNotFoundError:
        return "missing manifest.json"
    except (json.JSONDecodeError, OSError) as e:
        return f"unreadable manifest.json: {e}"
    files = manifest.get("files")
    if files is None:  # legacy format: best effort
        ppath = os.path.join(ckpt_dir, "params.npz")
        if not os.path.exists(ppath):
            return "missing params.npz"
        try:
            np.load(ppath, allow_pickle=False).files
        except Exception as e:
            return f"params.npz unreadable: {type(e).__name__}: {e}"
        return None
    for fname, info in files.items():
        fpath = os.path.join(ckpt_dir, fname)
        if not os.path.exists(fpath):
            return f"missing {fname}"
        if not verify_crc:
            continue
        try:
            data = np.load(fpath, allow_pickle=False)
            keys = set(data.files)
        except Exception as e:
            return f"{fname} unreadable: {type(e).__name__}: {e}"
        for key, entry in info.get("arrays", {}).items():
            if key not in keys:
                return f"{fname} missing array {key}"
            try:
                arr = data[key]
            except Exception as e:
                return f"{fname}:{key} undecodable: {type(e).__name__}: {e}"
            crc = zlib.crc32(np.ascontiguousarray(arr).tobytes())
            if crc != entry.get("crc32"):
                return (f"{fname}:{key} CRC mismatch "
                        f"({crc:#x} != {entry.get('crc32', 0):#x})")
    return None


def quarantine_checkpoint(ckpt_dir: str, reason: str) -> None:
    """Drop ``ckpt_dir`` out of ``latest_pass`` eligibility without
    destroying it: a marker file validation refuses from then on.  Used
    by the at-rest scrubber (resilience/integrity.py) when a previously
    valid checkpoint stops re-hashing.  The marker protocol is shared
    with pserver snapshot dirs (``pserver.snapshot.quarantine_snapshot``
    delegates here)."""
    tmp = os.path.join(ckpt_dir,
                       f".{QUARANTINE_MARKER}-{uuid.uuid4().hex[:8]}")
    try:
        with open(tmp, "w") as f:
            json.dump({"reason": reason, "time": time.time()}, f)
        os.replace(tmp, os.path.join(ckpt_dir, QUARANTINE_MARKER))
    except OSError as e:
        logger.warning("could not quarantine %s: %s", ckpt_dir, e)


def quarantine_reason(d: str) -> Optional[str]:
    """The validation-reason string for a quarantined dir, or ``None``
    when no marker is present — the read half of the shared marker
    protocol."""
    qpath = os.path.join(d, QUARANTINE_MARKER)
    if not os.path.exists(qpath):
        return None
    try:
        with open(qpath) as f:
            why = json.load(f).get("reason", "")
    except (OSError, json.JSONDecodeError, ValueError):
        why = ""
    return "quarantined by scrubber" + (f": {why}" if why else "")


def failing_member(reason: str) -> str:
    """Best-effort extraction of the file/member a validation reason
    names ('params.npz:KEY CRC mismatch' -> 'params.npz'), for journal
    records and fsck output; '' when no member is identifiable."""
    if not reason:
        return ""
    toks = reason.split()
    if toks[0] == "missing" and len(toks) > 1:
        return toks[1]
    if "." in toks[0]:
        return toks[0].split(":", 1)[0]
    return ""


def latest_pass(save_dir: str, *, validate: bool = True) -> int:
    """Highest pass id with a VALID checkpoint under save_dir, or -1.

    Corrupt/truncated directories (failed CRC, missing files or manifest)
    are logged and skipped, so resume lands on the newest checkpoint that
    will actually load — the self-locating ``--start_pass`` analog."""
    if not os.path.isdir(save_dir):
        return -1
    ids = []
    for name in os.listdir(save_dir):
        m = _PASS_RE.fullmatch(name)
        if m:
            ids.append(int(m.group(1)))
    for pid in sorted(ids, reverse=True):
        if not validate:
            return pid
        reason = validate_checkpoint(pass_dir(save_dir, pid))
        if reason is None:
            return pid
        logger.warning("skipping corrupt checkpoint %s: %s",
                       pass_dir(save_dir, pid), reason)
        # not just a log line: postmortems (`obs merge`) must see WHEN a
        # checkpoint went bad and which member failed, not merely that
        # resume landed on an earlier pass (no-op without --obs_journal)
        from paddle_tpu.obs import journal_event

        journal_event("ckpt_quarantined", dir=pass_dir(save_dir, pid),
                      member=failing_member(reason), reason=reason)
    return -1


def latest_valid_pass(save_dir: str) -> int:
    """Alias of ``latest_pass(validate=True)`` for call sites that want the
    validation behavior spelled out."""
    return latest_pass(save_dir, validate=True)


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------


def _file_dtypes(manifest: Dict[str, Any], fname: str) -> Optional[Dict[str, str]]:
    files = manifest.get("files") or {}
    info = files.get(fname)
    if not info:
        return None
    return {k: v["orig_dtype"] for k, v in info.get("arrays", {}).items()
            if "orig_dtype" in v}


def load_checkpoint(save_dir: str, pass_id: int, *, params, state=None,
                    opt_state=None, extra_like: Optional[Dict[str, Any]] = None,
                    validate: bool = True):
    """Validate + restore ``pass-%05d``; raises CheckpointError when the
    directory fails verification.  Returns ``(params, state, opt_state)``
    (plus a ``{stem: tree}`` dict as a 4th element when ``extra_like``
    names extra files to restore).  Dtypes restore from the manifest's
    ``orig_dtype`` map, falling back to the ``like`` tree for legacy
    checkpoints.  ``validate=False`` skips the CRC pass — for callers
    that JUST validated (e.g. auto-resume after a validating
    ``latest_pass``), large checkpoints should not be decompressed and
    hashed twice inside the preemption grace window."""
    d = pass_dir(save_dir, pass_id)
    if validate:
        reason = validate_checkpoint(d)
        if reason is not None:
            raise CheckpointError(f"checkpoint {d} failed validation: {reason}")
    try:
        manifest = read_manifest(d)
    except FileNotFoundError:
        manifest = {}
    out_params = load_pytree(os.path.join(d, "params.npz"), params,
                             dtypes=_file_dtypes(manifest, "params.npz"))
    out_state = state
    out_opt = opt_state
    if state is not None and os.path.exists(os.path.join(d, "state.npz")):
        out_state = load_pytree(os.path.join(d, "state.npz"), state,
                                dtypes=_file_dtypes(manifest, "state.npz"))
    if opt_state is not None and os.path.exists(os.path.join(d, "opt_state.npz")):
        out_opt = load_pytree(os.path.join(d, "opt_state.npz"), opt_state,
                              dtypes=_file_dtypes(manifest, "opt_state.npz"))
    if extra_like is None:
        return out_params, out_state, out_opt
    extras = {}
    for stem, like in extra_like.items():
        fpath = os.path.join(d, f"{stem}.npz")
        if os.path.exists(fpath):
            extras[stem] = load_pytree(
                fpath, like, dtypes=_file_dtypes(manifest, f"{stem}.npz"))
    return out_params, out_state, out_opt, extras
