"""Preemption handling — SIGTERM/SIGINT -> checkpoint at a batch boundary.

TPU fleets deliver a grace window before eviction (the PATHWAYS-style
orchestration contract): the runtime sends SIGTERM, the job has seconds to
persist.  ``PreemptionHandler`` converts those signals into a *request*
flag; the trainer polls it at every batch boundary, writes an atomic
mid-pass checkpoint (manifest carries ``next_batch`` so auto-resume
re-enters the pass at the exact batch), and returns cleanly.

Handlers install as a context manager and restore the previous disposition
on exit.  Installation is best-effort: ``signal.signal`` only works in the
main thread — elsewhere the handler degrades to the programmatic
``request()`` path (which is also how the chaos harness delivers simulated
preemptions without killing the test process).

Multi-host: with a gang attached (``handler.gang``, wired by the trainer
from ``resilience.cluster.current_gang()``), ``poll()`` is a GANG-AGREED
decision — the local latch is OR-reduced across every process, so a
SIGTERM delivered to one host makes the whole gang checkpoint at the same
consistent point instead of leaving N-1 ranks to die mid-collective.
``poll()`` is called by the trainer at every batch boundary, the one spot
every rank passes symmetrically: on live pods the reduce is a DCN
collective and MUST be executed by all processes in lockstep, which is
also why the ``requested`` property stays local and side-effect-free —
reading it from an event handler on one rank can never deadlock the pod.
"""

from __future__ import annotations

import os
import signal as _signal
import threading
from typing import Dict, Optional, Tuple

from paddle_tpu.utils import logger

__all__ = ["PreemptionHandler"]


class PreemptionHandler:
    """Latches a preemption request from OS signals or ``request()``."""

    def __init__(self, signals: Tuple[int, ...] = (_signal.SIGTERM,
                                                   _signal.SIGINT),
                 gang=None) -> None:
        self.signals = tuple(signals)
        self._requested = threading.Event()
        self._prev: Dict[int, object] = {}
        self._installed = False
        self.signum: Optional[int] = None
        # a resilience.cluster gang context (or None): requested becomes
        # the OR over all ranks' local latches
        self.gang = gang

    # -- context manager -------------------------------------------------

    def __enter__(self) -> "PreemptionHandler":
        self.install()
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def install(self) -> None:
        if self._installed:
            return
        for s in self.signals:
            try:
                self._prev[s] = _signal.signal(s, self._on_signal)
            except ValueError:
                # not the main thread: signal-driven preemption unavailable,
                # request() still works
                logger.warning(
                    "PreemptionHandler: cannot install handler for signal "
                    "%s outside the main thread", s)
        self._installed = True

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            try:
                _signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
        self._prev.clear()
        self._installed = False

    # -- request plane ---------------------------------------------------

    def _on_signal(self, signum, frame) -> None:
        if self._requested.is_set():
            # second signal: the user/orchestrator is done waiting (e.g. a
            # hung reader never reaches the batch boundary) — restore the
            # previous dispositions and re-deliver so Ctrl-C/kill behave
            # normally again
            logger.warning(
                "second signal %s before the batch boundary: restoring "
                "default handlers", _signal.Signals(signum).name)
            self.uninstall()
            os.kill(os.getpid(), signum)
            return
        self.signum = signum
        self._requested.set()
        logger.warning(
            "signal %s received: checkpoint requested at next batch "
            "boundary", _signal.Signals(signum).name)

    def request(self, signum: Optional[int] = None) -> None:
        """Programmatic preemption (chaos harness / orchestrator hook)."""
        self.signum = signum
        self._requested.set()

    def poll(self) -> bool:
        """Gang-agreed preemption check — the trainer's batch-boundary
        probe.  Without a gang this is just the local latch; with one,
        the latch is OR-reduced across ranks (flag files on the shared
        dir, or a DCN allgather on live pods — every rank calls poll()
        at every boundary, keeping the collective symmetric), and a
        gang-sourced request latches locally so the decision is sticky
        even if the flag's origin rank exits first."""
        local = self._requested.is_set()
        gang = self.gang
        if gang is None or gang.size <= 1:
            return local
        if gang.agree_preempt(local):
            self._requested.set()
            return True
        return False

    @property
    def requested(self) -> bool:
        """Local latch only — side-effect-free and collective-free, safe
        to read from any rank or thread.  Gang agreement happens in
        ``poll()``."""
        return self._requested.is_set()

    def clear(self) -> None:
        self._requested.clear()
        self.signum = None
