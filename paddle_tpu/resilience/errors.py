"""Typed failure classes for the resilience subsystem.

Each maps a recovery path to a distinct exception so callers (and the
trainer's event plane) can attribute a failure to the tier that produced
it: storage (``CheckpointError``), the input pipeline (``ReaderError``),
or the numerics of the step itself (``TooManyBadSteps``).
"""

from __future__ import annotations

__all__ = ["CheckpointError", "ReaderError", "TooManyBadSteps"]


class CheckpointError(RuntimeError):
    """A checkpoint directory failed validation (missing files, CRC
    mismatch, unreadable manifest) or could not be written atomically."""


class ReaderError(RuntimeError):
    """The data reader raised (or kept raising past its retry budget).

    The trainer re-raises reader-side failures under this type so a
    mid-pass crash is attributed to the input pipeline, never to the
    train step that happened to be in flight.
    """


class TooManyBadSteps(RuntimeError):
    """The bad-step guard skipped ``max_bad_steps`` consecutive updates —
    the loss/gradients are persistently non-finite and continuing would
    only burn accelerator time."""
