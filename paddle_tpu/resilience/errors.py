"""Typed failure classes for the resilience subsystem.

Each maps a recovery path to a distinct exception so callers (and the
trainer's event plane) can attribute a failure to the tier that produced
it: storage (``CheckpointError``), the input pipeline (``ReaderError``),
the numerics of the step itself (``TooManyBadSteps``), or the cluster
runtime (``GangError`` / ``GangFailedError``).
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["CheckpointError", "ReaderError", "TooManyBadSteps",
           "GangError", "GangFailedError", "GangResized", "SDCDivergence",
           "DCNError", "DCNTimeout", "DCNPartitioned"]


class CheckpointError(RuntimeError):
    """A checkpoint directory failed validation (missing files, CRC
    mismatch, unreadable manifest) or could not be written atomically."""


class ReaderError(RuntimeError):
    """The data reader raised (or kept raising past its retry budget).

    The trainer re-raises reader-side failures under this type so a
    mid-pass crash is attributed to the input pipeline, never to the
    train step that happened to be in flight.
    """


class TooManyBadSteps(RuntimeError):
    """The bad-step guard skipped ``max_bad_steps`` consecutive updates —
    the loss/gradients are persistently non-finite and continuing would
    only burn accelerator time."""


class SDCDivergence(RuntimeError):
    """This rank's in-jit state fingerprint lost the cross-replica vote
    (resilience/integrity.py): its params/optimizer-slots differ from the
    replicas that are bit-identical by construction — the silent-data-
    corruption signature.  Raising it exits the rank nonzero so the
    elastic supervisor expels (shrinks) it and heals the gang from a
    verified checkpoint; the divergence itself is journaled and the
    quarantine marker names this rank in the gang dir."""


class GangError(RuntimeError):
    """A gang coordination primitive failed on the WORKER side: a barrier
    or coordinator-broadcast timed out (a peer likely died mid-protocol).
    The supervisor treats the resulting nonzero exit like any rank death
    and relaunches the gang."""


class GangResized(Exception):
    """Control-flow signal, not a failure: the supervisor published a new
    world while this rank was blocked in a gang barrier (typically the
    save barrier — waiting on a peer that just died).  Carries the new
    ``world`` dict; the trainer catches it at its save sites and runs the
    elastic resize protocol instead of waiting out the barrier timeout.
    A rank that does not catch it exits nonzero and the supervisor falls
    back to the whole-gang relaunch — never less safe than the old path.
    """

    def __init__(self, world: dict) -> None:
        super().__init__(f"gang resized to epoch {world.get('epoch')}: "
                         f"ranks {world.get('ranks')}")
        self.world = dict(world)


class DCNError(GangError):
    """A cross-pod (DCN) transport operation failed.  Subclass of
    ``GangError`` so every existing worker-side handler that treats a
    gang-primitive failure as fatal keeps working unchanged; the typed
    subclasses below add WHICH pod was unreachable and WHY."""

    def __init__(self, message: str, *, pod: Optional[int] = None,
                 op: str = "", attempts: int = 0) -> None:
        super().__init__(message)
        #: pod index the transport attributes the failure to (None when
        #: no single pod could be blamed)
        self.pod = pod
        #: the transport operation that failed (e.g. "exchange sdc-...")
        self.op = op
        #: attempts made (1 + retries) before giving up
        self.attempts = attempts


class DCNTimeout(DCNError):
    """A DCN exchange exhausted its retry budget and the missing pod is
    NOT heartbeating — indistinguishable from pod death on this evidence,
    so the caller should let the normal pod-failure path (supervisor
    watchdog -> elastic shrink of the dcn axis) attribute and expel it."""


class DCNPartitioned(DCNError):
    """A DCN exchange exhausted its retry budget while the missing pod's
    ranks were still heartbeating: the pod is alive but unreachable over
    DCN — a network partition, not a death.  Distinct from
    :class:`DCNTimeout` so the supervisor can expel a partitioned pod
    with "partition" attribution (and tests can pin the difference), and
    distinct from "pod slow", which the bounded retries absorb without
    raising at all."""


class GangFailedError(RuntimeError):
    """The gang supervisor burned its restart budget (or deadline).

    ``reports`` carries per-attempt, per-rank attribution
    (:class:`~paddle_tpu.resilience.cluster.RankReport`): which rank died
    with what exit code, which rank hung and how stale its heartbeat was,
    and which ranks were merely gang-killed alongside the culprit.
    """

    def __init__(self, message: str, *, reports: Optional[List] = None) -> None:
        super().__init__(message)
        self.reports = list(reports or [])
