"""``paddle_tpu.resilience`` — fault-tolerant training subsystem.

Production TPU training is preemption-dominated; this package makes every
tier of the trainer survivable (docs/resilience.md):

- **checkpoint_io** — atomic, CRC-verified ``pass-%05d`` checkpoints with
  a manifest (per-array CRC32 + original dtypes + wall-clock + meta),
  ``keep_last_n`` retention, and a validating ``latest_pass`` that skips
  corrupt directories;
- **guard** — in-jit finite checks on loss and gradient global-norm with a
  ``lax.cond`` skip of the optimizer update (no host syncs; audited by
  ``paddle_tpu.analysis``);
- **reader** — ``resilient_reader`` retry/backoff/skip-bad-batch wrapper;
- **signals** — SIGTERM/SIGINT -> checkpoint-at-batch-boundary + clean
  exit (``PreemptionHandler``), gang-agreed when a cluster context is
  attached;
- **cluster** — the gang-supervised runtime (docs/resilience.md
  "Multi-host recovery"): ``GangSupervisor`` kills and relaunches the
  whole gang on rank death or heartbeat stall (bounded restarts,
  exponential backoff, per-rank attribution in ``GangFailedError``);
  ``current_gang()`` gives workers the barrier / preemption-OR /
  coordinator-broadcast primitives that make checkpoints and resume
  multi-host-consistent;
- **chaos** — fault injection (corrupt/truncate checkpoints, NaN-grad
  batches, flaky readers, simulated preemptions, rank kill/hang) proving
  each recovery path end-to-end in tests/test_resilience.py and
  tests/test_gang.py.
"""

from paddle_tpu.resilience.errors import (CheckpointError, DCNError,
                                          DCNPartitioned, DCNTimeout,
                                          GangError, GangFailedError,
                                          GangResized, ReaderError,
                                          SDCDivergence, TooManyBadSteps)
from paddle_tpu.resilience.cluster import (GangContext, GangResult,
                                           GangSupervisor, RankReport,
                                           current_gang)
from paddle_tpu.resilience.checkpoint_io import (MANIFEST_VERSION,
                                                 latest_pass,
                                                 latest_valid_pass,
                                                 load_checkpoint,
                                                 load_pytree, npz_safe,
                                                 pass_dir,
                                                 prune_checkpoints,
                                                 read_manifest,
                                                 save_checkpoint,
                                                 save_pytree,
                                                 validate_checkpoint)
from paddle_tpu.resilience.guard import (global_grad_norm, guarded_update,
                                         init_loss_scale,
                                         scaled_guarded_update)
from paddle_tpu.resilience.integrity import (ScrubDaemon, fingerprint_hex,
                                             fingerprint_int,
                                             latest_verified_pass,
                                             make_agreement_check,
                                             np_tree_fingerprint,
                                             scrub_paths, sdc_vote,
                                             sdc_vote_pods,
                                             tree_fingerprint)
from paddle_tpu.resilience.reader import resilient_reader
from paddle_tpu.resilience.signals import PreemptionHandler
from paddle_tpu.resilience import chaos

__all__ = [
    "CheckpointError",
    "ReaderError",
    "TooManyBadSteps",
    "GangError",
    "GangFailedError",
    "GangResized",
    "DCNError",
    "DCNTimeout",
    "DCNPartitioned",
    "GangContext",
    "GangResult",
    "GangSupervisor",
    "RankReport",
    "current_gang",
    "MANIFEST_VERSION",
    "npz_safe",
    "save_pytree",
    "load_pytree",
    "save_checkpoint",
    "load_checkpoint",
    "read_manifest",
    "validate_checkpoint",
    "latest_pass",
    "latest_valid_pass",
    "prune_checkpoints",
    "pass_dir",
    "global_grad_norm",
    "guarded_update",
    "init_loss_scale",
    "scaled_guarded_update",
    "resilient_reader",
    "PreemptionHandler",
    "chaos",
    "SDCDivergence",
    "tree_fingerprint",
    "np_tree_fingerprint",
    "fingerprint_int",
    "fingerprint_hex",
    "sdc_vote",
    "sdc_vote_pods",
    "make_agreement_check",
    "scrub_paths",
    "latest_verified_pass",
    "ScrubDaemon",
]
