"""Silent-data-corruption firewall — detect what CRCs at rest cannot.

Every failure the rest of this package recovers from is *loud*: a dead
rank, a NaN loss, a checkpoint that fails its CRC on read.  At fleet
scale the dominant unhandled hazard is *silent* corruption — a flaky core
or DMA bit-flip that leaves one data-parallel replica's parameters subtly
wrong while training marches on (Google's "Cores that don't count" and
Meta's "Silent Data Corruptions at Scale" both report per-mille
defective-host rates).  This module holds the three defenses:

- **in-jit fingerprints** (`tree_fingerprint`): a 64-bit digest of an
  arbitrary pytree — every leaf bitcast to u32 words and folded with
  position-dependent odd multipliers in wraparound u32 arithmetic, leaves
  combined in sorted-key order.  Pure integer ops, so the digest is
  BIT-STABLE across process restarts, jit recompiles, mesh resizes, CPU
  vs TPU backends, and any sharding/placement of the leaves (integer adds
  commute exactly; GSPMD partial-sums change nothing mod 2^32).  The
  trainer computes it INSIDE the compiled step over the post-update
  params + optimizer slots (+ pserver tables), so the parameters
  themselves never cross the host link — only the 8-byte digest does, at
  the check cadence, exactly like the loss.  `np_tree_fingerprint` is the
  bit-identical host twin (pinned against the jit form by test).

- **cross-replica agreement** (`sdc_vote`, `make_agreement_check`): the
  digests are exchanged across the data-parallel replicas
  (`GangContext.exchange_json` on supervised gangs, `lax.all_gather`
  over the mesh data axis via `make_agreement_check` for replica-stacked
  state) and compared.  A unique strict majority identifies the minority
  rank(s) — those are quarantined and expelled through the elastic
  shrink.  A TIE (the 2-replica case: attribution is information-
  theoretically impossible without a third voter) breaks against the
  non-coordinator ranks AND marks every survivor's state suspect, so
  survivors roll back to the last verified checkpoint — state
  correctness is guaranteed regardless of which replica actually
  flipped; only the *attribution* needs >=3 replicas to be exact.

- **at-rest scrubbing** (`scrub_paths`, `ScrubDaemon`, `python -m
  paddle_tpu fsck`): checkpoints, pserver shard snapshots, and deploy
  bundles are re-hashed long after their first read.  A newly-corrupt
  checkpoint dir is QUARANTINED (marker file `validate_checkpoint`
  honors, demoting it out of `latest_pass` eligibility), the failure is
  journaled as a fsync'd `scrub_fail` anchor, and `scrub.json` records
  the newest fully-verified pass so rollback always has a trusted
  target.

See docs/resilience.md "Silent corruption" for the failure-model table.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, List, Mapping, Optional, Sequence,
                    Tuple)

import numpy as np

from paddle_tpu.utils import logger

__all__ = [
    "tree_fingerprint",
    "np_tree_fingerprint",
    "fingerprint_int",
    "fingerprint_hex",
    "sdc_vote",
    "sdc_vote_pods",
    "SdcVote",
    "make_agreement_check",
    "ScrubFinding",
    "ScrubReport",
    "scrub_paths",
    "latest_verified_pass",
    "ScrubDaemon",
    "audit_sdc_step",
    "run_fsck",
]

# ---------------------------------------------------------------------------
# fingerprints — one u64 per pytree, identical in-jit and on the host
# ---------------------------------------------------------------------------

# odd multipliers (Knuth/xxhash lineage): position-dependent weights make
# the fold sensitive to WHERE a bit flipped, not only that one did; two
# independent lanes push the collision floor to ~2^-64.  These constants
# are part of the on-disk/manifest contract — changing them invalidates
# every recorded fingerprint, so they are pinned by a golden test.
_MUL1 = 2654435761   # 2^32 / golden ratio
_MUL2 = 2246822519   # xxhash PRIME32_2
_SALT2 = 0x9E3779B9
_COMBINE = 2654435789


def _np_u32_words(arr: np.ndarray) -> np.ndarray:
    """Any array -> its raw bits as a flat u32 word stream (narrow dtypes
    zero-extend per element, 64-bit dtypes split into two words)."""
    a = np.ascontiguousarray(arr)
    if a.dtype == np.bool_:
        a = a.astype(np.uint8)
    size = a.dtype.itemsize
    if size == 4:
        return a.view(np.uint32).ravel()
    if size == 2:
        return a.view(np.uint16).ravel().astype(np.uint32)
    if size == 1:
        return a.view(np.uint8).ravel().astype(np.uint32)
    if size == 8:
        # little-endian word order, matching lax.bitcast_convert_type's
        # minor-dimension split
        return a.view(np.uint32).ravel()
    raise TypeError(f"unsupported dtype {a.dtype} for fingerprinting")


def _np_fold(arr: np.ndarray) -> Tuple[np.uint32, np.uint32]:
    w = _np_u32_words(arr)
    if w.size == 0:
        return np.uint32(0), np.uint32(0)
    i = np.arange(1, w.size + 1, dtype=np.uint32)
    l1 = np.sum(w * (i * np.uint32(_MUL1) | np.uint32(1)), dtype=np.uint32)
    l2 = np.sum((w ^ np.uint32(_SALT2))
                * (i * np.uint32(_MUL2) | np.uint32(1)), dtype=np.uint32)
    return l1, l2


def _jnp_u32_words(x):
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(x)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    size = x.dtype.itemsize
    if size == 4:
        return lax.bitcast_convert_type(x, jnp.uint32).reshape(-1)
    if size == 2:
        return lax.bitcast_convert_type(
            x, jnp.uint16).astype(jnp.uint32).reshape(-1)
    if size == 1:
        return lax.bitcast_convert_type(
            x, jnp.uint8).astype(jnp.uint32).reshape(-1)
    if size == 8:
        # bitcast 64->32 appends a minor dim of 2 (lo, hi on LE) — the
        # flatten order matches the numpy view above
        return lax.bitcast_convert_type(x, jnp.uint32).reshape(-1)
    raise TypeError(f"unsupported dtype {x.dtype} for fingerprinting")


def _jnp_fold(x):
    import jax.numpy as jnp

    w = _jnp_u32_words(x)
    if w.size == 0:
        return jnp.zeros((), jnp.uint32), jnp.zeros((), jnp.uint32)
    i = jnp.arange(1, w.size + 1, dtype=jnp.uint32)
    l1 = jnp.sum(w * (i * jnp.uint32(_MUL1) | jnp.uint32(1)),
                 dtype=jnp.uint32)
    l2 = jnp.sum((w ^ jnp.uint32(_SALT2))
                 * (i * jnp.uint32(_MUL2) | jnp.uint32(1)),
                 dtype=jnp.uint32)
    return l1, l2


def _sorted_leaves(tree):
    import jax

    leaves = [(jax.tree_util.keystr(path), leaf) for path, leaf
              in jax.tree_util.tree_flatten_with_path(tree)[0]]
    leaves.sort(key=lambda kv: kv[0])
    return leaves


def _key_salt(key: str) -> int:
    return zlib.crc32(key.encode()) & 0xFFFFFFFF


def tree_fingerprint(tree):
    """(2,) uint32 digest of ``tree`` — jit-safe, zero host transfers.

    Leaves combine in sorted-``keystr`` order with a per-key CRC salt, so
    the digest depends on (structure, names, values) and nothing else:
    not on device placement, sharding, mesh shape, or which backend
    computed it.  ``fingerprint_int`` packs the two lanes into the one
    u64 per rank that crosses the gang channel."""
    import jax.numpy as jnp

    acc1 = jnp.zeros((), jnp.uint32)
    acc2 = jnp.zeros((), jnp.uint32)
    for key, leaf in _sorted_leaves(tree):
        l1, l2 = _jnp_fold(leaf)
        salt = jnp.uint32(_key_salt(key))
        acc1 = acc1 * jnp.uint32(_COMBINE) + (l1 ^ salt)
        acc2 = acc2 * jnp.uint32(_COMBINE) + (l2 ^ salt)
    return jnp.stack([acc1, acc2])


def np_tree_fingerprint(tree) -> np.ndarray:
    """Host twin of :func:`tree_fingerprint` — bit-identical by test.
    The combine runs in python ints masked to 32 bits (numpy SCALAR
    arithmetic warns on the wraparound the fold depends on)."""
    acc1 = 0
    acc2 = 0
    mask = 0xFFFFFFFF
    for key, leaf in _sorted_leaves(tree):
        l1, l2 = _np_fold(np.asarray(leaf))
        salt = _key_salt(key)
        acc1 = (acc1 * _COMBINE + (int(l1) ^ salt)) & mask
        acc2 = (acc2 * _COMBINE + (int(l2) ^ salt)) & mask
    return np.asarray([acc1, acc2], np.uint32)


def fingerprint_int(fp) -> int:
    """Pack the (2,) u32 lanes into one python u64."""
    a = np.asarray(fp, np.uint32).reshape(-1)
    return (int(a[0]) << 32) | int(a[1])


def fingerprint_hex(fp) -> str:
    return f"{fingerprint_int(fp):016x}"


# ---------------------------------------------------------------------------
# the vote
# ---------------------------------------------------------------------------


@dataclass
class SdcVote:
    """Outcome of one cross-replica agreement round.

    ``tie`` means no unique strict majority existed (the 2-replica case,
    or an even split): attribution is impossible, so the tie breaks
    against the non-coordinator ranks AND every survivor must treat its
    own state as suspect (roll back to the last verified checkpoint) —
    correctness never depends on guessing right."""

    agreed: bool
    presumed: int                  # fingerprint presumed good
    minority: List[int] = field(default_factory=list)
    tie: bool = False


def sdc_vote(fps: Mapping[int, int], coordinator: int) -> SdcVote:
    """Majority vote over ``{rank: u64 fingerprint}``.

    A unique value held by a strict majority of ranks is presumed good
    and every other rank is minority.  Without one, the coordinator's
    value is presumed (deterministic on every rank — all ranks see the
    same fps) and ``tie`` is set so callers run the conservative
    rollback path."""
    if not fps:
        return SdcVote(agreed=True, presumed=0)
    counts: Dict[int, int] = {}
    for v in fps.values():
        counts[v] = counts.get(v, 0) + 1
    if len(counts) == 1:
        return SdcVote(agreed=True, presumed=next(iter(counts)))
    best = max(counts.values())
    leaders = [v for v, c in counts.items() if c == best]
    if len(leaders) == 1 and best * 2 > len(fps):
        presumed, tie = leaders[0], False
    else:
        presumed, tie = fps[coordinator], True
    minority = sorted(r for r, v in fps.items() if v != presumed)
    return SdcVote(agreed=False, presumed=presumed, minority=minority,
                   tie=tie)


def _fold_digest(digest: "tuple") -> int:
    """Rotate-xor fold of a pod digest into one u64 — purely a stable
    label for journaling/logging (`SdcVote.presumed` is rendered %016x).
    A single-member digest folds to the member's own fingerprint, so
    pod_size-1 voting journals the same value :func:`sdc_vote` would."""
    acc = 0
    for v in digest:
        acc = (((acc << 7) | (acc >> 57)) ^ int(v)) & 0xFFFFFFFFFFFFFFFF
    return acc


def sdc_vote_pods(fps: Mapping[int, int], coordinator: int,
                  pod_of: Callable[[int], int]) -> SdcVote:
    """Pod-level majority vote over ``{rank: u64 fingerprint}``.

    With a dcn axis bound, ranks WITHIN a pod are shards of one replica
    (their fingerprints legitimately differ rank to rank), while pods are
    bit-identical replicas of each other — so the unit of agreement is
    the POD: its digest is the rank-ordered tuple of its members'
    fingerprints, and the vote runs over pod digests.  A minority pod's
    ranks are ALL minority (the pod is the failure unit — one corrupt
    shard poisons every collective the pod runs), so the elastic
    supervisor quarantines and expels the whole pod.  Tie-break mirrors
    :func:`sdc_vote`: no unique strict majority of PODS presumes the
    coordinator's pod and sets ``tie`` so survivors run the conservative
    rollback path."""
    if not fps:
        return SdcVote(agreed=True, presumed=0)
    members: Dict[int, List[Tuple[int, int]]] = {}
    for r, v in fps.items():
        members.setdefault(pod_of(r), []).append((int(r), int(v)))
    digests = {p: tuple(v for _, v in sorted(ms))
               for p, ms in members.items()}
    counts: Dict[tuple, int] = {}
    for d in digests.values():
        counts[d] = counts.get(d, 0) + 1
    if len(counts) == 1:
        return SdcVote(agreed=True,
                       presumed=_fold_digest(next(iter(counts))))
    best = max(counts.values())
    leaders = [d for d, c in counts.items() if c == best]
    if len(leaders) == 1 and best * 2 > len(digests):
        presumed_digest, tie = leaders[0], False
    else:
        presumed_digest, tie = digests[pod_of(coordinator)], True
    minority = sorted(r for p, ms in members.items()
                      if digests[p] != presumed_digest for r, _ in ms)
    return SdcVote(agreed=False, presumed=_fold_digest(presumed_digest),
                   minority=minority, tie=tie)


# ---------------------------------------------------------------------------
# in-jit agreement collective over the mesh data axis
# ---------------------------------------------------------------------------


def make_agreement_check(mesh, axis: Optional[str] = None):
    """Compile the agreement check over the mesh's data axis.

    Returns a jitted ``check(stacked_tree) -> (fps [R, 2] u32, minority
    [R] bool)`` where every leaf of ``stacked_tree`` carries a leading
    replica dimension of size R sharded over the data axis.  Inside
    ``shard_map`` each replica fingerprints its OWN slice, the 8-byte
    digests are ``lax.all_gather``-ed across the axis, and the
    minority mask is computed in-trace — params never leave the device
    and nothing crosses the host link (the ``lint --sdc`` audit pins
    the per-rank fingerprint path host-transfer-free; ties still
    resolve host-side via :func:`sdc_vote`)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.api import agreement_spec
    from paddle_tpu.parallel import compat

    mesh, axis, n = agreement_spec(mesh, axis)

    def body(stacked):
        local = jax.tree_util.tree_map(
            lambda x: x.reshape(x.shape[1:]), stacked)
        fp = tree_fingerprint(local)
        fps = lax.all_gather(fp, axis)                       # [n, 2]
        same = jnp.all(fps[:, None, :] == fps[None, :, :], axis=-1)
        votes = jnp.sum(same.astype(jnp.int32), axis=1)      # [n]
        minority = votes * 2 <= n                            # no strict maj.
        return fps, minority

    shm = compat.shard_map(body, mesh=mesh, in_specs=(P(axis),),
                           out_specs=(P(), P()))
    return jax.jit(shm)


# ---------------------------------------------------------------------------
# at-rest scrubbing: checkpoints, pserver snapshots, deploy bundles
# ---------------------------------------------------------------------------

SCRUB_STATE = "scrub.json"

#: artifact archives the scrub re-hashes (zip-layer member CRCs): deploy
#: bundles, AOT artifacts, and plain zips — ONE list for both the
#: direct-file and tree-walk paths, so they can never disagree
_BUNDLE_EXTS = (".ptz", ".aotz", ".zip")


@dataclass
class ScrubFinding:
    path: str
    kind: str            # 'checkpoint' | 'snapshot' | 'bundle'
    reason: str
    member: str = ""
    quarantined: bool = False
    already_quarantined: bool = False

    def describe(self) -> str:
        tag = " [quarantined]" if (self.quarantined
                                   or self.already_quarantined) else ""
        return f"{self.kind} {self.path}: {self.reason}{tag}"


@dataclass
class ScrubReport:
    checked: int = 0
    findings: List[ScrubFinding] = field(default_factory=list)
    #: per checkpoint ROOT (the dir holding pass-%05d children): the
    #: newest pass whose every member re-verified this scrub
    latest_verified: Dict[str, int] = field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return not self.findings

    def corrupt_members(self) -> List[str]:
        return [f"{f.path}" + (f":{f.member}" if f.member else "")
                for f in self.findings]


def _verify_bundle(path: str) -> Optional[Tuple[str, str]]:
    """Re-hash a ``.ptz``/zip artifact at rest: the zip layer stores a
    CRC-32 per member which ``testzip`` re-verifies over the full
    payload.  Returns ``(member, reason)`` or None when clean."""
    import zipfile

    try:
        with zipfile.ZipFile(path) as z:
            bad = z.testzip()
            if bad is not None:
                return bad, f"member {bad!r} failed its CRC"
    except zipfile.BadZipFile as e:
        return "", f"not a readable zip: {e}"
    except OSError as e:
        return "", f"unreadable: {e}"
    return None


def _journal_scrub_fail(finding: ScrubFinding) -> None:
    from paddle_tpu.obs import journal_event

    # fsync'd: a scrub failure is a durable anchor a postmortem orders
    # resume decisions against (WHEN did the checkpoint go bad, not just
    # that resume landed earlier)
    journal_event("scrub_fail", fsync=True, artifact=finding.kind,
                  dir=finding.path, member=finding.member,
                  reason=finding.reason,
                  quarantined=finding.quarantined)


def scrub_paths(paths: Sequence[str], *, quarantine: bool = False,
                registry=None) -> ScrubReport:
    """Re-verify every checkpoint chain, pserver snapshot chain, and
    deploy bundle under ``paths``.

    With ``quarantine``, a newly-corrupt checkpoint/snapshot dir gets the
    ``QUARANTINED`` marker (``validate_checkpoint`` then refuses it, so
    it drops out of ``latest_pass`` eligibility without destroying the
    forensic evidence a rename/delete would), the failure is journaled
    as a fsync'd ``scrub_fail`` anchor, and each checkpoint root's
    ``scrub.json`` records the newest fully-verified pass.  Bundles are
    reported (and journaled) but never renamed — serving paths point at
    them by name."""
    from paddle_tpu.resilience.checkpoint_io import (
        _PASS_RE, QUARANTINE_MARKER, failing_member,
        quarantine_checkpoint, validate_checkpoint)
    from paddle_tpu.pserver.snapshot import (_SNAP_RE, quarantine_snapshot,
                                             validate_snapshot)

    report = ScrubReport()
    ckpt_roots: Dict[str, List[Tuple[int, Optional[ScrubFinding]]]] = {}

    def _one(kind: str, d: str, validate, quarantine_fn) -> Optional[ScrubFinding]:
        report.checked += 1
        already = os.path.exists(os.path.join(d, QUARANTINE_MARKER))
        reason = validate(d)
        if reason is None:
            return None
        f = ScrubFinding(path=d, kind=kind, reason=reason,
                         member=failing_member(reason),
                         already_quarantined=already)
        if quarantine and not already:
            quarantine_fn(d, reason)
            f.quarantined = True
        if not already:  # re-journaling a known-bad dir every pass is spam
            _journal_scrub_fail(f)
        report.findings.append(f)
        return f

    for root in paths:
        root = os.path.abspath(root)
        if os.path.isfile(root):
            if root.endswith(_BUNDLE_EXTS):
                report.checked += 1
                bad = _verify_bundle(root)
                if bad is not None:
                    f = ScrubFinding(path=root, kind="bundle",
                                     reason=bad[1], member=bad[0])
                    _journal_scrub_fail(f)
                    report.findings.append(f)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            base = os.path.basename(dirpath)
            if _PASS_RE.fullmatch(base):
                dirnames[:] = []
                f = _one("checkpoint", dirpath, validate_checkpoint,
                         quarantine_checkpoint)
                parent = os.path.dirname(dirpath)
                ckpt_roots.setdefault(parent, []).append(
                    (int(_PASS_RE.fullmatch(base).group(1)), f))
                continue
            if _SNAP_RE.fullmatch(base):
                dirnames[:] = []
                _one("snapshot", dirpath, validate_snapshot,
                     quarantine_snapshot)
                continue
            dirnames[:] = [n for n in dirnames if not n.startswith(".")]
            for name in filenames:
                if name.endswith(_BUNDLE_EXTS):
                    report.checked += 1
                    p = os.path.join(dirpath, name)
                    bad = _verify_bundle(p)
                    if bad is not None:
                        f = ScrubFinding(path=p, kind="bundle",
                                         reason=bad[1], member=bad[0])
                        _journal_scrub_fail(f)
                        report.findings.append(f)

    for parent, entries in ckpt_roots.items():
        ok = [pid for pid, f in entries if f is None]
        tip = max(ok) if ok else -1
        report.latest_verified[parent] = tip
        if quarantine:
            _write_scrub_state(parent, tip, entries)
    if registry is not None:
        registry.counter("scrub_runs_total", "scrub passes completed").inc()
        if report.findings:
            registry.counter(
                "scrub_fail_total",
                "artifacts that failed an at-rest scrub").inc(
                len(report.findings))
    return report


def _write_scrub_state(root: str, tip: int, entries) -> None:
    """Atomically record the scrub outcome next to the pass dirs: the
    newest fully-verified pass is rollback's trusted target."""
    import uuid

    state = {
        "time": time.time(),
        "latest_verified_pass": tip,
        "passes": {str(pid): (f.reason if f is not None else "ok")
                   for pid, f in sorted(entries)},
    }
    path = os.path.join(root, SCRUB_STATE)
    tmp = f"{path}.tmp-{uuid.uuid4().hex[:8]}"
    try:
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, path)
    except OSError as e:
        logger.warning("scrub state not recorded under %s: %s", root, e)


def latest_verified_pass(save_dir: str) -> int:
    """The newest pass the scrubber fully re-verified (``scrub.json``),
    falling back to a validating ``latest_pass`` walk when no scrub has
    run — rollback's trusted-target resolver."""
    from paddle_tpu.resilience.checkpoint_io import latest_pass

    try:
        with open(os.path.join(save_dir, SCRUB_STATE)) as f:
            tip = int(json.load(f).get("latest_verified_pass", -1))
    except (FileNotFoundError, json.JSONDecodeError, OSError, ValueError):
        return latest_pass(save_dir)
    if tip < 0:
        return latest_pass(save_dir)
    # trust but re-verify: the dir may have rotted (or been pruned) since
    # the scrub pass that blessed it
    from paddle_tpu.resilience.checkpoint_io import (pass_dir,
                                                     validate_checkpoint)

    if validate_checkpoint(pass_dir(save_dir, tip)) is None:
        return tip
    return latest_pass(save_dir)


class ScrubDaemon:
    """Background checkpoint scrubber (``--scrub_every_s``, rank 0).

    A daemon thread re-verifies everything under its roots every
    ``every_s`` seconds with quarantine enabled.  Scrubbing only touches
    published, immutable artifacts (temp dirs are dot-prefixed and
    skipped), so it never races an in-flight save."""

    def __init__(self, roots, *, every_s: float) -> None:
        self.roots = [roots] if isinstance(roots, str) else list(roots)
        self.every_s = float(every_s)
        self.scrubs = 0
        self.corrupt_found = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="sdc-scrubber", daemon=True)

    def start(self) -> "ScrubDaemon":
        self._thread.start()
        return self

    def _run(self) -> None:
        from paddle_tpu.obs import get_registry

        while not self._stop.wait(self.every_s):
            try:
                report = scrub_paths(self.roots, quarantine=True,
                                     registry=get_registry())
            except Exception as e:  # noqa: BLE001 — scrubbing never kills training
                logger.warning("checkpoint scrub failed: %s", e)
                continue
            self.scrubs += 1
            self.corrupt_found += len(report.findings)
            for f in report.findings:
                if not f.already_quarantined:
                    logger.error("scrub: %s", f.describe())

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# lint gate: --sdc_check_every=0 must be equation-identical to today's
# step, and the fingerprint itself must audit host-transfer-free
# ---------------------------------------------------------------------------


def _tiny_trainer():
    import numpy as _np

    import paddle_tpu.nn as nn
    from paddle_tpu.param.optimizers import Adam
    from paddle_tpu.trainer import SGDTrainer

    nn.reset_naming()
    x = nn.data("sdc_audit_x", size=8)
    y = nn.data("sdc_audit_y", size=2)
    cost = nn.mse_cost(input=nn.fc(x, 2, act="relu", name="sdc_audit_h"),
                       label=y)
    tr = SGDTrainer(cost, Adam(learning_rate=0.01), seed=0)
    rs = _np.random.RandomState(0)
    feed = {"sdc_audit_x": rs.randn(4, 8).astype(_np.float32),
            "sdc_audit_y": rs.randn(4, 2).astype(_np.float32)}
    return tr, feed


def audit_sdc_step():
    """``lint --sdc``: the SDC-firewall contract on the compiled step.

    1. with ``--sdc_check_every=0`` the traced step is equation-identical
       to a never-enabled build — the firewall off IS today's program;
    2. with the check on, the step (now carrying the in-jit fingerprint
       of params + slots) audits host-transfer-free and constant-bloat
       clean — the digest is computed on device and only its 8 bytes
       ever cross the link, at the caller's cadence;
    3. the enabled step really does differ (the fingerprint exists) —
       a refactor cannot silently turn the check into a no-op.
    """
    import re

    import jax

    from paddle_tpu.analysis.findings import Finding
    from paddle_tpu.utils.flags import FLAGS

    findings: List[Finding] = []
    keep = FLAGS.sdc_check_every

    def canon(jaxpr) -> str:
        # the printed jaxpr embeds function-object reprs (custom_jvp
        # thunks) whose ADDRESSES differ across otherwise-identical
        # builds — strip them so the diff compares equations only
        return re.sub(r" at 0x[0-9a-f]+", "", str(jaxpr))

    try:
        FLAGS.sdc_check_every = 0
        tr_off, feed = _tiny_trainer()
        rng = jax.random.PRNGKey(0)

        def jaxpr_of(tr):
            return jax.make_jaxpr(tr._step_fn)(
                tr.params, tr.state, tr.opt_state, {}, rng, feed)

        off_a = jaxpr_of(tr_off)

        FLAGS.sdc_check_every = 4
        tr_on, feed = _tiny_trainer()
        from paddle_tpu.analysis import audit_fn

        findings.extend(audit_fn(
            tr_on._step_fn, tr_on.params, tr_on.state, tr_on.opt_state,
            {}, rng, feed, label="sdc:train_step",
            checks=("host-transfer", "constant-bloat")))
        on = jaxpr_of(tr_on)

        FLAGS.sdc_check_every = 0
        tr_off2, feed = _tiny_trainer()
        off_b = jaxpr_of(tr_off2)

        if canon(off_a) != canon(off_b):
            findings.append(Finding(
                check="sdc-step-drift", severity="ERROR",
                where="sdc:train_step",
                message="the compiled step with --sdc_check_every=0 "
                        "DIFFERS across builds — the fingerprint must be "
                        "fully gated by the flag "
                        f"({len(off_a.jaxpr.eqns)} vs "
                        f"{len(off_b.jaxpr.eqns)} top-level eqns)"))
        if canon(on) == canon(off_a):
            findings.append(Finding(
                check="sdc-step-missing", severity="ERROR",
                where="sdc:train_step",
                message="--sdc_check_every>0 left the compiled step "
                        "UNCHANGED — the in-jit fingerprint is gone and "
                        "agreement checks would compare nothing"))
    except Exception as e:  # a step that fails to trace is itself a finding
        from paddle_tpu.analysis.findings import Finding as F

        findings.append(F(
            check="sdc-build", severity="ERROR", where="sdc:train_step",
            message=f"sdc audit failed to build/trace the step: "
                    f"{type(e).__name__}: {e}"))
    finally:
        FLAGS.sdc_check_every = keep
    return findings


# ---------------------------------------------------------------------------
# ``python -m paddle_tpu fsck`` — the operator surface of the scrubber
# ---------------------------------------------------------------------------


def run_fsck(argv: Optional[List[str]] = None) -> int:
    """CI-friendly integrity walk: exit 0 when everything re-verifies,
    exit 2 with every corrupt member NAMED otherwise (exit 1 is reserved
    for crashes, so a wrapper can tell 'corrupt' from 'broken')."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu fsck",
        description="Re-hash checkpoints, pserver snapshots, and deploy "
                    "bundles at rest (docs/resilience.md 'Silent "
                    "corruption')")
    p.add_argument("paths", nargs="+", metavar="DIR_OR_BUNDLE",
                   help="checkpoint root(s), snapshot root(s), .ptz "
                        "bundle(s), or any tree containing them")
    p.add_argument("--quarantine", action="store_true",
                   help="mark newly-corrupt checkpoint/snapshot dirs "
                        "QUARANTINED (demoted out of latest_pass "
                        "eligibility) and record scrub.json")
    p.add_argument("--format", choices=("text", "json"), default="text")
    try:
        ns = p.parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on a usage error — exit 2 here MEANS "corrupt
        # artifacts found", so a typo'd invocation must not read as a
        # corruption page; remap to 1 (crash/usage), keep 0 for --help
        return 0 if not e.code else 1

    report = scrub_paths(ns.paths, quarantine=ns.quarantine)
    if ns.format == "json":
        print(json.dumps({
            "checked": report.checked,
            "corrupt": report.corrupt_members(),
            "latest_verified": report.latest_verified,
            "findings": [{"path": f.path, "kind": f.kind,
                          "member": f.member, "reason": f.reason,
                          "quarantined": f.quarantined} for f in
                         report.findings],
        }, indent=1))
    else:
        for f in report.findings:
            print(f"CORRUPT {f.describe()}")
        for root, tip in sorted(report.latest_verified.items()):
            print(f"verified {root}: latest fully-verified pass = {tip}")
        print(f"fsck: {report.checked} artifact(s) checked, "
              f"{len(report.findings)} corrupt")
    return 0 if report.clean else 2
