"""Bad-step guard — skip non-finite optimizer updates inside the jitted step.

The mixed-precision-training discipline: one NaN/Inf loss or gradient must
not poison the parameters forever, so the finite checks run ON DEVICE
(``jnp.isfinite`` of the loss and of the gradient global-norm) and a
``lax.cond`` selects between the real optimizer update and an identity
step.  Nothing here crosses the host link — the trainer reads the skip
flag from the step's extras at the same cadence it already pulls the loss,
and ``analysis.audit_fn`` verifies the guarded step stays
host-transfer-free (tests/test_resilience.py gate).

The reference's analog was process-fatal FP traps
(``feenableexcept`` in TrainerMain.cpp) — correct for debugging, wrong for
a 10k-chip run where one flaky batch should cost one skipped step, not the
job.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = ["global_grad_norm", "guarded_update"]


def global_grad_norm(grads) -> jnp.ndarray:
    """L2 norm over every gradient leaf, accumulated in f32 (bf16 squares
    overflow at ~256; the norm must be trustworthy or the finite check is
    theater)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


def guarded_update(
    update_fn: Callable[[Any, Any, Any], Tuple[Any, Any]],
    *,
    loss,
    grads,
    params,
    opt_state,
    new_state,
    old_state,
) -> Tuple[Any, Any, Any, Dict[str, jnp.ndarray]]:
    """Apply ``update_fn(params, grads, opt_state)`` only when the step is
    finite; otherwise hold params, optimizer slots, AND layer state (a NaN
    forward also poisons BN running stats) unchanged.

    Returns ``(new_params, new_opt_state, selected_state, extras)`` where
    extras carries device scalars: ``grad_norm`` and ``bad_step`` (1 when
    the update was skipped).  Pure and jit/pjit-safe; both cond branches
    are traced, only one executes.
    """
    gnorm = global_grad_norm(grads)
    finite = jnp.isfinite(loss) & jnp.isfinite(gnorm)

    def _apply(op):
        p, g, o = op
        return update_fn(p, g, o)

    def _skip(op):
        p, _, o = op
        return p, o

    new_params, new_opt = jax.lax.cond(
        finite, _apply, _skip, (params, grads, opt_state))
    sel_state = jax.lax.cond(
        finite, lambda s: s[0], lambda s: s[1], (new_state, old_state))
    extras = {
        "grad_norm": gnorm,
        "bad_step": (~finite).astype(jnp.int32),
    }
    return new_params, new_opt, sel_state, extras
