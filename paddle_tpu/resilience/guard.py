"""Bad-step guard — skip non-finite optimizer updates inside the jitted step.

The mixed-precision-training discipline: one NaN/Inf loss or gradient must
not poison the parameters forever, so the finite checks run ON DEVICE
(``jnp.isfinite`` of the loss and of the gradient global-norm) and a
``lax.cond`` selects between the real optimizer update and an identity
step.  Nothing here crosses the host link — the trainer reads the skip
flag from the step's extras at the same cadence it already pulls the loss,
and ``analysis.audit_fn`` verifies the guarded step stays
host-transfer-free (tests/test_resilience.py gate).

The reference's analog was process-fatal FP traps
(``feenableexcept`` in TrainerMain.cpp) — correct for debugging, wrong for
a 10k-chip run where one flaky batch should cost one skipped step, not the
job.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = ["global_grad_norm", "guarded_update", "init_loss_scale",
           "scaled_guarded_update"]


def global_grad_norm(grads) -> jnp.ndarray:
    """L2 norm over every gradient leaf, accumulated in f32 (bf16 squares
    overflow at ~256; the norm must be trustworthy or the finite check is
    theater)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


def guarded_update(
    update_fn: Callable[[Any, Any, Any], Tuple[Any, Any]],
    *,
    loss,
    grads,
    params,
    opt_state,
    new_state,
    old_state,
) -> Tuple[Any, Any, Any, Dict[str, jnp.ndarray]]:
    """Apply ``update_fn(params, grads, opt_state)`` only when the step is
    finite; otherwise hold params, optimizer slots, AND layer state (a NaN
    forward also poisons BN running stats) unchanged.

    Returns ``(new_params, new_opt_state, selected_state, extras)`` where
    extras carries device scalars: ``grad_norm`` and ``bad_step`` (1 when
    the update was skipped).  Pure and jit/pjit-safe; both cond branches
    are traced, only one executes.
    """
    gnorm = global_grad_norm(grads)
    finite = jnp.isfinite(loss) & jnp.isfinite(gnorm)

    def _apply(op):
        p, g, o = op
        return update_fn(p, g, o)

    def _skip(op):
        p, _, o = op
        return p, o

    new_params, new_opt = jax.lax.cond(
        finite, _apply, _skip, (params, grads, opt_state))
    sel_state = jax.lax.cond(
        finite, lambda s: s[0], lambda s: s[1], (new_state, old_state))
    extras = {
        "grad_norm": gnorm,
        "bad_step": (~finite).astype(jnp.int32),
    }
    return new_params, new_opt, sel_state, extras


# ---------------------------------------------------------------------------
# dynamic loss scaling (--amp; docs/mixed_precision.md)
# ---------------------------------------------------------------------------


def init_loss_scale(scale: float, *,
                    growth_interval: int = 2000) -> Dict[str, Any]:
    """Fresh loss-scale state: the scale itself plus the consecutive-good-
    steps counter the growth schedule runs on.  Lives inside the trainer's
    ``opt_state['amp']`` so it is donated with the slots and rides
    checkpoints for free (a resumed ``--amp`` run continues the exact
    scale trajectory)."""
    del growth_interval  # static, read from flags at trace time
    return {"scale": jnp.asarray(float(scale), jnp.float32),
            "good_steps": jnp.zeros((), jnp.int32)}


def scaled_guarded_update(
    update_fn: Callable[[Any, Any, Any], Tuple[Any, Any]],
    *,
    loss,
    scaled_grads,
    amp_state: Dict[str, Any],
    params,
    opt_state,
    new_state,
    old_state,
    growth_interval: int,
    max_scale: float,
    min_scale: float = 1.0,
) -> Tuple[Any, Any, Any, Dict[str, Any], Dict[str, jnp.ndarray]]:
    """The bad-step guard with dynamic loss scaling folded in — the
    mixed-precision state machine (Micikevicius et al.):

    - ``scaled_grads`` are d(scale * loss)/dp.  A finite step unscales
      them (f32 multiply by 1/scale) and applies ``update_fn``; the
      good-steps counter advances and, every ``growth_interval``
      consecutive finite steps, the scale DOUBLES (capped at
      ``max_scale``) to track the widest representable gradient range.
    - an overflow (non-finite scaled-grad norm, or a non-finite loss)
      skips the update — params, slots, and layer state held, exactly the
      plain guard's skip — and HALVES the scale (floored at
      ``min_scale``), so the next step retries in range instead of the
      process aborting.

    ``extras['bad_step']`` stays the abort signal and fires only when the
    LOSS itself is non-finite (a poisoned batch — same abort pressure as
    the unscaled guard); a pure gradient overflow is a normal
    loss-scaling event (``extras['amp_overflow']``) and must NOT count
    toward ``max_bad_steps``: a too-high initial scale legitimately takes
    several halvings to find range.  Pure and jit/pjit-safe.
    """
    scale = amp_state["scale"]
    gnorm_s = global_grad_norm(scaled_grads)
    loss_finite = jnp.isfinite(loss)
    finite = jnp.isfinite(gnorm_s) & loss_finite
    # unscale in f32; inv=0 on overflow keeps the (discarded) skip-branch
    # operands NaN-free so XLA's speculative execution can't trap
    inv = jnp.where(finite, 1.0 / scale, 0.0)
    grads = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype),
        scaled_grads)

    def _apply(op):
        p, g, o = op
        return update_fn(p, g, o)

    def _skip(op):
        p, _, o = op
        return p, o

    new_params, new_opt = jax.lax.cond(
        finite, _apply, _skip, (params, grads, opt_state))
    sel_state = jax.lax.cond(
        finite, lambda s: s[0], lambda s: s[1], (new_state, old_state))

    good = jnp.where(finite, amp_state["good_steps"] + 1, 0)
    grow = (growth_interval > 0) & (good >= growth_interval)
    new_scale = jnp.where(
        finite,
        jnp.where(grow, jnp.minimum(scale * 2.0, max_scale), scale),
        jnp.maximum(scale * 0.5, min_scale))
    new_amp = {"scale": new_scale,
               "good_steps": jnp.where(grow, 0, good)}
    extras = {
        "grad_norm": jnp.where(finite, gnorm_s * inv, jnp.inf),
        "bad_step": (~loss_finite).astype(jnp.int32),
        "amp_overflow": (~finite).astype(jnp.int32),
        "loss_scale": new_scale,
    }
    return new_params, new_opt, sel_state, new_amp, extras
