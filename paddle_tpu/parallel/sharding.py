"""Sharding rules — the SPMD replacement for the reference's parallel tiers.

What the reference does with explicit machinery, this framework does with
sharding annotations compiled by XLA GSPMD (SURVEY.md §5.8):

- MultiGradientMachine per-GPU threads + ring grad scatter/gather
  (gserver/gradientmachines/MultiGradientMachine.h:44-94) -> batch sharded
  over the 'data' mesh axis; XLA inserts the gradient all-reduce over ICI.
- ParallelNeuralNetwork per-layer device pinning (ParallelNeuralNetwork.h:34)
  -> parameter PartitionSpecs over the 'model' axis (tensor parallelism —
  strictly more general than layer pinning).
- pserver block-sharded parameter store (pserver/ParameterServer2.h:147-166)
  -> parameters simply *live* sharded on the mesh; there is no separate
  parameter tier to talk to.

``ShardingRules`` maps param-name glob patterns to PartitionSpecs; apply to a
params pytree to get NamedShardings for device_put / jit in_shardings.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import as_mesh

__all__ = ["ShardingRules", "replicated", "batch_sharding", "shard_params", "P"]


def replicated(mesh) -> NamedSharding:
    return NamedSharding(as_mesh(mesh), P())


def batch_sharding(mesh, ndim: int, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over ``axis``; replicate the rest.
    ``mesh`` may be a ``Mesh`` or a ``parallel.MeshConfig``."""
    return NamedSharding(as_mesh(mesh), P(axis, *([None] * (ndim - 1))))


class ShardingRules:
    """Ordered (pattern, PartitionSpec) rules; first match wins.

    Patterns are fnmatch globs over parameter names, e.g.::

        rules = ShardingRules([
            ("*emb*",   P(None, "model")),   # embedding: shard feature dim
            ("*out_w",  P(None, "model")),   # readout: shard vocab dim
            ("*_wx",    P(None, "model")),   # input projections: column-wise
            ("*",       P()),                # everything else replicated
        ])
    """

    def __init__(self, rules: Sequence[Tuple[str, P]]):
        self.rules = list(rules)

    def spec_for(self, name: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if fnmatch.fnmatch(name, pat):
                if len(spec) > ndim:
                    return P(*spec[:ndim])
                return spec
        return P()

    def shardings(self, mesh, params: Dict[str, Any]) -> Dict[str, NamedSharding]:
        mesh = as_mesh(mesh)
        out = {}
        for name, p in params.items():
            ndim = getattr(p, "ndim", 0)
            out[name] = NamedSharding(mesh, self.spec_for(name, ndim))
        return out


def shard_params(mesh, params: Dict[str, Any],
                 rules: Optional[ShardingRules] = None) -> Dict[str, Any]:
    """device_put every param to its (rule-derived or replicated) sharding.
    ``mesh`` may be a ``Mesh`` or a ``parallel.MeshConfig``."""
    mesh = as_mesh(mesh)
    if rules is None:
        repl = replicated(mesh)
        return {k: jax.device_put(v, repl) for k, v in params.items()}
    sh = rules.shardings(mesh, params)
    return {k: jax.device_put(v, sh[k]) for k, v in params.items()}
