"""High-level parallel training step builder.

Replaces the reference's updater/machine selection matrix (local vs remote vs
sparse-remote updaters, TrainerInternal.cpp:217-292; MultiGradientMachine) with
one function: give it a loss function (or Topology), a mesh, and sharding
rules — get back a compiled SPMD train step.  Collectives are chosen by XLA
GSPMD from the shardings; there is no separate communication code path to
maintain.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import as_mesh
from paddle_tpu.parallel.sharding import ShardingRules, batch_sharding, replicated
from paddle_tpu.param.optimizers import Optimizer

__all__ = ["make_parallel_train_step", "shard_batch", "agreement_spec"]


def agreement_spec(mesh, axis: Optional[str] = None):
    """Resolve the mesh + axis the cross-replica agreement collective
    (resilience/integrity.py) runs over: ``(built_mesh, axis_name,
    replica_count)``.

    ``mesh`` may be a ``Mesh`` or a ``parallel.MeshConfig``; ``axis``
    defaults to the config's DATA-role axis (the replica axis of
    data-parallel training — the one whose members are bit-identical by
    construction and therefore comparable).  A missing or size-1 axis is
    a config error: agreement over one replica compares nothing."""
    from paddle_tpu.parallel.mesh import MeshConfig
    from paddle_tpu.utils.error import ConfigError

    if isinstance(mesh, MeshConfig):
        name = axis or mesh.role_axis("data")
        built = mesh.build()
    else:
        built = mesh
        name = axis or "data"
    if name not in built.axis_names:
        raise ConfigError(
            f"agreement axis {name!r} not in mesh axes "
            f"{tuple(built.axis_names)}")
    n = int(built.shape[name])
    if n < 2:
        raise ConfigError(
            f"agreement over axis {name!r} needs >=2 replicas, mesh has "
            f"{n} — nothing to compare")
    return built, name, n


def shard_batch(mesh, feed: Dict[str, Any], axis: str = "data") -> Dict[str, Any]:
    """Place every array (or (value, lengths) tuple) batch-sharded on ``axis``.
    ``mesh`` may be a ``Mesh`` or a ``parallel.MeshConfig``."""
    mesh = as_mesh(mesh)

    def put(v):
        v = jnp.asarray(v)
        return jax.device_put(v, batch_sharding(mesh, v.ndim, axis))

    out: Dict[str, Any] = {}
    for k, v in feed.items():
        out[k] = tuple(put(x) for x in v) if isinstance(v, tuple) else put(v)
    return out


def make_parallel_train_step(
    loss_fn: Callable[[Dict[str, Any], Dict[str, Any]], jax.Array],
    optimizer: Optimizer,
    mesh,
    *,
    rules: Optional[ShardingRules] = None,
    donate: bool = True,
) -> Callable:
    """Build ``step(params, opt_state, batch) -> (loss, params, opt_state)``
    compiled SPMD over ``mesh`` (a ``Mesh`` or a ``parallel.MeshConfig``).

    ``loss_fn(params, batch) -> scalar`` must be pure. Params should be placed
    with ``shard_params(mesh, params, rules)`` and the batch with
    ``shard_batch`` — jit then infers all collectives (grad all-reduce over
    'data', activation collectives over 'model') from the operand shardings.

    A ``MeshConfig`` that binds a ``dcn_axis`` (``--dcn_axis``) routes the
    pure data-parallel case (``rules is None``) through the two-level
    ICI-reduce-scatter / DCN-allreduce / ICI-allgather schedule
    (``parallel/hierarchical.py``) — same signature, same sum (bit-equal
    to flat on a single pod).  The bf16-compressed DCN variant changes
    the signature (it threads error-feedback residuals), so it is only
    available via ``make_hierarchical_train_step`` directly.
    """
    from paddle_tpu.parallel.mesh import MeshConfig

    if (rules is None and isinstance(mesh, MeshConfig) and mesh.dcn_axis
            and mesh.dcn_axis in mesh.shape):
        from paddle_tpu.parallel.hierarchical import \
            make_hierarchical_train_step

        return make_hierarchical_train_step(loss_fn, optimizer, mesh,
                                            compress=False, donate=donate)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        # fused multi-tensor apply only without tensor-parallel rules:
        # concatenating differently-sharded leaves mispartitions under
        # GSPMD (see Optimizer.update's caller contract)
        new_params, new_opt = optimizer.update(params, grads, opt_state,
                                               fused=rules is None)
        return loss, new_params, new_opt

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)
