"""Pipeline parallelism over a 'stage' mesh axis — GPipe as one SPMD program.

The reference's closest machinery is the overlapped send/recv parameter
pipeline of RemoteParameterUpdater (paddle/trainer/RemoteParameterUpdater.h:
163-179) and the per-layer device placement of ParallelNeuralNetwork;
SURVEY.md §2 directs this framework to add modern pipeline parallelism as an
idiomatic jax.sharding feature instead.  The TPU-first design:

- Stage weights are STACKED on a leading [S, ...] axis and sharded over the
  ``stage`` mesh axis — every device holds exactly its stage's slice.
- All stages run ONE program under ``jax.shard_map``; activations hop to the
  next stage with ``lax.ppermute`` (ICI neighbor traffic, no host involvement).
- The GPipe fill/drain schedule is a ``lax.scan`` over ``S + M - 1`` ticks
  for M microbatches; stage 0 ingests microbatch t at tick t, the last stage
  emits microbatch t at tick t + S - 1.
- The whole loop is differentiable (ppermute transposes to the reverse
  permute, scan to the reverse scan), so ``jax.grad`` derives the backward
  pipeline schedule automatically — there is no hand-written backward pass,
  and cotangents for the stage-stacked weights arrive correctly reduced over
  any unmentioned data axis (shard_map inserts the psum from the in_specs).
- Composes with a ``data`` axis for dp x pp: microbatches carry their batch
  dim sharded over ``data`` while weights shard over ``stage``.

Constraints (by construction of the single-program schedule): all stages
share one ``stage_fn`` with equal input/output activation STRUCTURE (the
canonical homogeneous-block pipeline — transformer blocks, residual MLPs,
stacked RNN cells), and the microbatch count must divide the batch.
Activations may be arbitrary pytrees (every leaf with a leading batch dim)
— a sequence stage passes (value, mask, lengths) through the ppermute hops
as one tree.  The fill/drain ticks additionally require stage_fn's VJP to
be finite on a real microbatch (the carry is seeded with microbatch 0, not
zeros — see _gpipe_local).  ``parallel/pipeline_dsl.py`` drives this from
the ``nn`` DSL: ``device_pin`` stage tags partition a Topology into
head -> homogeneous stages -> tail.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import compat
from paddle_tpu.parallel.mesh import as_mesh
from paddle_tpu.param.optimizers import Optimizer

__all__ = ["stack_stage_params", "shard_stage_params", "pipeline_apply",
           "make_pipeline_train_step"]


def stack_stage_params(per_stage: Sequence[Any]):
    """[stage0_params, stage1_params, ...] (identical pytree structure) ->
    one pytree with leading stage dim S on every leaf."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)


def shard_stage_params(mesh, stacked, *, stage_axis: str = "stage"):
    """Place a stage-stacked pytree with leading dim sharded over the stage
    mesh axis (each device holds its own stage's weights).  ``mesh`` may be
    a ``Mesh`` or a ``parallel.MeshConfig``."""
    sharding = NamedSharding(as_mesh(mesh), P(stage_axis))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), stacked)


def _gpipe_local(stage_fn, w_stacked_local, x_mb, *, axis: str):
    """shard_map body: run the fill/drain schedule on this device's stage.

    ``w_stacked_local``: stage-stacked weights AFTER sharding — leading dim 1
    (this stage's slice).  ``x_mb``: pytree of [M, mb, ...] microbatch
    leaves (every stage receives them; only stage 0 reads them).  Returns
    the same tree with [M, mb, ...] outputs, psum-replicated over the
    stage axis."""
    tmap = jax.tree_util.tree_map
    S = compat.axis_size(axis)
    sid = lax.axis_index(axis)
    w_local = tmap(lambda a: a[0], w_stacked_local)
    M = jax.tree_util.tree_leaves(x_mb)[0].shape[0]
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(prev, t):
        # stage 0 ingests microbatch t (clamped: ticks >= M feed a dummy
        # whose products drain past the last stage unrecorded); later
        # stages consume what ppermute delivered last tick
        i = jnp.clip(t, 0, M - 1)
        x_in = tmap(lambda full, p: jnp.where(sid == 0, full[i], p),
                    x_mb, prev)
        y = stage_fn(w_local, x_in)
        return tmap(lambda a: lax.ppermute(a, axis, perm), y), y

    # seed the carry with a REAL microbatch, not zeros: fill/drain ticks run
    # stage_fn (and, under grad, its VJP) on the carry with their output
    # cotangents masked to zero — but a derivative singular at 0 (sqrt,
    # x/||x||) makes inf intermediates and inf*0 = NaN would leak into the
    # weight grads accumulated over all ticks (ADVICE r4)
    _, ys = lax.scan(tick, tmap(lambda a: a[0], x_mb), jnp.arange(M + S - 1))
    # the last stage produced microbatch j at tick j + S - 1; replicate its
    # outputs across the stage axis (mask + psum — everyone else holds
    # intermediate activations, zeroed out here)
    return tmap(
        lambda a: lax.psum(
            jnp.where(sid == S - 1, a[S - 1:], jnp.zeros_like(a[S - 1:])),
            axis),
        ys)


def pipeline_apply(stage_fn: Callable[[Any, Any], Any],
                   stacked_params, x: Any, *, mesh,
                   n_microbatches: int, stage_axis: str = "stage",
                   data_axis: Optional[str] = None) -> Any:
    """Run ``x`` (array or pytree whose leaves all lead with [B, ...])
    through the S-stage pipeline; returns the stage output tree with [B]
    leading each leaf.

    ``stage_fn(stage_params, x_mb) -> y_mb`` is one stage's forward on a
    microbatch (equal in/out STRUCTURE across stages).  ``stacked_params``
    leaves carry the leading [S] stage dim (see ``stack_stage_params``).
    With ``data_axis`` the microbatch batch dim additionally shards over
    that mesh axis (dp x pp).  Fully differentiable — wrap in jax.grad for
    training."""
    mesh = as_mesh(mesh)
    tmap = jax.tree_util.tree_map
    x_leaves = jax.tree_util.tree_leaves(x)
    B = x_leaves[0].shape[0]
    M = n_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by n_microbatches {M}")
    for leaf in x_leaves:
        if leaf.shape[0] != B:
            raise ValueError(
                f"every activation leaf must lead with the batch dim {B}; "
                f"got shape {leaf.shape}")
    S = mesh.shape[stage_axis]
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if leaves and leaves[0].shape[0] != S:
        # _gpipe_local reads slice [0] of each device's shard — a mismatch
        # would silently run a SUBSET of the stages
        raise ValueError(
            f"stacked_params carry {leaves[0].shape[0]} stages but mesh axis "
            f"{stage_axis!r} has {S} devices; they must be equal")
    x_mb = tmap(lambda a: a.reshape(M, B // M, *a.shape[1:]), x)
    mb_spec = P(None, data_axis) if data_axis else P()
    fn = functools.partial(_gpipe_local, stage_fn, axis=stage_axis)
    mapped = compat.shard_map(
        fn, mesh=mesh,
        in_specs=(P(stage_axis), mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )
    y_mb = mapped(stacked_params, x_mb)
    return tmap(lambda a: a.reshape(B, *a.shape[2:]), y_mb)


def make_pipeline_train_step(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, Any], jax.Array],
    optimizer: Optimizer,
    mesh,
    *,
    n_microbatches: int,
    stage_axis: str = "stage",
    data_axis: Optional[str] = None,
    donate: bool = True,
) -> Callable:
    """``step(stacked_params, opt_state, x, labels) -> (loss, params, opt)``
    jitted dp x pp: pipeline forward, autodiff backward schedule, optimizer
    update on the stage-sharded stacks.  ``loss_fn(y [B, ...], labels) ->
    scalar`` runs on the pipeline output (replicated over stage, sharded
    over data — GSPMD inserts the data-axis mean reduction)."""
    mesh = as_mesh(mesh)

    def step(stacked_params, opt_state, x, labels):
        def objective(w):
            y = pipeline_apply(stage_fn, w, x, mesh=mesh,
                               n_microbatches=n_microbatches,
                               stage_axis=stage_axis, data_axis=data_axis)
            return loss_fn(y, labels)

        loss, grads = jax.value_and_grad(objective)(stacked_params)
        # stage-stacked params are sharded over the stage axis: never fuse
        # (Optimizer.update's caller contract — concat of sharded leaves
        # mispartitions under GSPMD)
        new_params, new_opt = optimizer.update(stacked_params, grads,
                                               opt_state, fused=False)
        return loss, new_params, new_opt

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)
