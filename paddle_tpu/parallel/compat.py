"""jax-version compatibility for the SPMD layer.

The parallel tier is written against the modern ``jax.shard_map`` surface
(``check_vma=``, ``lax.axis_size``); the installed runtime may predate it
(0.4.x ships ``jax.experimental.shard_map.shard_map`` with ``check_rep=``
and no ``lax.axis_size``).  One shim, same policy as the
``_compiler_params`` rename shim in ops/pallas_kernels.py: resolve the
rename ONCE here so every shard_map call site stays written against the
current API.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

__all__ = ["shard_map", "axis_size"]


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions.

    ``check_vma`` (the modern name for replication checking) maps to the
    legacy ``check_rep``; both default off here — the parallel bodies use
    manual collectives whose replication the checker cannot prove.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)


def axis_size(name: str) -> Any:
    """Static size of a named mesh axis from inside a shard_map body.

    Legacy jax has no ``lax.axis_size``; ``lax.psum(1, name)`` of the
    python constant 1 constant-folds to the same static int there, so the
    result remains usable in shapes and fori_loop bounds.
    """
    if hasattr(lax, "axis_size"):
        return lax.axis_size(name)
    return lax.psum(1, name)
