"""Sharded embedding tables — the pserver sparse-row analog.

Reference: huge embedding tables live row-sharded on pservers; trainers
prefetch only the rows a batch touches and push sparse row gradients back
(paddle/math/SparseRowMatrix.h, trainer/RemoteParameterUpdater.h:265
SparseRemoteParameterUpdater, MultiGradientMachine.h:99-166).

TPU-native: the table is sharded across the 'model' mesh axis along the
*vocab* dimension.  Lookup runs under shard_map: each device gathers the ids
that fall in its shard (others contribute zeros) and a ``psum`` combines —
one collective instead of a parameter-server round trip.  The backward pass
(scatter-add into the local shard) is derived by autodiff through the same
program, so gradients stay sharded — the row-sparse push analog.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["sharded_embedding_lookup", "shard_table"]


def shard_table(mesh: Mesh, table, axis: str = "model"):
    """Place a [V, D] table row-sharded over ``axis`` (V must divide evenly)."""
    return jax.device_put(table, NamedSharding(mesh, P(axis, None)))


def _local_lookup(table_shard, ids, *, axis_name: str):
    """shard_map body: gather local rows, zero others, psum across shards."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    vshard = table_shard.shape[0]
    lo = idx * vshard
    local = ids - lo
    in_range = (local >= 0) & (local < vshard)
    safe = jnp.clip(local, 0, vshard - 1)
    rows = jnp.take(table_shard, safe, axis=0)
    rows = rows * in_range[..., None].astype(rows.dtype)
    return lax.psum(rows, axis_name)


def sharded_embedding_lookup(mesh: Mesh, table, ids, *, axis: str = "model"):
    """table: [V, D] sharded P(axis, None); ids: replicated int array.
    Returns replicated [ids.shape..., D] embeddings."""
    fn = functools.partial(_local_lookup, axis_name=axis)
    mapped = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )
    return mapped(table, ids)
