"""Sharded embedding tables — the pserver sparse-row analog (compat shim).

Reference: huge embedding tables live row-sharded on pservers; trainers
prefetch only the rows a batch touches and push sparse row gradients back
(paddle/math/SparseRowMatrix.h, trainer/RemoteParameterUpdater.h:265
SparseRemoteParameterUpdater, MultiGradientMachine.h:99-166).

This module is now a thin compatibility surface over the full pserver tier
(``paddle_tpu/pserver``): ``sharded_embedding_lookup`` delegates to the
all-to-all exchange (``pserver.lookup.all_to_all_lookup``) — ids bucketed
by owning shard, fixed-capacity all-to-all, local gather, payloads
returned to the requesting rows — which replaces the previous
psum-of-zeros broadcast that did O(shards) redundant gather work and
reduced a replicated [N, D] output.  The signature, autodiff contract
(gradients are row-sparse scatter-adds into the sharded table), and the
``shard_table`` placement helper are unchanged for existing callers.

``shard_table`` additionally honors the documented precondition instead of
failing inside ``device_put``: a vocab that does not divide the mesh axis
is padded up to a shard multiple with masked (zero) tail rows — or, with
``pad=False``, raises a typed ``ConfigError`` naming the table.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["sharded_embedding_lookup", "shard_table"]


def shard_table(mesh, table, axis: str = "model", *,
                pad: bool = True, name: str = "table"):
    """Place a [V, D] table row-sharded over ``axis``.  ``mesh`` may be a
    ``Mesh`` or a ``parallel.MeshConfig``.

    V not dividing the axis size is padded up to a shard multiple with
    zero tail rows (they can never be looked up: ids are < V) — or raises
    a typed ``ConfigError`` naming the table when ``pad=False``."""
    from paddle_tpu.parallel.mesh import as_mesh
    from paddle_tpu.pserver.table import pad_vocab

    mesh = as_mesh(mesh)
    table = jnp.asarray(table)
    n = int(mesh.shape[axis])
    v = table.shape[0]
    v_pad = pad_vocab(v, n, pad=pad, name=name)
    if v_pad != v:
        table = jnp.concatenate(
            [table, jnp.zeros((v_pad - v,) + table.shape[1:], table.dtype)])
    return jax.device_put(table, NamedSharding(mesh, P(axis, None)))


def sharded_embedding_lookup(mesh, table, ids, *, axis: str = "model"):
    """table: [V_pad, D] sharded P(axis, None); ids: replicated int array.
    Returns [ids.shape..., D] embeddings via the balanced all-to-all
    exchange (see paddle_tpu/pserver/lookup.py).  Differentiable: the
    table cotangent is the row-sparse scatter-add, kept sharded."""
    from paddle_tpu.parallel.mesh import as_mesh
    from paddle_tpu.pserver.lookup import all_to_all_lookup

    return all_to_all_lookup(as_mesh(mesh), table, ids, axis=axis)
