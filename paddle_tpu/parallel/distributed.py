"""Multi-host (multi-slice) initialization and failure model.

Reference equivalents: the pserver/trainer gflags topology (--pservers,
--trainer_id, --num_gradient_servers, paddle/utils/Flags.cpp) and the fabric
cluster launcher (paddle/scripts/cluster_train/paddle.py:101-175).  On TPU the
launcher is the TPU runtime itself: every host runs the same program,
``jax.distributed.initialize`` wires the DCN control plane, and
``jax.devices()`` becomes the global chip list.

Failure model: recovery is AUTOMATIC — the gang supervisor
(``paddle_tpu.resilience.cluster.GangSupervisor``; docs/resilience.md
"Multi-host recovery") detects rank death and heartbeat stalls and heals
by elastic shrink/grow (whole-gang relaunch is the fallback); the
relaunched ranks call ``shutdown_distributed``-fresh
``initialize_distributed`` and resume from the newest gang-consistent
checkpoint via ``--resume=auto`` (rank-0 publish + all-ranks barrier).
With ``--dcn_axis`` bound the POD (one ICI domain) is the failure unit:
the world shrinks/grows by whole pods, gradient reduction goes
hierarchical (``parallel/hierarchical.py``), and cross-pod exchanges ride
the partition-tolerant DCN transport (``resilience/dcn.py``).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

from paddle_tpu.utils import FLAGS, logger

__all__ = ["initialize_distributed", "shutdown_distributed", "global_mesh",
           "is_multi_host", "resume_pass"]

_initialized = False
_live = False          # True only when jax.distributed.initialize ran


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Idempotent jax.distributed.initialize wrapper. No-ops single-host.

    Env-driven on TPU pods (the runtime sets everything); explicit args are
    for CPU multi-process tests.  ``shutdown_distributed`` resets the
    latch for supervised re-entry and multi-scenario tests.
    """
    global _initialized, _live
    if _initialized:
        return
    import jax

    # wiring injected by parallel/launcher.py (the cluster-launcher analog of
    # the reference's --pservers/--trainer_id flags)
    coordinator_address = (coordinator_address
                           or os.environ.get("PADDLE_TPU_COORDINATOR"))
    if num_processes is None and os.environ.get("PADDLE_TPU_NUM_PROCESSES"):
        num_processes = int(os.environ["PADDLE_TPU_NUM_PROCESSES"])
    if process_id is None and os.environ.get("PADDLE_TPU_PROCESS_ID"):
        process_id = int(os.environ["PADDLE_TPU_PROCESS_ID"])
    if coordinator_address is None and not os.environ.get("JAX_COORDINATOR_ADDRESS"):
        # single-host: nothing to do
        _initialized = True
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    _live = True
    logger.info(
        "distributed init: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def shutdown_distributed() -> None:
    """Tear down the DCN control plane and reset the init latch.

    The module-global latch otherwise makes ``initialize_distributed`` a
    one-shot per process; supervised re-entry (a gang rank reused across
    scenarios) and multi-scenario tests need the way back.  Safe to call
    when nothing was initialized — only a LIVE ``jax.distributed`` client
    (one this module actually started) is shut down."""
    global _initialized, _live
    if _live:
        import jax

        try:
            jax.distributed.shutdown()
        except RuntimeError as e:  # already torn down elsewhere
            logger.warning("jax.distributed.shutdown: %s", e)
    _live = False
    _initialized = False


def is_multi_host() -> bool:
    import jax

    return jax.process_count() > 1


def global_mesh(shape: Optional[Sequence[int]] = None,
                axis_names: Optional[Sequence[str]] = None,
                config=None):
    """Mesh over ALL processes' devices. For pods, prefer putting the
    DCN-crossing axis ('data') first: intra-slice axes ride ICI, the
    slice-crossing axis rides DCN (scaling-book recipe).

    ``config`` (a :class:`paddle_tpu.parallel.MeshConfig`) is the
    preferred spelling — one object describes the whole world and elastic
    resize is ``config.fit_world(n).build()``; ``shape``/``axis_names``
    remain as the legacy positional form (they build an ad-hoc config
    from flags)."""
    initialize_distributed()
    if config is not None:
        return config.build()
    if shape is None and axis_names is None:
        from paddle_tpu.parallel.mesh import MeshConfig

        return MeshConfig.from_flags().build()
    from paddle_tpu.utils.devices import make_mesh

    return make_mesh(shape, axis_names)


def resume_pass(save_dir: str) -> int:
    """Pass id to resume from after restart (checkpoint-restart recovery)."""
    from paddle_tpu.trainer.checkpoint import latest_pass

    last = latest_pass(save_dir)
    return last + 1 if last >= 0 else 0
