"""Ring attention — sequence/context parallelism over a mesh axis.

The reference predates sequence parallelism entirely (SURVEY.md §5: its only
long-sequence machinery is padding-free batching + fused RNN kernels).  For a
first-class TPU framework long context is mandatory: this module implements
blockwise ring attention (Liu et al. 2023 style): Q/K/V are sharded along the
*sequence* dimension across a mesh axis; each device holds one Q block and the
K/V blocks rotate around the ring via ``ppermute`` while a numerically-stable
online-softmax accumulator folds in one block per step.  Peak memory per chip
is O(T/n) and the K/V transfers overlap compute around the ICI ring.

Layout: [B, H, T, D] with T sharded on ``axis``. Causal masking uses global
positions derived from the device's ring index.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import compat
from paddle_tpu.parallel.mesh import as_mesh

__all__ = ["ring_attention", "ring_attention_sharded"]


def _block_attn(q, k, v, mask_bias, m_prev, l_prev, acc_prev, scale):
    """Fold one K/V block into the online-softmax accumulator.

    q: [B,H,Tq,D], k/v: [B,H,Tk,D], mask_bias: [B?,1,Tq,Tk] additive (-inf to
    mask), accumulators: m [B,H,Tq,1], l [B,H,Tq,1], acc [B,H,Tq,D].
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    s = s + mask_bias
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (m_new = -inf): shift by 0 there
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe)
    corr = jnp.exp(jnp.where(jnp.isfinite(m_prev), m_prev - m_safe, -jnp.inf))
    l_new = corr * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = corr * acc_prev + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(p.dtype), preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Inside-shard_map ring attention. q/k/v local blocks [B,H,Tl,D];
    sequence is sharded over ``axis_name``. Returns local output block."""
    n = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    scale = scale if scale is not None else (1.0 / (D ** 0.5))
    qf = q.astype(jnp.float32)

    q_pos = idx * Tl + jnp.arange(Tl)  # global positions of local q rows

    m0 = jnp.full((B, H, Tl, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    a0 = jnp.zeros((B, H, Tl, D), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        k_blk, v_blk, m, l, acc = carry
        # k_blk originated on device (idx - t) mod n
        src = (idx - t) % n
        k_pos = src * Tl + jnp.arange(Tl)
        if causal:
            bias = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, -jnp.inf)
        else:
            bias = jnp.zeros((Tl, Tl), jnp.float32)
        bias = bias[None, None, :, :]
        m, l, acc = _block_attn(qf, k_blk.astype(jnp.float32),
                                v_blk.astype(jnp.float32), bias, m, l, acc, scale)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, l, acc), None

    (k_fin, v_fin, m, l, acc), _ = lax.scan(step, (k, v, m0, l0, a0), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, seq_axis: str = "seq",
                           causal: bool = False):
    """User entry: q/k/v global [B,H,T,D]; runs ring attention with T sharded
    over ``mesh`` axis ``seq_axis`` via shard_map.  ``mesh`` may be a
    ``Mesh`` or a ``parallel.MeshConfig``."""
    mesh = as_mesh(mesh)
    spec = P(None, None, seq_axis, None)

    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    mapped = compat.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
    )
    return mapped(q, k, v)
