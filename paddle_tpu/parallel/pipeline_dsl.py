"""Pipeline parallelism driven from the ``nn`` DSL — ``device_pin`` stage
tags partition a Topology into head -> homogeneous stages -> tail, and the
stages run as the GPipe SPMD program of ``parallel/pipeline.py``.

The reference's per-layer ``device`` attribute dispatches layers to device
threads inside ParallelNeuralNetwork (config_parser.py:1772-1848,
ParallelNeuralNetwork.h:34); here the same config surface — ``device_pin
(layer, "pp:<k>")`` — becomes a *pipeline* partitioning plane: tagged
layers form stage k of a GPipe schedule over a ``stage`` mesh axis, while
untagged layers before/after the pipelined region run replicated (head:
e.g. embeddings; tail: e.g. pooling + readout + cost).

Constraints (validated at construction, inherited from the single-program
GPipe schedule — parallel/pipeline.py):

- stages must be STRUCTURALLY IDENTICAL: same layer types, sizes and
  parameter shapes position-by-position (the canonical homogeneous stack —
  repeated LSTM/transformer blocks).  Flags invisible to the config (e.g.
  ``reverse=`` closures) must also match; only shapes/types are checkable,
  so an alternating-direction stack would silently use stage 0's direction
  — do not tag one.
- the activations crossing each stage boundary must match the head->stage0
  seam structure (same producing-layer positions, same shapes).
- no stateful layers (batch_norm) inside stages: stage state would need a
  per-stage reduction the schedule does not model.
- label/data layers feed the tail directly (they are not pipelined).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.nn.graph import (Act, ApplyContext, LayerOutput, ParamSpec,
                                 Topology, _coerce_feed)
from paddle_tpu.parallel.pipeline import pipeline_apply
from paddle_tpu.utils.error import ConfigError

__all__ = ["PipelinedTopology", "pp_stage"]


def pp_stage(node: LayerOutput, k: int) -> LayerOutput:
    """Tag ``node`` as belonging to pipeline stage ``k`` (sugar over
    ``device_pin(node, f"pp:{k}")``)."""
    node.meta["device"] = f"pp:{k}"
    return node


def _stage_of(layer: LayerOutput) -> Optional[int]:
    tag = layer.meta.get("device")
    if tag is None or not str(tag).startswith("pp:"):
        return None
    return int(str(tag).split(":", 1)[1])


class PipelinedTopology(Topology):
    """A Topology whose ``pp:<k>``-tagged layers execute as a GPipe
    pipeline over ``mesh[stage_axis]``.

    ``init`` returns the stage parameters STACKED on a leading [S] dim
    under stage-0 names (per-stage values keep their own random init);
    ``apply`` runs head -> pipeline_apply -> tail and is differentiable
    end-to-end, so ``SGDTrainer(cost, ..., mesh=mesh, pipeline=...)`` trains
    through it unchanged."""

    def __init__(self, outputs, *, mesh, n_microbatches: int,
                 stage_axis: str = "stage", data_axis: Optional[str] = None):
        from paddle_tpu.parallel.mesh import as_mesh

        super().__init__(outputs)
        self.mesh = mesh = as_mesh(mesh)
        self.n_microbatches = n_microbatches
        self.stage_axis = stage_axis
        self.data_axis = data_axis

        by_stage: Dict[int, List[LayerOutput]] = {}
        for l in self.layers:
            k = _stage_of(l)
            if k is not None:
                by_stage.setdefault(k, []).append(l)
        if not by_stage:
            raise ConfigError("PipelinedTopology: no pp:<k> tagged layers")
        K = len(by_stage)
        if sorted(by_stage) != list(range(K)):
            raise ConfigError(
                f"stage tags must be contiguous pp:0..pp:{K - 1}, got "
                f"{sorted(by_stage)}")
        if mesh.shape[stage_axis] != K:
            raise ConfigError(
                f"{K} stages but mesh axis {stage_axis!r} has "
                f"{mesh.shape[stage_axis]} devices")
        self.stage_layers: List[List[LayerOutput]] = [by_stage[k]
                                                     for k in range(K)]
        stage_set = {id(l) for ls in self.stage_layers for l in ls}

        # head = untagged layers none of whose ancestors are staged;
        # tail = untagged layers with a staged ancestor
        self.head_layers: List[LayerOutput] = []
        self.tail_layers: List[LayerOutput] = []
        downstream: set = set(stage_set)
        for l in self.layers:
            if id(l) in stage_set:
                continue
            if any(id(p) in downstream for p in l.parents):
                downstream.add(id(l))
                self.tail_layers.append(l)
            else:
                self.head_layers.append(l)

        self._validate_and_bind()

    # -- structure ------------------------------------------------------

    def _validate_and_bind(self) -> None:
        stage0 = self.stage_layers[0]
        pos0 = {id(l): i for i, l in enumerate(stage0)}
        for k, layers in enumerate(self.stage_layers[1:], start=1):
            if len(layers) != len(stage0):
                raise ConfigError(
                    f"stage {k} has {len(layers)} layers, stage 0 has "
                    f"{len(stage0)} — stages must be homogeneous")
            for a, b in zip(stage0, layers):
                if a.layer_type != b.layer_type or a.size != b.size:
                    raise ConfigError(
                        f"stage {k} layer {b.name!r} ({b.layer_type}/"
                        f"{b.size}) does not match stage 0's {a.name!r} "
                        f"({a.layer_type}/{a.size})")
                sa = [tuple(s.shape) for s in a.param_specs]
                sb = [tuple(s.shape) for s in b.param_specs]
                if sa != sb:
                    raise ConfigError(
                        f"stage {k} layer {b.name!r} param shapes {sb} != "
                        f"stage 0's {sa}")
                if any(s.is_state for s in a.param_specs):
                    raise ConfigError(
                        f"stateful layer {a.name!r} cannot be pipelined")

        # seam INTO stage 0: parents outside the stage, in first-use order
        def crossings(layers, inside_ids):
            seen, out = set(), []
            for i, l in enumerate(layers):
                for p in l.parents:
                    if id(p) not in inside_ids and id(p) not in seen:
                        seen.add(id(p))
                        out.append((i, p))
            return out

        ids0 = {id(l) for l in stage0}
        self.seam_in: List[Tuple[int, LayerOutput]] = crossings(stage0, ids0)
        # stage k>0 crossings must come from stage k-1 at consistent
        # positions; those positions define the seam OUT of every stage
        out_pos: Optional[List[int]] = None
        for k, layers in enumerate(self.stage_layers[1:], start=1):
            idsk = {id(l) for l in layers}
            cr = crossings(layers, idsk)
            if len(cr) != len(self.seam_in):
                raise ConfigError(
                    f"stage {k} has {len(cr)} boundary crossings, stage 0 "
                    f"has {len(self.seam_in)} — every stage must consume "
                    f"exactly the seam")
            prev_pos = {id(l): i for i, l in enumerate(self.stage_layers[k - 1])}
            pos = []
            for (i_use, p), (i_use0, _p0) in zip(cr, self.seam_in):
                if id(p) not in prev_pos:
                    raise ConfigError(
                        f"stage {k} consumes {p.name!r} which is not in "
                        f"stage {k - 1} — only neighbor-stage activations "
                        f"may cross a pipeline boundary")
                if i_use != i_use0:
                    raise ConfigError(
                        f"stage {k} seam use-position mismatch vs stage 0")
                pos.append(prev_pos[id(p)])
            if out_pos is None:
                out_pos = pos
            elif pos != out_pos:
                raise ConfigError("inconsistent seam positions across stages")
        last = self.stage_layers[-1]
        last_pos = {id(l): i for i, l in enumerate(last)}
        tail_pos = []  # last-stage positions the tail actually consumes
        seen = set()
        for l in self.tail_layers:
            for p in l.parents:
                if id(p) in last_pos and id(p) not in seen:
                    seen.add(id(p))
                    tail_pos.append(last_pos[id(p)])
        if out_pos is None:
            # single stage: the seam out of the pipeline is whatever the
            # tail consumes (there is no next stage to define it)
            out_pos = tail_pos
            if len(out_pos) != len(self.seam_in):
                raise ConfigError(
                    f"single-stage pipeline: tail consumes {len(out_pos)} "
                    f"stage activations but the seam in carries "
                    f"{len(self.seam_in)} — structures must match")
        self.seam_out_pos = out_pos

        # tail may consume only the LAST stage's seam-out layers (plus
        # head/data layers)
        allowed = {id(last[i]) for i in self.seam_out_pos}
        staged = {id(l) for ls in self.stage_layers for l in ls}
        for l in self.tail_layers:
            for p in l.parents:
                if id(p) in staged and id(p) not in allowed:
                    raise ConfigError(
                        f"tail layer {l.name!r} consumes stage-internal "
                        f"activation {p.name!r}; only the final seam "
                        f"crosses out of the pipeline")

        # positional param-name map: stage-0 spec name -> [per-stage names]
        self.stage_param_names: Dict[str, List[str]] = {}
        for li, l0 in enumerate(stage0):
            for si, spec in enumerate(l0.param_specs):
                names = [self.stage_layers[k][li].param_specs[si].name
                         for k in range(len(self.stage_layers))]
                self.stage_param_names[spec.name] = names

        # param_specs: stacked stage-0 specs (leading S), per-stage dropped
        S = len(self.stage_layers)
        dropped = {n for names in self.stage_param_names.values()
                   for n in names[1:]}
        new_specs: Dict[str, ParamSpec] = {}
        for name, spec in self.param_specs.items():
            if name in dropped:
                continue
            if name in self.stage_param_names:
                from dataclasses import replace as _replace

                spec = _replace(spec, shape=(S, *spec.shape))
            new_specs[name] = spec
        self._flat_param_specs = self.param_specs
        self.param_specs = new_specs

    # -- params ---------------------------------------------------------

    def init(self, rng, dtype=None, skip=()):
        # skip (pserver routing) is accepted for Topology-signature parity;
        # stage-stacked params are never routed, so it only affects
        # head/tail layers
        saved = self.param_specs
        self.param_specs = self._flat_param_specs
        try:
            args = (rng,) if dtype is None else (rng, dtype)
            params, state = Topology.init(self, *args, skip=skip)
        finally:
            self.param_specs = saved
        for name0, names in self.stage_param_names.items():
            params[name0] = jnp.stack([params.pop(n) if n != name0
                                       else params[name0] for n in names])
        return params, state

    def unstack_params(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Stacked params -> the flat per-stage dict of the plain Topology
        (checkpoint/serialization interop, and equivalence testing)."""
        out = dict(params)
        for name0, names in self.stage_param_names.items():
            stacked = out.pop(name0)
            for k, n in enumerate(names):
                out[n] = stacked[k]
        return out

    # -- execution ------------------------------------------------------

    def _run_layers(self, layers, env, all_params, ctx, feed):
        for layer in layers:
            if layer.is_data:
                env[layer.name] = _coerce_feed(layer, feed)
                continue
            parent_acts = [env[p.name] for p in layer.parents]
            local = {s.name: all_params[s.name] for s in layer.param_specs}
            env[layer.name] = layer.forward(ctx, local, *parent_acts)

    def apply(self, params, state, feed, *, train=False, rng=None,
              outputs=None, device_specs=None, param_overrides=None):
        # param_overrides (the pserver TableProxy hook) is accepted for
        # trainer-signature parity; pipelined stage layers consume plain
        # arrays, so overrides only reach head/tail layers
        ctx = ApplyContext(train, rng)
        env: Dict[str, Act] = {}
        stage0 = self.stage_layers[0]
        stacked = {n: params[n] for n in self.stage_param_names}
        flat_state = dict(state)
        all_params = {**params, **flat_state, **(param_overrides or {})}

        self._run_layers(self.head_layers, env, all_params, ctx, feed)

        # auxiliary Act.state (RNN final_h/final_c, attention probs) does
        # NOT cross pipeline boundaries: the seam-in and seam-out trees must
        # have identical structure for the ppermute carry swap, and a head
        # fc act has no state while a stage LSTM act does
        from dataclasses import replace as _dreplace

        def strip(act: Act) -> Act:
            return _dreplace(act, state={})

        xs = tuple(strip(env[p.name]) for _i, p in self.seam_in)

        def stage_fn(w, xs_mb):
            senv = {p.name: a for (_i, p), a in zip(self.seam_in, xs_mb)}
            for layer in stage0:
                parent_acts = [senv[p.name] for p in layer.parents]
                local = {s.name: w[s.name] for s in layer.param_specs}
                senv[layer.name] = layer.forward(ctx, local, *parent_acts)
            return tuple(strip(senv[stage0[i].name])
                         for i in self.seam_out_pos)

        ys = pipeline_apply(stage_fn, stacked, xs, mesh=self.mesh,
                            n_microbatches=self.n_microbatches,
                            stage_axis=self.stage_axis,
                            data_axis=self.data_axis)
        last = self.stage_layers[-1]
        for pos, y in zip(self.seam_out_pos, ys):
            env[last[pos].name] = y

        self._run_layers(self.tail_layers, env, all_params, ctx, feed)
        new_state = {**state, **ctx.updated_state}
        result = {name: act for name, act in env.items()}
        if outputs is not None:
            missing = set(outputs) - set(result)
            if missing:
                raise ConfigError(
                    f"unknown/unavailable output layers {sorted(missing)} "
                    f"(stage-internal activations are not exposed)")
        return result, new_state
