"""MeshConfig — ONE declarative description of the device world.

Before this module, every parallelism feature carried its own mesh/axis
plumbing: ``distributed.global_mesh`` parsed flags, ``ShardingRules`` took
a built ``Mesh``, the pipeline DSL took ``(mesh, stage_axis)``, ring
attention took ``(mesh, seq_axis)``, and the pserver tier took
``(mesh, axis)`` — five call sites that each privately knew part of the
world shape (the same scatter the reference spread across
``MultiGradientMachine`` and the trainer; PAPER.md layer map).

``MeshConfig`` is the single place that knows the world shape: an ordered
set of **named axes** with sizes, plus the role bindings (which axis is
the data/batch axis, which carries tensor-parallel shards, which is the
pipeline ``stage`` axis, which the pserver tables shard over).  Every
consumer accepts a ``MeshConfig`` anywhere it previously took a ``Mesh``
(``as_mesh`` materializes lazily), so changing the world is re-instanting
ONE object — which is exactly what elastic gang recovery does on a host
loss (``resilience/cluster.py``): ``cfg.fit_world(n)`` rescales the
elastic (data) axis to the surviving device count and everything
downstream (shardings, pipeline stages, pserver shard counts, checkpoint
resharding) follows from the one new mesh.

Checkpoints record ``cfg.to_json()`` in their manifest meta, so a restore
onto a differently-sized world can see what shape the state was saved
under — resharding then "falls out of the manifest": arrays are stored
host-side and layout-free, and re-placement under the new config's
shardings is the entire reshard (pserver tables additionally re-pad their
vocab to the new shard multiple; ``pserver/tier.py``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Sequence, Tuple, Union

from paddle_tpu.utils.error import ConfigError

__all__ = ["MeshConfig", "as_mesh", "mesh_axes"]


@dataclass(frozen=True)
class MeshConfig:
    """Named-axis device mesh description + role bindings.

    ``axes`` is an ordered ``((name, size), ...)`` tuple — order is the
    device-assignment order of ``jax.sharding.Mesh`` (put the DCN-crossing
    axis first on multi-slice pods, the scaling-book recipe).  Role fields
    name which axis plays each part; a role whose axis is absent from
    ``axes`` simply has size 1 (asking for it never errors — callers can
    treat every config as carrying all four roles).
    """

    axes: Tuple[Tuple[str, int], ...]
    data_axis: str = "data"        # batch sharding + gradient all-reduce
    model_axis: str = "model"      # tensor-parallel weight shards
    pipe_axis: str = "stage"       # GPipe pipeline stages
    seq_axis: str = "seq"          # ring-attention sequence shards
    pserver_axis: Optional[str] = None   # embedding-table shards
                                         # (None = FLAGS.pserver_axis)
    #: the axis elastic resize rescales (host loss shrinks the world along
    #: this axis; grow-back restores it).  Defaults to ``data_axis``.
    elastic_axis: Optional[str] = None
    #: the DCN-crossing (pod-boundary) axis.  Non-None makes the POD the
    #: failure unit: ``fit_world`` shrinks/grows this axis by whole pods,
    #: gradient allreduce goes hierarchical (parallel/hierarchical.py),
    #: and the pserver a2a routes in two hops.  Keep it FIRST in ``axes``
    #: so pods are contiguous rank blocks (the docstring's multi-slice
    #: device-assignment rule).
    dcn_axis: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(self, "axes",
                           tuple((str(n), int(s)) for n, s in self.axes))
        names = [n for n, _ in self.axes]
        if len(set(names)) != len(names):
            raise ConfigError(f"duplicate mesh axis names in {names}")
        for n, s in self.axes:
            if s < 1:
                raise ConfigError(f"mesh axis {n!r} must have size >= 1, "
                                  f"got {s}")

    # -- construction ----------------------------------------------------

    @classmethod
    def of(cls, **axis_sizes: int) -> "MeshConfig":
        """``MeshConfig.of(data=4, model=2)`` — ordered as given."""
        return cls(axes=tuple(axis_sizes.items()))

    @classmethod
    def named(cls, shape: Sequence[int],
              axis_names: Optional[Sequence[str]] = None) -> "MeshConfig":
        """Config from a shape plus optional names: names truncate to the
        shape's rank, and a missing/mismatched list falls back to the
        default ``data, model, seq, expert, stage`` prefix.  The ONE
        naming rule — ``from_flags`` and ``utils.devices.make_mesh`` both
        route through here."""
        shape = tuple(int(s) for s in shape)
        names = tuple(axis_names or ())[: len(shape)]
        if len(names) != len(shape):
            base = ("data", "model", "seq", "expert", "stage")
            if len(shape) > len(base):
                raise ConfigError(
                    f"mesh shape {shape} has {len(shape)} dimensions but "
                    f"only {len(base)} default axis names exist — pass "
                    f"axis_names covering every dimension")
            names = base[: len(shape)]
        return cls(axes=tuple(zip(names, shape)))

    @classmethod
    def from_flags(cls, n_devices: Optional[int] = None) -> "MeshConfig":
        """The flag plane (``--mesh_shape`` / ``--mesh_axes`` /
        ``--pserver_axis``) as a config; empty ``--mesh_shape`` = one 1-D
        data axis over all devices."""
        from paddle_tpu.utils.flags import FLAGS

        if n_devices is None:
            import jax

            n_devices = len(jax.devices())
        from paddle_tpu.utils.devices import _parse_mesh_shape

        cfg = cls.named(_parse_mesh_shape(FLAGS.mesh_shape, n_devices),
                        FLAGS.mesh_axes.split(","))
        return replace(cfg, pserver_axis=FLAGS.pserver_axis,
                       dcn_axis=FLAGS.dcn_axis or None)

    @classmethod
    def from_mesh(cls, mesh) -> "MeshConfig":
        """Describe an existing ``jax.sharding.Mesh``."""
        return cls(axes=tuple((n, int(mesh.shape[n]))
                              for n in mesh.axis_names))

    # -- shape queries ---------------------------------------------------

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.axes)

    @property
    def shape(self) -> Dict[str, int]:
        return dict(self.axes)

    @property
    def size(self) -> int:
        return math.prod(s for _, s in self.axes) if self.axes else 1

    def axis_size(self, name: str) -> int:
        """Size of axis ``name``; 1 when the axis is absent (a missing
        axis IS a size-1 axis for every sharding purpose)."""
        return self.shape.get(name, 1)

    # -- pod (DCN) topology ----------------------------------------------

    @property
    def dcn_size(self) -> int:
        """Number of pods (size of the dcn axis; 1 when no dcn axis is
        bound — a single-pod world IS a dcn_size-1 world)."""
        return self.axis_size(self.dcn_axis) if self.dcn_axis else 1

    @property
    def pod_size(self) -> int:
        """Ranks/devices per pod: everything that is NOT the dcn axis."""
        return self.size // self.dcn_size

    def pod_of(self, rank: int) -> int:
        """Pod index of ``rank``.  Pods are contiguous rank blocks — the
        dcn axis is first in ``axes`` (device-assignment order), so rank
        ``r`` lives in pod ``r // pod_size``."""
        if not 0 <= rank < self.size:
            raise ConfigError(f"rank {rank} outside mesh of size "
                              f"{self.size}")
        return rank // self.pod_size

    def role_axis(self, role: str) -> str:
        """Axis name bound to ``role`` ('data'|'model'|'pipe'|'seq'|
        'pserver')."""
        if role == "pserver" and self.pserver_axis is None:
            from paddle_tpu.utils.flags import FLAGS

            return FLAGS.pserver_axis
        name = getattr(self, f"{role}_axis")
        if name is None:
            raise ConfigError(f"unknown mesh role {role!r}")
        return name

    # -- resize (the elastic operation) ----------------------------------

    def resize(self, **axis_sizes: int) -> "MeshConfig":
        """New config with the named axes resized (axes not mentioned keep
        their size; resizing an absent axis appends it)."""
        known = dict(self.axes)
        updated = tuple((n, axis_sizes.get(n, s)) for n, s in self.axes)
        appended = tuple((n, s) for n, s in axis_sizes.items()
                         if n not in known)
        return replace(self, axes=updated + appended)

    def fit_world(self, n_devices: int) -> "MeshConfig":
        """Rescale the ELASTIC axis so the mesh fits ``n_devices``: the
        other axes are fixed (model/pipe shards are topology, not
        capacity), the elastic axis becomes ``n_devices // prod(others)``.
        This is the one-call shrink/grow of elastic gang recovery.

        With a ``dcn_axis`` bound, the DCN axis is the elastic one — the
        failure unit is the POD, so the world shrinks/grows by whole pods
        (``n_devices // pod_size`` pods survive; a partial pod's stragglers
        are dropped with their pod, never resharded across pods)."""
        el = (self.dcn_axis if self.dcn_axis and
              self.dcn_axis in self.shape else
              self.elastic_axis or self.data_axis)
        others = math.prod(s for n, s in self.axes if n != el)
        new = n_devices // others
        if new < 1:
            raise ConfigError(
                f"cannot fit mesh {dict(self.axes)} into {n_devices} "
                f"device(s): non-elastic axes already need {others}")
        return self.resize(**{el: new})

    # -- materialization -------------------------------------------------

    def build(self, devices: Optional[Sequence] = None):
        """Instantiate the ``jax.sharding.Mesh`` over ``devices`` (default:
        all).  The one place a config becomes hardware."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        devs = list(devices) if devices is not None else jax.devices()
        if self.size > len(devs):
            raise ConfigError(
                f"mesh {dict(self.axes)} needs {self.size} devices, "
                f"have {len(devs)}")
        shape = tuple(s for _, s in self.axes) or (1,)
        names = self.axis_names or ("data",)
        arr = np.asarray(devs[: math.prod(shape)]).reshape(shape)
        return Mesh(arr, names)

    # -- manifest plumbing -----------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        return {
            "axes": [[n, s] for n, s in self.axes],
            "data_axis": self.data_axis,
            "model_axis": self.model_axis,
            "pipe_axis": self.pipe_axis,
            "seq_axis": self.seq_axis,
            "pserver_axis": self.pserver_axis,
            "elastic_axis": self.elastic_axis,
            "dcn_axis": self.dcn_axis,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "MeshConfig":
        return cls(axes=tuple((n, int(s)) for n, s in d["axes"]),
                   data_axis=d.get("data_axis", "data"),
                   model_axis=d.get("model_axis", "model"),
                   pipe_axis=d.get("pipe_axis", "stage"),
                   seq_axis=d.get("seq_axis", "seq"),
                   pserver_axis=d.get("pserver_axis"),
                   elastic_axis=d.get("elastic_axis"),
                   dcn_axis=d.get("dcn_axis"))

    def __repr__(self) -> str:
        body = ",".join(f"{n}={s}" for n, s in self.axes)
        return f"MeshConfig({body})"


def as_mesh(mesh_or_config, devices: Optional[Sequence] = None):
    """Materialize: a ``Mesh`` passes through, a ``MeshConfig`` builds,
    ``None`` stays ``None``.  Every parallel consumer routes its ``mesh``
    argument through here so call sites may hold the declarative config
    instead of a bound device object."""
    if mesh_or_config is None:
        return None
    if isinstance(mesh_or_config, MeshConfig):
        return mesh_or_config.build(devices)
    return mesh_or_config


def mesh_axes(mesh_or_config) -> Tuple[str, ...]:
    """Axis names of either form without materializing devices."""
    if isinstance(mesh_or_config, MeshConfig):
        return mesh_or_config.axis_names
    return tuple(mesh_or_config.axis_names)
