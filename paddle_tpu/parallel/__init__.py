"""paddle_tpu.parallel — SPMD parallelism over jax.sharding meshes.

Replaces the reference's MultiGradientMachine (single-node DP),
ParameterServer2 tier (multi-node DP), and ParallelNeuralNetwork (layer-device
model parallelism) with mesh shardings + XLA collectives, and adds the modern
strategies the reference predates: tensor parallelism, sequence parallelism
(ring attention), pipeline parallelism (GPipe over a 'stage' axis,
``pipeline.py``), sharded embeddings. See SURVEY.md §2 parallelism map & §5.8.

The world shape lives in ONE object: :class:`MeshConfig` (``mesh.py``) —
named axes + role bindings that every consumer here (and the pserver tier
and the trainer) accepts wherever a ``jax.sharding.Mesh`` is expected.
Elastic gang recovery (``resilience/cluster.py``) resizes the world by
re-instantiating this one config (``cfg.fit_world(n)``); see
docs/parallel.md.
"""

from paddle_tpu.parallel.mesh import MeshConfig, as_mesh, mesh_axes
from paddle_tpu.parallel.sharding import (
    ShardingRules,
    replicated,
    batch_sharding,
    shard_params,
    P,
)
from paddle_tpu.parallel.api import make_parallel_train_step, shard_batch
from paddle_tpu.parallel.hierarchical import (hierarchical_psum,
                                              hierarchical_psum_compressed,
                                              init_dcn_residuals,
                                              make_hierarchical_train_step)
from paddle_tpu.parallel.pipeline import (
    stack_stage_params,
    shard_stage_params,
    pipeline_apply,
    make_pipeline_train_step,
)
from paddle_tpu.parallel.ring_attention import ring_attention, ring_attention_sharded
from paddle_tpu.parallel.embedding import sharded_embedding_lookup, shard_table
from paddle_tpu.parallel.compat import axis_size, shard_map
from paddle_tpu.parallel.distributed import (
    initialize_distributed,
    shutdown_distributed,
    global_mesh,
    is_multi_host,
    resume_pass,
)
from paddle_tpu.parallel.launcher import (ClusterLauncher, launch_local,
                                          launch_supervised)
from paddle_tpu.utils.devices import make_mesh
