"""Cluster job launcher — the analog of the reference's fabric launcher
(paddle/scripts/cluster_train/paddle.py:101-175: job_pserver/job_trainer
start one process per HOSTS entry over ssh with the wiring flags injected).

TPU-native shape: there is no pserver tier to start — every process runs the
SAME training program and ``jax.distributed`` wires the control plane.  The
launcher's job is exactly the reference's job_trainer loop: for each host,
start the program with the coordinator address / world size / process id
injected (env vars here, gflags there), local ranks via subprocess, remote
ranks via ssh.  ``initialize_distributed()`` on the worker side picks the
env up (PADDLE_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID).

On real TPU pods the platform launcher (GKE/xpk/ray) plays this role; this
module is the self-contained equivalent for bare hosts and for tests.

Supervision: ``poll()``/``kill_gang()`` expose the gang-level process
control the :class:`paddle_tpu.resilience.cluster.GangSupervisor` builds
on (detect rank death, SIGKILL the whole gang — SIGKILL, because a rank
wedged in a JAX collective, or SIGSTOPped by the chaos harness, ignores
SIGTERM).  ``launch_supervised`` is the one-call local form: launch N
ranks under a supervisor that gang-restarts them on death or hang.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from paddle_tpu.utils import logger

__all__ = ["ClusterLauncher", "launch_local", "launch_supervised"]

_LOCAL_HOSTS = ("localhost", "127.0.0.1", "::1", "")


def _parse_host(entry: str):
    """Split 'user@host[:port]' -> (user|None, host, port|None) — the ONE
    parser behind local-detection, the coordinator address, and ssh.

    IPv6: a bare address ('::1', '2001:db8::2') never carries a port; use
    bracket syntax '[2001:db8::2]:2222' to attach one."""
    user, _, rest = entry.rpartition("@")
    user = user or None
    if rest.startswith("["):            # bracketed IPv6, optional :port
        host, _, tail = rest[1:].partition("]")
        port = tail[1:] if tail.startswith(":") else None
    elif rest.count(":") == 1:          # host:port
        host, _, port = rest.partition(":")
    else:                               # plain host, or bare IPv6 (no port)
        host, port = rest, None
    return user, host, port or None


def _host_part(entry: str) -> str:
    """'user@10.0.0.2:2222' -> '10.0.0.2' (port/user stripped)."""
    return _parse_host(entry)[1]


def _ssh_dest(entry: str):
    """'user@10.0.0.2:2222' -> ('user@10.0.0.2', '2222'); port None if
    absent.  ssh does not accept ':port' in the destination — it must ride a
    separate '-p' flag."""
    user, host, port = _parse_host(entry)
    return (f"{user}@{host}" if user else host), port


@dataclass
class ClusterLauncher:
    """Start one process per entry of ``hosts`` running the same program.

    hosts: e.g. ``["localhost", "localhost"]`` or ``["10.0.0.1", "user@10.0.0.2"]``
    — entry 0 also hosts the jax.distributed coordinator.  Remote entries run
    through ``ssh_cmd``; 'localhost'/'127.0.0.1' fork directly.
    """

    hosts: Sequence[str]
    coordinator_port: int = 12355
    python: str = sys.executable          # local ranks
    remote_python: str = "python3"        # remote ranks: sys.executable's
                                          # venv path rarely exists there
    ssh_cmd: Sequence[str] = ("ssh", "-o", "BatchMode=yes")
    procs: List[subprocess.Popen] = field(default_factory=list)

    def _coordinator(self) -> str:
        host = _host_part(self.hosts[0])
        if host in _LOCAL_HOSTS:
            host = "127.0.0.1"
        if ":" in host:  # IPv6 literal: gRPC targets need [addr]:port
            host = f"[{host}]"
        return f"{host}:{self.coordinator_port}"

    def _rank_spec(self, i: int, script: str, args: Sequence[str],
                   env: Optional[Dict[str, str]], cwd: Optional[str],
                   env_extra: Optional[Dict[str, str]] = None) -> Dict:
        """Popen kwargs for one rank, with the distributed wiring (and any
        ``env_extra`` overlay) injected — shared by ``launch`` and
        ``relaunch_rank`` so a relaunched rank's ssh export string is
        rebuilt, not replayed stale."""
        wiring = {
            "PADDLE_TPU_COORDINATOR": self._coordinator(),
            "PADDLE_TPU_NUM_PROCESSES": str(len(self.hosts)),
            "PADDLE_TPU_PROCESS_ID": str(i),
        }
        user, hname, port = _parse_host(self.hosts[i])
        dest = f"{user}@{hname}" if user else hname
        # an explicit :port on a local name means a forwarded sshd —
        # honor it with ssh; only a bare local name forks directly
        if hname in _LOCAL_HOSTS and port is None:
            penv = {**os.environ, **(env or {}), **wiring,
                    **(env_extra or {})}
            return dict(args=[self.python, script, *args], env=penv,
                        cwd=cwd)
        q = shlex.quote
        exports = " ".join(
            f"{q(k)}={q(str(v))}"
            for k, v in {**(env or {}), **wiring,
                         **(env_extra or {})}.items())
        remote = (f"cd {q(cwd or '.')} && env {exports} "
                  f"{q(self.remote_python)} {q(script)} "
                  + " ".join(q(str(a)) for a in args))
        port_flag = ("-p", port) if port else ()
        return dict(args=[*self.ssh_cmd, *port_flag, dest, remote])

    def launch(self, script: str, args: Sequence[str] = (),
               env: Optional[Dict[str, str]] = None,
               cwd: Optional[str] = None) -> List[subprocess.Popen]:
        """Start ``python script args...`` on every host with the distributed
        wiring injected; returns the Popen handles (remote ones wrap ssh)."""
        if self.procs:
            raise RuntimeError("launcher already started a job")
        self._job = (script, tuple(args), env, cwd)  # for relaunch_rank
        for i, host in enumerate(self.hosts):
            spec = self._rank_spec(i, script, args, env, cwd)
            p = subprocess.Popen(**spec)
            logger.info("launched rank %d on %s (pid %d)", i, host or "local",
                        p.pid)
            self.procs.append(p)
        return self.procs

    def poll(self) -> List[Optional[int]]:
        """Non-blocking per-rank exit codes (None = still running)."""
        return [p.poll() for p in self.procs]

    def kill_rank(self, rank: int, timeout: float = 10.0) -> Optional[int]:
        """SIGKILL one rank and reap it (elastic shrink: the rest of the
        gang stays up).  SIGKILL also takes down a SIGSTOPped rank, which
        SIGTERM would not.  Returns its exit code."""
        p = self.procs[rank]
        if p.poll() is None:
            p.kill()
        try:
            return p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return p.poll()

    def relaunch_rank(self, rank: int,
                      env_extra: Optional[Dict[str, str]] = None
                      ) -> subprocess.Popen:
        """Start a REPLACEMENT process for one (dead) rank with the same
        command/wiring the original launch used — the elastic grow-back
        primitive.  The old Popen at this index must already be reaped.
        ``env_extra`` overlays the environment for BOTH local forks and
        ssh ranks (the remote export string is rebuilt, not replayed) —
        the supervisor uses it to hand a joiner its join epoch."""
        if self.procs[rank].poll() is None:
            raise RuntimeError(f"rank {rank} is still alive; kill it first")
        script, args, env, cwd = self._job
        spec = self._rank_spec(rank, script, args, env, cwd,
                               env_extra=env_extra)
        p = subprocess.Popen(**spec)
        logger.info("relaunched rank %d (pid %d)", rank, p.pid)
        self.procs[rank] = p
        return p

    def kill_gang(self) -> List[Optional[int]]:
        """SIGKILL every rank and reap; returns the exit codes.  The gang
        is one failure domain: once any rank is dead or hung, surviving
        ranks are wedged in collectives (or about to be) and must die too
        before a relaunch can bind the same ports."""
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        codes = []
        for p in self.procs:
            try:
                codes.append(p.wait(timeout=10))
            except subprocess.TimeoutExpired:
                codes.append(p.poll())
        return codes

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        """Wait for all ranks; returns exit codes (raises on timeout)."""
        deadline = time.time() + timeout if timeout else None
        codes = []
        for p in self.procs:
            left = (deadline - time.time()) if deadline else None
            codes.append(p.wait(timeout=left))
        return codes

    def terminate(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def launch_local(n: int, script: str, args: Sequence[str] = (),
                 env: Optional[Dict[str, str]] = None,
                 coordinator_port: int = 12355) -> ClusterLauncher:
    """Convenience: start ``n`` local ranks of ``script`` (the 2-process
    self-test shape; also useful for CPU multi-process debugging)."""
    l = ClusterLauncher(hosts=["localhost"] * n,
                        coordinator_port=coordinator_port)
    l.launch(script, args, env=env)
    return l


def launch_supervised(n: int, script: str, args: Sequence[str] = (),
                      env: Optional[Dict[str, str]] = None, **kw):
    """Run ``n`` local ranks of ``script`` under a gang supervisor: rank
    death or heartbeat stall kills and relaunches the whole gang (bounded
    by ``--gang_max_restarts``, exponential backoff), resuming through the
    trainer's ``--resume=auto`` path.  Keyword args forward to
    :class:`paddle_tpu.resilience.cluster.GangSupervisor`; returns its
    ``GangResult``, raising ``GangFailedError`` when the budget is spent."""
    from paddle_tpu.resilience.cluster import GangSupervisor

    sup = GangSupervisor(["localhost"] * n, script, args, env=env, **kw)
    return sup.run()
