"""Cluster job launcher — the analog of the reference's fabric launcher
(paddle/scripts/cluster_train/paddle.py:101-175: job_pserver/job_trainer
start one process per HOSTS entry over ssh with the wiring flags injected).

TPU-native shape: there is no pserver tier to start — every process runs the
SAME training program and ``jax.distributed`` wires the control plane.  The
launcher's job is exactly the reference's job_trainer loop: for each host,
start the program with the coordinator address / world size / process id
injected (env vars here, gflags there), local ranks via subprocess, remote
ranks via ssh.  ``initialize_distributed()`` on the worker side picks the
env up (PADDLE_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID).

On real TPU pods the platform launcher (GKE/xpk/ray) plays this role; this
module is the self-contained equivalent for bare hosts and for tests.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from paddle_tpu.utils import logger

__all__ = ["ClusterLauncher", "launch_local"]

_LOCAL_HOSTS = ("localhost", "127.0.0.1", "")


def _host_part(entry: str) -> str:
    """'user@10.0.0.2:2222' -> '10.0.0.2' (port/user stripped)."""
    return entry.split("@")[-1].split(":")[0]


@dataclass
class ClusterLauncher:
    """Start one process per entry of ``hosts`` running the same program.

    hosts: e.g. ``["localhost", "localhost"]`` or ``["10.0.0.1", "user@10.0.0.2"]``
    — entry 0 also hosts the jax.distributed coordinator.  Remote entries run
    through ``ssh_cmd``; 'localhost'/'127.0.0.1' fork directly.
    """

    hosts: Sequence[str]
    coordinator_port: int = 12355
    python: str = sys.executable          # local ranks
    remote_python: str = "python3"        # remote ranks: sys.executable's
                                          # venv path rarely exists there
    ssh_cmd: Sequence[str] = ("ssh", "-o", "BatchMode=yes")
    procs: List[subprocess.Popen] = field(default_factory=list)

    def _coordinator(self) -> str:
        host = _host_part(self.hosts[0])
        if host in _LOCAL_HOSTS:
            host = "127.0.0.1"
        return f"{host}:{self.coordinator_port}"

    def launch(self, script: str, args: Sequence[str] = (),
               env: Optional[Dict[str, str]] = None,
               cwd: Optional[str] = None) -> List[subprocess.Popen]:
        """Start ``python script args...`` on every host with the distributed
        wiring injected; returns the Popen handles (remote ones wrap ssh)."""
        if self.procs:
            raise RuntimeError("launcher already started a job")
        coord = self._coordinator()
        for i, host in enumerate(self.hosts):
            wiring = {
                "PADDLE_TPU_COORDINATOR": coord,
                "PADDLE_TPU_NUM_PROCESSES": str(len(self.hosts)),
                "PADDLE_TPU_PROCESS_ID": str(i),
            }
            if _host_part(host) in _LOCAL_HOSTS:
                penv = {**os.environ, **(env or {}), **wiring}
                p = subprocess.Popen([self.python, script, *args],
                                     env=penv, cwd=cwd)
            else:
                q = shlex.quote
                exports = " ".join(
                    f"{q(k)}={q(str(v))}"
                    for k, v in {**(env or {}), **wiring}.items())
                remote = (f"cd {q(cwd or '.')} && env {exports} "
                          f"{q(self.remote_python)} {q(script)} "
                          + " ".join(q(str(a)) for a in args))
                p = subprocess.Popen([*self.ssh_cmd, host, remote])
            logger.info("launched rank %d on %s (pid %d)", i, host or "local",
                        p.pid)
            self.procs.append(p)
        return self.procs

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        """Wait for all ranks; returns exit codes (raises on timeout)."""
        deadline = time.time() + timeout if timeout else None
        codes = []
        for p in self.procs:
            left = (deadline - time.time()) if deadline else None
            codes.append(p.wait(timeout=left))
        return codes

    def terminate(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def launch_local(n: int, script: str, args: Sequence[str] = (),
                 env: Optional[Dict[str, str]] = None,
                 coordinator_port: int = 12355) -> ClusterLauncher:
    """Convenience: start ``n`` local ranks of ``script`` (the 2-process
    self-test shape; also useful for CPU multi-process debugging)."""
    l = ClusterLauncher(hosts=["localhost"] * n,
                        coordinator_port=coordinator_port)
    l.launch(script, args, env=env)
    return l
