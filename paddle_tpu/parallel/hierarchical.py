"""Hierarchical (two-level) gradient allreduce over the ``dcn`` axis.

A multi-pod mesh has two link classes: ICI inside a pod (fast, uniform)
and DCN between pods (an order of magnitude less bandwidth, higher and
noisier latency — the scaling-book multi-slice model).  A flat allreduce
over the joint ``(dcn, data)`` replica axes moves every gradient byte
across DCN once per hop of the ring it happens to land on; the
bandwidth-optimal schedule instead uses each tier for what it is good
at:

1. **ICI reduce-scatter** over the pod-local ``data`` axis — every
   device ends up owning the pod-local SUM of one ``1/ici_size`` shard
   of the gradient;
2. **DCN allreduce of the partials** — only ``1/ici_size`` of the bytes
   cross the slow tier, and the transfer parallelizes across the pod's
   devices (each device exchanges only its own shard with its
   same-index peers in other pods);
3. **ICI allgather** to rebuild the fully-reduced gradient on every
   device.

The sum is the SAME sum — the two-level schedule only reassociates it —
and on a single pod (``dcn_size == 1``) :func:`hierarchical_psum` IS
``lax.psum`` by construction, so the flat and hierarchical paths are
bit-compatible there (pinned by test).

Optionally the DCN hop compresses the partials to bfloat16 with **error
feedback** (:func:`hierarchical_psum_compressed`): each pod keeps the
quantization residual it introduced and adds it back into the next
step's partials, so the compression error accumulates into the model as
a one-step-delayed correction instead of a bias.  Not bit-exact with the
uncompressed path — gated by the convergence tier, not by the
bit-equality pins (``--dcn_compress``).

``make_hierarchical_train_step`` is the step-builder twin of
``parallel.api.make_parallel_train_step`` for dcn-bound data-parallel
meshes: it computes per-shard gradients inside ``shard_map`` (GSPMD's
implicit ``value_and_grad`` reduction would already be global — summing
it again would multiply by the world size) and routes them through the
two-level schedule above.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import compat
from paddle_tpu.parallel.mesh import MeshConfig
from paddle_tpu.param.optimizers import Optimizer
from paddle_tpu.utils import FLAGS
from paddle_tpu.utils.error import ConfigError

__all__ = ["hierarchical_psum", "hierarchical_psum_compressed",
           "init_dcn_residuals", "make_hierarchical_train_step"]


def _padded(size: int, ici_size: int) -> int:
    return size + (-size % ici_size)


def hierarchical_psum(x: jax.Array, ici_axis: str, dcn_axis: str, *,
                      ici_size: int, dcn_size: int) -> jax.Array:
    """Two-level allreduce of ``x`` from inside a shard_map body.

    ``dcn_size == 1`` returns the flat ``lax.psum`` — bit-compatible by
    construction, so a single-pod world pays zero schedule overhead and
    the hierarchical step builder needs no special-casing."""
    if dcn_size <= 1:
        return lax.psum(x, ici_axis)
    flat = x.reshape(-1)
    pad = _padded(flat.size, ici_size) - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    # 1) ICI reduce-scatter: own the pod-local sum of one shard
    part = lax.psum_scatter(flat, ici_axis, scatter_dimension=0,
                            tiled=True)
    # 2) DCN allreduce of the 1/ici_size partials only
    part = lax.psum(part, dcn_axis)
    # 3) ICI allgather rebuilds the full reduced tensor
    full = lax.all_gather(part, ici_axis, tiled=True)
    if pad:
        full = full[:-pad]
    return full.reshape(x.shape)


def hierarchical_psum_compressed(x: jax.Array, residual: jax.Array,
                                 ici_axis: str, dcn_axis: str, *,
                                 ici_size: int, dcn_size: int):
    """:func:`hierarchical_psum` with the DCN hop in bfloat16 + error
    feedback.  ``residual`` is this device's carried quantization error
    (shape ``[padded_size // ici_size]``, the scattered-partial shape);
    returns ``(reduced, new_residual)``.  The ICI hops stay full
    precision — only the slow tier is compressed."""
    if dcn_size <= 1:
        return lax.psum(x, ici_axis), residual
    flat = x.reshape(-1)
    pad = _padded(flat.size, ici_size) - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    part = lax.psum_scatter(flat, ici_axis, scatter_dimension=0,
                            tiled=True)
    # error feedback: fold last step's quantization error back in BEFORE
    # quantizing, so the error is a one-step delay, not a bias
    carried = part + residual.astype(part.dtype)
    q = carried.astype(jnp.bfloat16)
    new_residual = carried - q.astype(part.dtype)
    part = lax.psum(q, dcn_axis).astype(part.dtype)
    full = lax.all_gather(part, ici_axis, tiled=True)
    if pad:
        full = full[:-pad]
    return full.reshape(x.shape), new_residual


def _resolve(mesh) -> "tuple":
    """``(cfg, built, dcn_axis, data_axis, dcn_size, ici_size)`` from a
    MeshConfig (required — the role bindings live there)."""
    if not isinstance(mesh, MeshConfig):
        raise ConfigError(
            "make_hierarchical_train_step needs a MeshConfig (the dcn "
            "axis is a role binding, not a bare mesh property)")
    dcn = mesh.dcn_axis
    if not dcn or dcn not in mesh.shape:
        raise ConfigError(
            f"mesh {mesh!r} binds no dcn axis — use "
            "make_parallel_train_step (flat GSPMD reduction) instead")
    data = mesh.role_axis("data")
    if data == dcn:
        raise ConfigError(
            f"dcn axis {dcn!r} cannot also be the data axis — the ICI "
            "reduce-scatter needs a pod-local replica axis")
    if data not in mesh.shape:
        raise ConfigError(
            f"mesh {mesh!r} has no {data!r} axis to reduce-scatter over")
    built = mesh.build()
    return (mesh, built, dcn, data, int(built.shape[dcn]),
            int(built.shape[data]))


def init_dcn_residuals(mesh, params) -> Any:
    """Zero error-feedback state for ``--dcn_compress``: one residual
    leaf per param leaf, shaped ``[dcn_size, padded_size]`` and sharded
    ``P(dcn, data)`` — each device holds the residual of ITS scattered
    partial, each pod its own (pods quantize independent partial sums,
    so their errors are independent state)."""
    cfg, built, dcn, data, dcn_size, ici_size = _resolve(mesh)

    def leaf(p):
        shape = (dcn_size, _padded(int(jnp.size(p)), ici_size))
        z = jnp.zeros(shape, jnp.float32)
        return jax.device_put(z, NamedSharding(built, P(dcn, data)))

    return jax.tree_util.tree_map(leaf, params)


def make_hierarchical_train_step(
    loss_fn: Callable[[Dict[str, Any], Dict[str, Any]], jax.Array],
    optimizer: Optimizer,
    mesh,
    *,
    compress: Optional[bool] = None,
    donate: bool = True,
) -> Callable:
    """Build the dcn-aware data-parallel train step.

    Uncompressed: ``step(params, opt_state, batch) -> (loss, params,
    opt_state)`` — drop-in for ``make_parallel_train_step`` on a
    dcn-bound config.  With ``compress`` (default ``--dcn_compress``):
    ``step(params, opt_state, residuals, batch) -> (loss, params,
    opt_state, residuals)`` where ``residuals`` starts as
    :func:`init_dcn_residuals`.

    Gradients are computed PER SHARD inside shard_map and reduced by the
    explicit two-level schedule — data-parallel only (params replicated;
    tensor-parallel rules need GSPMD's implicit reduction and keep using
    ``make_parallel_train_step``).  The batch shards over ``(dcn,
    data)`` jointly, exactly how ``shard_batch`` places it when both
    axes exist."""
    cfg, built, dcn, data, dcn_size, ici_size = _resolve(mesh)
    if compress is None:
        compress = bool(FLAGS.dcn_compress)
    n = dcn_size * ici_size
    batch_spec = P((dcn, data))

    def reduce_loss(loss):
        loss = lax.psum(loss, data)
        if dcn_size > 1:
            loss = lax.psum(loss, dcn)
        return loss / n

    def plain_body(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = jax.tree_util.tree_map(
            lambda g: hierarchical_psum(g, data, dcn, ici_size=ici_size,
                                        dcn_size=dcn_size) / n, grads)
        new_params, new_opt = optimizer.update(params, grads, opt_state,
                                               fused=True)
        return reduce_loss(loss), new_params, new_opt

    def compressed_body(params, opt_state, residuals, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        res_leaves = treedef.flatten_up_to(residuals)
        out_g, out_r = [], []
        for g, r in zip(leaves, res_leaves):
            red, nr = hierarchical_psum_compressed(
                g, r.reshape(-1), data, dcn, ici_size=ici_size,
                dcn_size=dcn_size)
            out_g.append(red / n)
            out_r.append(nr.reshape(r.shape))
        grads = jax.tree_util.tree_unflatten(treedef, out_g)
        new_res = jax.tree_util.tree_unflatten(treedef, out_r)
        new_params, new_opt = optimizer.update(params, grads, opt_state,
                                               fused=True)
        return reduce_loss(loss), new_params, new_opt, new_res

    rep = P()  # params/opt replicated across both axes
    if compress:
        shm = compat.shard_map(
            compressed_body, mesh=built,
            in_specs=(rep, rep, P(dcn, data), batch_spec),
            out_specs=(rep, rep, rep, P(dcn, data)))
        donate_argnums = (0, 1, 2) if donate else ()
    else:
        shm = compat.shard_map(
            plain_body, mesh=built,
            in_specs=(rep, rep, batch_spec),
            out_specs=(rep, rep, rep))
        donate_argnums = (0, 1) if donate else ()
    return jax.jit(shm, donate_argnums=donate_argnums)
