"""Evaluators — analog of the reference's metric framework.

Reference: 14 registered evaluator types accumulated across batches and
printed per pass (paddle/gserver/evaluators/Evaluator.cpp:995-1046 —
classification_error :46, sum :503, column_sum :584, rankauc, auc :862,
precision_recall, pnpair; ChunkEvaluator.cpp; CTCErrorEvaluator.cpp; printer
evaluators) driven by Evaluator::start/eval/finish.

TPU-native split: the *per-batch statistic* is a pure jnp function (can run
inside the jitted step and on sharded data — a psum away from global); the
*accumulation* across batches is a tiny host-side state machine.  Each
evaluator implements ``batch_stats(**kw) -> dict of arrays`` (pure) and
``update(stats)`` / ``result()`` (host).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

try:  # jnp only needed for the pure parts; numpy fallback keeps host tools light
    import jax.numpy as jnp
except Exception:  # pragma: no cover
    jnp = np

from paddle_tpu.utils.registry import Registry

__all__ = [
    "EVALUATORS",
    "Evaluator",
    "DeviceAccumulator",
    "ClassificationError",
    "SumEvaluator",
    "ColumnSumEvaluator",
    "Auc",
    "RankAuc",
    "PrecisionRecall",
    "PnpairEvaluator",
    "ChunkEvaluator",
    "CTCErrorEvaluator",
    "SeqClassificationError",
    "ValuePrinter",
    "GradientPrinter",
    "MaxIdPrinter",
    "MaxFramePrinter",
]

EVALUATORS: Registry = Registry("evaluator")


class Evaluator:
    name = "evaluator"
    #: True when ``batch_stats`` dicts combine across batches by elementwise
    #: sum — enables device-side accumulation (DeviceAccumulator).  Printers
    #: and row-collecting evaluators (pnpair) override to False.
    additive = True

    def start(self) -> None:
        raise NotImplementedError

    def batch_stats(self, **kw) -> Dict[str, Any]:
        """Pure per-batch statistic(s); safe to call inside jit."""
        raise NotImplementedError

    def update(self, stats: Dict[str, Any]) -> None:
        raise NotImplementedError

    def result(self) -> float:
        raise NotImplementedError

    # convenience: one-shot eval on host arrays
    def eval_batch(self, **kw) -> None:
        self.update({k: np.asarray(v) for k, v in self.batch_stats(**kw).items()})


@EVALUATORS.register("classification_error")
class ClassificationError(Evaluator):
    """Top-1 error rate (Evaluator.cpp ClassificationErrorEvaluator)."""

    name = "classification_error"

    def start(self):
        self.err, self.total = 0.0, 0.0

    def batch_stats(self, *, logits, labels, mask=None):
        pred = jnp.argmax(logits, axis=-1)
        labels = labels.reshape(pred.shape)
        wrong = (pred != labels).astype(jnp.float32)
        if mask is not None:
            wrong = wrong * mask
            return {"err": jnp.sum(wrong), "total": jnp.sum(mask)}
        return {"err": jnp.sum(wrong), "total": jnp.asarray(float(np.prod(pred.shape)))}

    def update(self, s):
        self.err += float(s["err"])
        self.total += float(s["total"])

    def result(self):
        return self.err / max(self.total, 1.0)


@EVALUATORS.register("sum")
class SumEvaluator(Evaluator):
    name = "sum"

    def start(self):
        self.sum, self.n = 0.0, 0

    def batch_stats(self, *, value, mask=None):
        if mask is not None:
            value = value * mask
        return {"sum": jnp.sum(value)}

    def update(self, s):
        self.sum += float(s["sum"])
        self.n += 1

    def result(self):
        return self.sum


@EVALUATORS.register("column_sum")
class ColumnSumEvaluator(Evaluator):
    name = "column_sum"

    def start(self):
        self.sum = None
        self.total = 0.0

    def batch_stats(self, *, value):
        return {"col": jnp.sum(value, axis=0), "n": jnp.asarray(float(value.shape[0]))}

    def update(self, s):
        col = np.asarray(s["col"])
        self.sum = col if self.sum is None else self.sum + col
        self.total += float(s["n"])

    def result(self):
        if self.sum is None:
            return 0.0
        return float(np.mean(self.sum / max(self.total, 1.0)))


@EVALUATORS.register("auc")
class Auc(Evaluator):
    """ROC AUC via fixed binning (the reference uses the same trick to stay
    streaming: AucEvaluator bins scores, Evaluator.cpp:862)."""

    name = "auc"

    def __init__(self, num_bins: int = 4096):
        self.num_bins = num_bins

    def start(self):
        self.pos = np.zeros(self.num_bins)
        self.neg = np.zeros(self.num_bins)

    def batch_stats(self, *, prob, labels):
        """prob: [B] or [B,2] (positive-class prob); labels: [B] in {0,1}."""
        if prob.ndim == 2:
            prob = prob[:, -1]
        labels = labels.reshape(prob.shape)
        idx = jnp.clip((prob * self.num_bins).astype(jnp.int32), 0, self.num_bins - 1)
        pos = jnp.zeros(self.num_bins).at[idx].add(labels.astype(jnp.float32))
        neg = jnp.zeros(self.num_bins).at[idx].add(1.0 - labels.astype(jnp.float32))
        return {"pos": pos, "neg": neg}

    def update(self, s):
        self.pos += np.asarray(s["pos"])
        self.neg += np.asarray(s["neg"])

    def result(self):
        # sum over bins high->low of TPR/FPR trapezoid
        pos = self.pos[::-1]
        neg = self.neg[::-1]
        tp = np.cumsum(pos)
        fp = np.cumsum(neg)
        P, N = tp[-1], fp[-1]
        if P == 0 or N == 0:
            return 0.5
        tpr = np.concatenate([[0.0], tp / P])
        fpr = np.concatenate([[0.0], fp / N])
        return float(np.trapezoid(tpr, fpr))


@EVALUATORS.register("rankauc")
class RankAuc(Evaluator):
    """Pairwise ranking AUC on (score, label) lists (RankAucEvaluator)."""

    name = "rankauc"

    def start(self):
        self.concordant, self.pairs = 0.0, 0.0

    def batch_stats(self, *, score, labels):
        s = score.reshape(-1)
        y = labels.reshape(-1).astype(jnp.float32)
        ds = s[:, None] - s[None, :]
        dy = y[:, None] - y[None, :]
        valid = dy > 0
        conc = jnp.sum(((ds > 0) & valid).astype(jnp.float32))
        ties = 0.5 * jnp.sum(((ds == 0) & valid).astype(jnp.float32))
        return {"conc": conc + ties, "pairs": jnp.sum(valid.astype(jnp.float32))}

    def update(self, st):
        self.concordant += float(st["conc"])
        self.pairs += float(st["pairs"])

    def result(self):
        return self.concordant / max(self.pairs, 1.0)


@EVALUATORS.register("precision_recall")
class PrecisionRecall(Evaluator):
    """Per-class precision/recall/F1 (PrecisionRecallEvaluator)."""

    name = "precision_recall"

    def __init__(self, num_classes: int = 2, positive_label: Optional[int] = None):
        self.num_classes = num_classes
        self.positive_label = positive_label

    def start(self):
        self.tp = np.zeros(self.num_classes)
        self.fp = np.zeros(self.num_classes)
        self.fn = np.zeros(self.num_classes)

    def batch_stats(self, *, logits, labels):
        pred = jnp.argmax(logits, axis=-1).reshape(-1)
        lab = labels.reshape(-1)
        C = self.num_classes
        oh_p = jnp.eye(C)[pred]
        oh_l = jnp.eye(C)[lab]
        tp = jnp.sum(oh_p * oh_l, axis=0)
        return {"tp": tp, "fp": jnp.sum(oh_p, 0) - tp, "fn": jnp.sum(oh_l, 0) - tp}

    def update(self, s):
        self.tp += np.asarray(s["tp"])
        self.fp += np.asarray(s["fp"])
        self.fn += np.asarray(s["fn"])

    def result(self):
        prec = self.tp / np.maximum(self.tp + self.fp, 1.0)
        rec = self.tp / np.maximum(self.tp + self.fn, 1.0)
        f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
        if self.positive_label is not None:
            return float(f1[self.positive_label])
        return float(np.mean(f1))

    def detail(self):
        prec = self.tp / np.maximum(self.tp + self.fp, 1.0)
        rec = self.tp / np.maximum(self.tp + self.fn, 1.0)
        return {"precision": prec, "recall": rec}


@EVALUATORS.register("pnpair")
class PnpairEvaluator(Evaluator):
    """Positive/negative pair ratio grouped by query (PnpairEvaluator):
    for each query id, counts concordant score pairs between pos & neg."""

    name = "pnpair"
    additive = False  # collects raw rows; pairs need the full pass

    def start(self):
        self.rows: List[np.ndarray] = []

    def batch_stats(self, *, score, labels, query_id):
        return {"score": score.reshape(-1), "labels": labels.reshape(-1),
                "qid": query_id.reshape(-1)}

    def update(self, s):
        self.rows.append(np.stack([
            np.asarray(s["score"], np.float64),
            np.asarray(s["labels"], np.float64),
            np.asarray(s["qid"], np.float64),
        ], 1))

    def result(self):
        if not self.rows:
            return 0.0
        data = np.concatenate(self.rows, 0)
        better = worse = ties = 0.0
        for q in np.unique(data[:, 2]):
            rows = data[data[:, 2] == q]
            for i in range(len(rows)):
                for j in range(len(rows)):
                    if rows[i, 1] > rows[j, 1]:
                        if rows[i, 0] > rows[j, 0]:
                            better += 1
                        elif rows[i, 0] < rows[j, 0]:
                            worse += 1
                        else:
                            ties += 1
        return (better + 0.5 * ties) / max(better + worse + ties, 1.0)


@EVALUATORS.register("seq_classification_error")
class SeqClassificationError(ClassificationError):
    """Sequence-level error: a sequence counts wrong if ANY token is wrong
    (SequenceClassificationErrorEvaluator)."""

    name = "seq_classification_error"

    def batch_stats(self, *, logits, labels, mask):
        pred = jnp.argmax(logits, axis=-1)
        wrong_tok = (pred != labels).astype(jnp.float32) * mask
        seq_wrong = (jnp.sum(wrong_tok, axis=1) > 0).astype(jnp.float32)
        return {"err": jnp.sum(seq_wrong), "total": jnp.asarray(float(pred.shape[0]))}


def _extract_chunks(tags: np.ndarray, scheme: str = "IOB") -> set:
    """Decode chunk spans from an IOB tag sequence: tag 2k = B-type k,
    2k+1 = I-type k, last id = O (the ChunkEvaluator convention)."""
    chunks = set()
    # convention: num_chunk_types*2 tags (B-k=2k, I-k=2k+1) then O = max id
    O = int(max(tags.max(initial=0), 0))
    start = ctype = None
    for i, t in enumerate(tags):
        t = int(t)
        if t == O or t < 0:
            if start is not None:
                chunks.add((start, i - 1, ctype))
                start = ctype = None
            continue
        typ, is_inside = t // 2, (t % 2 == 1)
        if not is_inside:  # B- tag
            if start is not None:
                chunks.add((start, i - 1, ctype))
            start, ctype = i, typ
        else:  # I- tag
            if start is None or ctype != typ:
                if start is not None:
                    chunks.add((start, i - 1, ctype))
                start, ctype = i, typ
    if start is not None:
        chunks.add((start, len(tags) - 1, ctype))
    return chunks


@EVALUATORS.register("chunk")
class ChunkEvaluator(Evaluator):
    """Chunking F1 over IOB tag sequences (ChunkEvaluator.cpp) — host-side
    decode (string-ish logic has no place on the MXU)."""

    name = "chunk"
    additive = False  # raw tag rows, decoded per batch on host

    def start(self):
        self.correct = self.pred = self.label = 0.0

    def batch_stats(self, *, pred_tags, label_tags, lengths):
        return {"pred_tags": pred_tags, "label_tags": label_tags, "lengths": lengths}

    def update(self, s):
        preds = np.asarray(s["pred_tags"])
        labs = np.asarray(s["label_tags"])
        lens = np.asarray(s["lengths"])
        for i in range(preds.shape[0]):
            L = int(lens[i])
            pc = _extract_chunks(preds[i, :L])
            lc = _extract_chunks(labs[i, :L])
            self.correct += len(pc & lc)
            self.pred += len(pc)
            self.label += len(lc)

    def result(self):
        p = self.correct / max(self.pred, 1.0)
        r = self.correct / max(self.label, 1.0)
        return 2 * p * r / max(p + r, 1e-12)


def _edit_distance(a, b) -> int:
    la, lb = len(a), len(b)
    dp = list(range(lb + 1))
    for i in range(1, la + 1):
        prev = dp[0]
        dp[0] = i
        for j in range(1, lb + 1):
            cur = dp[j]
            dp[j] = min(dp[j] + 1, dp[j - 1] + 1, prev + (a[i - 1] != b[j - 1]))
            prev = cur
    return dp[lb]


@EVALUATORS.register("ctc_edit_distance")
class CTCErrorEvaluator(Evaluator):
    """Edit-distance error rate after CTC best-path collapse
    (CTCErrorEvaluator.cpp)."""

    name = "ctc_edit_distance"
    additive = False  # raw argmax paths, collapsed per batch on host

    def __init__(self, blank: int = 0):
        self.blank = blank

    def start(self):
        self.dist = self.total = 0.0

    def batch_stats(self, *, log_probs, labels, in_lengths, label_lengths):
        return {"path": jnp.argmax(log_probs, axis=-1), "labels": labels,
                "in_lengths": in_lengths, "label_lengths": label_lengths}

    def update(self, s):
        paths = np.asarray(s["path"])
        labels = np.asarray(s["labels"])
        in_lens = np.asarray(s["in_lengths"])
        lab_lens = np.asarray(s["label_lengths"])
        for i in range(paths.shape[0]):
            raw = paths[i, : int(in_lens[i])]
            collapsed = []
            prev = None
            for t in raw:
                if t != self.blank and t != prev:
                    collapsed.append(int(t))
                prev = t
            ref = [int(x) for x in labels[i, : int(lab_lens[i])]]
            self.dist += _edit_distance(collapsed, ref)
            self.total += max(len(ref), 1)

    def result(self):
        return self.dist / max(self.total, 1.0)


class _Printer(Evaluator):
    additive = False  # side-effecting: every batch is materialized

    def start(self):
        self.lines: List[str] = []

    def update(self, s):
        self.lines.append(str({k: np.asarray(v) for k, v in s.items()}))

    def result(self):
        return float(len(self.lines))


@EVALUATORS.register("value_printer")
class ValuePrinter(_Printer):
    name = "value_printer"

    def batch_stats(self, *, value):
        return {"value": value}


@EVALUATORS.register("gradient_printer")
class GradientPrinter(_Printer):
    name = "gradient_printer"

    def batch_stats(self, *, grad):
        return {"grad": grad}


@EVALUATORS.register("maxid_printer")
class MaxIdPrinter(_Printer):
    name = "maxid_printer"

    def batch_stats(self, *, logits):
        return {"maxid": jnp.argmax(logits, -1)}


@EVALUATORS.register("maxframe_printer")
class MaxFramePrinter(_Printer):
    name = "maxframe_printer"

    def batch_stats(self, *, value):
        return {"frame": jnp.argmax(jnp.linalg.norm(value, axis=-1), axis=-1)}


# ---------------------------------------------------------------------------
# device-side accumulation
# ---------------------------------------------------------------------------


class DeviceAccumulator:
    """Accumulate an additive evaluator's batch stats ON DEVICE.

    The host-side ``eval_batch`` path pulls every batch's stats to the host —
    a device sync per batch, expensive over a TPU link.  This wrapper keeps
    the running totals in HBM: ``add(**kw)`` dispatches one jitted
    stats-and-add program (async — it does NOT block the host), and only
    ``result()`` syncs, once.  The reference's evaluators accumulate in
    device memory the same way during GPU eval passes
    (paddle/gserver/evaluators/Evaluator.cpp:46-120 totalScore_/numSamples_
    updated from device reductions).

    Usage::

        acc = DeviceAccumulator(ClassificationError())
        for batch in reader():
            out = infer_fn(params, state, batch)        # device arrays
            acc.add(logits=out["logits"], labels=batch["labels"])
        err = acc.result()                              # single host pull
    """

    def __init__(self, evaluator: Evaluator):
        if not evaluator.additive:
            raise ValueError(
                f"evaluator {evaluator.name!r} is not additive; use eval_batch"
            )
        self.evaluator = evaluator
        self._acc: Optional[Dict[str, Any]] = None
        self._jit_add = None

    def add(self, **kw) -> None:
        import jax

        if self._jit_add is None:
            ev = self.evaluator

            def first(**kw):
                return ev.batch_stats(**kw)

            def step(acc, **kw):
                s = ev.batch_stats(**kw)
                return jax.tree_util.tree_map(jnp.add, acc, s)

            self._jit_first = jax.jit(first)
            self._jit_add = jax.jit(step)
        if self._acc is None:
            self._acc = self._jit_first(**kw)
        else:
            self._acc = self._jit_add(self._acc, **kw)

    def result(self) -> float:
        self.evaluator.start()
        if self._acc is not None:
            self.evaluator.update(
                {k: np.asarray(v) for k, v in self._acc.items()}
            )
        return self.evaluator.result()

    def reset(self) -> None:
        self._acc = None

@EVALUATORS.register("seqtext_printer")
class SeqTextPrinter(_Printer):
    """Prints decoded id sequences, optionally mapped through a vocabulary —
    the NMT-generation inspection evaluator (reference:
    trainer_config_helpers/evaluators.py seqtext_printer_evaluator:573,
    gserver/evaluators/Evaluator.cpp sequence text printer)."""

    name = "seqtext_printer"

    def __init__(self, vocab=None, delimiter=" "):
        self.vocab = vocab
        self.delimiter = delimiter

    def batch_stats(self, *, ids):
        return {"ids": ids}

    def _rows(self, ids):
        """Normalize [.., L] arrays, ragged python lists, and scalars to a
        list of flat id rows (generation output is naturally ragged)."""
        if isinstance(ids, (list, tuple)) and ids and isinstance(
                ids[0], (list, tuple, np.ndarray)):
            return [np.asarray(r).ravel() for r in ids]
        arr = np.asarray(ids)
        if arr.ndim == 0:
            return [arr.reshape(1)]
        if arr.ndim == 1:
            return [arr]
        return list(arr.reshape(-1, arr.shape[-1]))

    def update(self, s):
        for row in self._rows(s["ids"]):
            toks = [str(int(t)) if self.vocab is None
                    else str(self.vocab[int(t)]) for t in row]
            self.lines.append(self.delimiter.join(toks))


@EVALUATORS.register("classification_error_printer")
class ClassificationErrorPrinter(_Printer):
    """Prints the per-sample classification error of each batch (reference:
    evaluators.py classification_error_printer_evaluator:663)."""

    name = "classification_error_printer"

    def batch_stats(self, *, logits, labels):
        pred = jnp.argmax(logits, -1)
        lab = labels.reshape(pred.shape)
        return {"err": (pred != lab).astype(jnp.float32)}

    def update(self, s):
        self.lines.append(" ".join(f"{v:g}" for v in np.asarray(s["err"]).ravel()))

