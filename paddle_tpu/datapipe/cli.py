"""``python -m paddle_tpu data {pack|verify}`` — shard-set tooling.

``pack`` drains any reader — a ``module:callable`` spec resolving to a
reader creator (every ``paddle_tpu.data.datasets`` loader qualifies), or
a ``--config CONF.py`` train config whose reader yields batches — into
an atomically-published indexed shard set.  ``verify`` re-hashes an
existing set: manifest file CRCs, the per-shard footer index, and every
record's own CRC; the first failure exits 2 naming the shard file and
record index (the address ``resilience.chaos.corrupt_shard`` damages).
"""

from __future__ import annotations

import argparse
import importlib
import sys
from typing import List, Optional

__all__ = ["run"]


def _resolve_reader(spec: str):
    """``pkg.mod:attr`` (or dotted ``attr.path``) -> reader creator."""
    if ":" not in spec:
        raise SystemExit(
            f"--reader must be 'module:callable', got {spec!r}")
    mod_name, attr = spec.split(":", 1)
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    if not callable(obj):
        raise SystemExit(f"--reader {spec!r} is not callable")
    return obj


def _config_reader(path: str, *, unbatch: bool):
    import runpy

    ns = runpy.run_path(path)
    if "get_config" not in ns:
        raise SystemExit(f"config {path!r} does not define get_config()")
    conf = ns["get_config"]()
    if "reader" not in conf:
        raise SystemExit(f"get_config() in {path!r} returned no 'reader'")
    reader = conf["reader"]
    if not unbatch:
        return reader

    def samples():
        for batch in reader():
            for sample in batch:
                yield sample

    return samples


def run(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu data",
        description="indexed shard-set tooling (docs/data.md)")
    sub = p.add_subparsers(dest="cmd", required=True)

    pk = sub.add_parser("pack", help="build a shard set from a reader")
    pk.add_argument("out", help="output shard-set directory (must not "
                    "exist; published atomically)")
    src = pk.add_mutually_exclusive_group(required=True)
    src.add_argument("--reader", help="module:callable reader creator "
                     "yielding SAMPLES (e.g. "
                     "paddle_tpu.data.datasets:uci_housing.train)")
    src.add_argument("--config", help="train config (get_config()) whose "
                     "batch reader is unbatched into samples")
    pk.add_argument("--shards", type=int, default=None,
                    help="shard count (default: --data_shards)")
    pk.add_argument("--limit", type=int, default=None,
                    help="stop after N samples (smoke packs)")

    vf = sub.add_parser("verify", help="CRC-verify an existing shard set")
    vf.add_argument("root", help="shard-set directory")

    args = p.parse_args(argv)

    from paddle_tpu.datapipe.shards import (ShardDataset, ShardError,
                                            write_shard_set)

    if args.cmd == "pack":
        reader = (_resolve_reader(args.reader) if args.reader
                  else _config_reader(args.config, unbatch=True))
        try:
            manifest = write_shard_set(args.out, reader,
                                       num_shards=args.shards,
                                       limit=args.limit)
        except ShardError as e:
            print(f"pack failed: {e}", file=sys.stderr)
            return 2
        print(f"packed {manifest['num_records']} record(s) into "
              f"{len(manifest['shards'])} shard(s) at {args.out}")
        return 0

    try:
        summary = ShardDataset(args.root).validate()
    except ShardError as e:
        print(f"verify FAILED: {e}", file=sys.stderr)
        return 2
    print(f"verified {summary['records']} record(s) across "
          f"{summary['shards']} shard(s), {summary['bytes']} bytes — OK")
    return 0
