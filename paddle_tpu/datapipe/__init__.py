"""``paddle_tpu.datapipe`` — the deterministic sharded data pipeline.

The production input tier the reference devotes ``gserver/dataproviders``
to (PyDataProvider2 feeding the trainer), rebuilt index-first
(docs/data.md):

- **indexed record shards** (``shards.py``): CRC-per-record files with a
  footer index for O(1) random access, written as atomically-published
  CRC-manifested sets; ``write_shard_set`` packs any ``paddle_tpu.data``
  reader (also ``python -m paddle_tpu data pack``);
- **deterministic global shuffle** (``sampler.py``): a seeded
  permutation over record indices, recomputed — never stored — from
  ``(seed, pass)``, strided per host;
- **checkpointable iteration** (``iterator.py``): ``ShardSource``'s
  entire state is a tiny cursor ``(seed, pass, offset, next_batch)``
  that rides the checkpoint manifest — ``--resume=auto`` restores the
  cursor with ZERO replayed samples, and an elastic resize re-splits the
  same permutation with no duplicated or dropped sample;
- **sequence packing** (``packing.py``): multiple short sequences share
  one padded row with segment ids / position offsets plumbed through
  masking, the RNN carries, and the sequence losses (``--data_pack``).
"""

from paddle_tpu.datapipe.iterator import ShardSource, is_checkpointable_source
from paddle_tpu.datapipe.packing import (PackedDataFeeder, auto_pack,
                                         pack_reader, pack_samples)
from paddle_tpu.datapipe.sampler import (pass_permutation, pass_rng_word,
                                         split_positions)
from paddle_tpu.datapipe.shards import (ShardCorruptError, ShardDataset,
                                        ShardError, ShardReader, ShardWriter,
                                        write_shard_set)

__all__ = [
    "ShardWriter",
    "ShardReader",
    "ShardDataset",
    "ShardError",
    "ShardCorruptError",
    "write_shard_set",
    "pass_permutation",
    "pass_rng_word",
    "split_positions",
    "ShardSource",
    "is_checkpointable_source",
    "pack_samples",
    "pack_reader",
    "PackedDataFeeder",
    "auto_pack",
]
