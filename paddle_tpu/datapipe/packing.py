"""Sequence packing — multiple short sequences share one padded row
(docs/data.md "Sequence packing"; ``--data_pack``).

The bucketed feeder bounds pad waste per batch, but a pad-heavy
workload (IMDB-style length distributions) still burns most of the
``[B, T]`` grid on dead tokens — the exact waste keeping the textclf /
LSTM bench rows MFU-starved (ROADMAP item 3).  Packing fills each row
with several whole sequences back-to-back and plumbs the segment
structure through the graph:

- the packed seq slot feeds a 5-tuple ``(ids [B,T], lengths [B],
  seg_ids [B,T], positions [B,T], seg_lengths [B,S])`` —
  ``nn.graph._coerce_feed`` turns it into a sequence ``Act`` carrying
  the pack state;
- recurrent layers RESET their carry at segment starts (direction-aware
  — ``ops.segment_starts``), pooling/last/first become per-SEGMENT
  reductions returning a sequence over the segment axis, and the
  sequence losses then reduce over valid segments — so the packed batch
  computes exactly the per-sample math of the unpacked one (the
  bit-parity oracle in tests/test_datapipe.py);
- per-sample slots (the label) feed as ``[B, S]``.

The packer is greedy-in-order (first sequence that does not fit closes
the row): deterministic, order-preserving, and O(1) state — it composes
with the checkpointable ``ShardSource`` cursor.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, \
    Tuple

import numpy as np

from paddle_tpu.data.feeder import DataFeeder, bucket_length, note_padding
from paddle_tpu.utils.error import ConfigError

__all__ = ["pack_samples", "pack_reader", "PackedDataFeeder", "auto_pack",
           "DEFAULT_PACK_LEN", "DEFAULT_PACK_SEGMENTS"]

DEFAULT_PACK_LEN = 256
DEFAULT_PACK_SEGMENTS = 8

#: a packed row: the sequences it holds (in arrival order) and, per
#: segment, the sample's remaining slots (original tuple minus the seq)
PackedRow = Tuple[List[List[int]], List[Tuple]]


def pack_reader(reader: Callable[[], Iterator[Tuple]], *, max_len: int,
                max_segments: int = DEFAULT_PACK_SEGMENTS,
                seq_slot: int = 0) -> Callable[[], Iterator[PackedRow]]:
    """Greedy in-order streaming packer: walk the samples once, appending
    each to the open row until its tokens would overflow ``max_len`` or
    the row already holds ``max_segments`` segments — then the row
    closes.  A single sequence longer than ``max_len`` is truncated to
    it (the feeder ``max_len`` semantics).  Deterministic and
    order-preserving: the concatenation of all segments equals the input
    sample order."""
    if max_len < 1 or max_segments < 1:
        raise ValueError("max_len and max_segments must be >= 1")

    def creator() -> Iterator[PackedRow]:
        seqs: List[List[int]] = []
        rest: List[Tuple] = []
        used = 0
        for sample in reader():
            seq = list(sample[seq_slot])[:max_len]
            other = tuple(v for i, v in enumerate(sample) if i != seq_slot)
            if seqs and (used + len(seq) > max_len
                         or len(seqs) >= max_segments):
                yield seqs, rest
                seqs, rest, used = [], [], 0
            seqs.append(seq)
            rest.append(other)
            used += len(seq)
        if seqs:
            yield seqs, rest

    return creator


def pack_samples(samples: Sequence[Tuple], *, max_len: int,
                 max_segments: int = DEFAULT_PACK_SEGMENTS,
                 seq_slot: int = 0) -> List[PackedRow]:
    """List form of :func:`pack_reader` — ONE packing policy, two call
    shapes (the streamed and listed packers can never disagree)."""
    return list(pack_reader(lambda: iter(samples), max_len=max_len,
                            max_segments=max_segments,
                            seq_slot=seq_slot)())


class PackedDataFeeder:
    """Packed rows -> feed dicts (the packed half of ``DataFeeder``).

    ``types`` uses the DataFeeder kinds with exactly ONE ``ids_seq``
    slot (the packed axis); every other slot must be per-sample
    ``int`` (fed ``[B, S]``) or ``dense`` (fed ``[B, S, D]``).  The seq
    slot feeds the packed 5-tuple; ``S`` is the static
    ``max_segments`` so XLA sees one shape per (T-bucket) regardless of
    how full each row is."""

    def __init__(self, types: Dict[str, str],
                 feeding: Optional[Dict[str, int]] = None, *,
                 max_segments: int = DEFAULT_PACK_SEGMENTS,
                 buckets: Sequence[int] = None,
                 dtype: str = "float32") -> None:
        from paddle_tpu.data.feeder import _DEFAULT_BUCKETS

        self.types = dict(types)
        self.feeding = feeding or {n: i for i, n in enumerate(types)}
        self.max_segments = int(max_segments)
        self.buckets = tuple(buckets or _DEFAULT_BUCKETS)
        self.dtype = dtype
        seq = [n for n, k in types.items() if k in ("ids_seq", "dense_seq")]
        if len(seq) != 1 or types[seq[0]] != "ids_seq":
            raise ConfigError(
                f"PackedDataFeeder needs exactly one 'ids_seq' slot to "
                f"pack, got {types}")
        self.seq_name = seq[0]
        for n, k in types.items():
            if n != self.seq_name and k not in ("int", "dense"):
                raise ConfigError(
                    f"PackedDataFeeder slot {n!r}: kind {k!r} is not "
                    f"packable (per-sample slots must be 'int' or "
                    f"'dense')")
        # per-sample slot order: feeding indices minus the seq slot,
        # re-based to the packed row's ``rest`` tuples
        seq_idx = self.feeding[self.seq_name]
        self._rest_index = {
            n: (i if i < seq_idx else i - 1)
            for n, i in self.feeding.items() if n != self.seq_name}
        #: cumulative pad accounting (shares the registry gauges with
        #: DataFeeder — the packed-vs-bucketed A/B reads one metric)
        self.tokens_real = 0
        self.tokens_padded = 0

    @classmethod
    def from_feeder(cls, feeder: DataFeeder, *,
                    max_segments: int = DEFAULT_PACK_SEGMENTS
                    ) -> "PackedDataFeeder":
        return cls(feeder.types, feeder.feeding,
                   max_segments=max_segments, buckets=feeder.buckets,
                   dtype=feeder.dtype)

    @property
    def pad_waste(self) -> float:
        """Cumulative padded-but-dead token fraction."""
        if not self.tokens_padded:
            return 0.0
        return 1.0 - self.tokens_real / self.tokens_padded

    def __call__(self, rows: List[PackedRow]) -> Dict[str, Any]:
        B, S = len(rows), self.max_segments
        tok = 1
        for seqs, rest in rows:
            if len(seqs) > S:
                raise ConfigError(
                    f"packed row holds {len(seqs)} segments but "
                    f"max_segments={S} — pack and feed must agree")
            tok = max(tok, sum(len(s) for s in seqs))
        T = bucket_length(tok, self.buckets)
        ids = np.zeros((B, T), np.int32)
        seg_ids = np.full((B, T), -1, np.int32)
        positions = np.zeros((B, T), np.int32)
        lengths = np.zeros((B,), np.int32)
        seg_lengths = np.zeros((B, S), np.int32)
        for b, (seqs, rest) in enumerate(rows):
            t = 0
            for s, seq in enumerate(seqs):
                L = len(seq)
                ids[b, t:t + L] = seq
                seg_ids[b, t:t + L] = s
                positions[b, t:t + L] = np.arange(L, dtype=np.int32)
                seg_lengths[b, s] = L
                t += L
            lengths[b] = t
        self.tokens_real += int(lengths.sum())
        self.tokens_padded += B * T
        note_padding(int(lengths.sum()), B * T, T,
                     waste=self.pad_waste)
        feed: Dict[str, Any] = {
            self.seq_name: (ids, lengths, seg_ids, positions, seg_lengths)}
        for name, kind in self.types.items():
            if name == self.seq_name:
                continue
            ri = self._rest_index[name]
            if kind == "int":
                out = np.zeros((B, S), np.int32)
                for b, (seqs, rest) in enumerate(rows):
                    for s, other in enumerate(rest):
                        v = other[ri]
                        out[b, s] = int(v[0] if isinstance(
                            v, (list, tuple, np.ndarray)) else v)
            else:  # dense
                D = None
                for seqs, rest in rows:
                    if rest:
                        D = len(np.atleast_1d(rest[0][ri]))
                        break
                out = np.zeros((B, S, D or 1), self.dtype)
                for b, (seqs, rest) in enumerate(rows):
                    for s, other in enumerate(rest):
                        out[b, s] = np.asarray(other[ri], self.dtype)
            feed[name] = out
        return feed


def auto_pack(reader: Callable, feeder: DataFeeder, *,
              batch_size: Optional[int] = None,
              max_len: Optional[int] = None,
              max_segments: int = DEFAULT_PACK_SEGMENTS
              ) -> Tuple[Callable, PackedDataFeeder]:
    """The ``--data_pack`` wiring (CLI train job): re-plumb a
    batch-reader + DataFeeder pair into the packed pipeline.  The
    incoming reader's batches are flattened back to samples, packed,
    and re-batched at ``batch_size`` ROWS — default: the source batch
    size (a cursor source's ``batch_size`` attribute, else peeked from
    a fresh ``reader()`` call — safe for the repo's re-invocable reader
    creators; a stateful source without the attribute should pass
    ``batch_size`` explicitly), so a packed step keeps the same row
    count and processes >= as many SAMPLES per batch.  ``max_len``
    defaults to the feeder's own truncation cap when it has one (packed
    and bucketed training must truncate identically), else
    ``DEFAULT_PACK_LEN`` — packing always needs a finite row budget."""
    from paddle_tpu.utils import logger

    pf = PackedDataFeeder.from_feeder(feeder, max_segments=max_segments)
    seq_idx = feeder.feeding[pf.seq_name]
    if max_len is None:
        cap = getattr(feeder, "max_len", None)
        max_len = int(cap or DEFAULT_PACK_LEN)
        if not cap:
            # the bucketed path fed uncapped sequences whole; packing
            # needs a finite row budget — make the new truncation loud
            logger.warning(
                "--data_pack: the feeder has no max_len — sequences "
                "longer than %d tokens will be TRUNCATED to the packed "
                "row budget (pass max_len= to auto_pack, or set the "
                "feeder's max_len, to choose the cap)", max_len)
    if batch_size is None:
        # a checkpointable source advances its cursor when iterated — read
        # its declared batch size instead of consuming a batch
        batch_size = getattr(reader, "batch_size", None)
    if batch_size is None:
        try:
            batch_size = len(next(iter(reader())))
        except StopIteration:
            batch_size = 64
    bs = int(batch_size)

    def sample_stream() -> Iterator[Tuple]:
        for batch in reader():
            for sample in batch:
                yield sample

    packed = pack_reader(sample_stream, max_len=max_len,
                         max_segments=max_segments, seq_slot=seq_idx)

    def creator():
        rows: List[PackedRow] = []
        for row in packed():
            rows.append(row)
            if len(rows) >= bs:
                yield rows
                rows = []
        if rows:
            yield rows

    return creator, pf
