"""Deterministic global shuffle — a seeded permutation over record
indices, split per host (docs/data.md "Determinism contract").

The whole shuffle STATE is the tiny tuple ``(seed, pass)``: the
permutation itself is recomputed on demand from a ``numpy``
``SeedSequence([seed, pass_id])`` stream, never stored — which is what
makes the iterator cursor O(1) (datapipe/iterator.py) instead of an
O(dataset) shuffle-buffer snapshot.

Host split: rank ``r`` of ``W`` reads the permutation positions
``p >= offset`` with ``(p - offset) % W == r`` — a strided split of ONE
global sequence.  Because SPMD training consumes the same number of
batches on every rank, the globally-consumed prefix after ``k`` batches
of per-rank size ``B`` is exactly ``offset + k*B*W`` positions — so an
elastic resize at a batch boundary re-splits the SAME permutation from
that offset under the new world size with no duplicated and no dropped
sample (pinned by tests/test_datapipe.py).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

__all__ = ["pass_permutation", "split_positions", "pass_rng_word"]


def pass_permutation(n: int, seed: int, pass_id: int,
                     shuffle: bool = True) -> np.ndarray:
    """The global record order of one pass: a permutation of
    ``arange(n)`` drawn from ``SeedSequence([seed, pass_id])`` (each pass
    reshuffles deterministically), or plain ``arange`` with
    ``shuffle=False``."""
    if not shuffle:
        return np.arange(n, dtype=np.int64)
    rng = np.random.default_rng(np.random.SeedSequence([int(seed),
                                                        int(pass_id)]))
    return rng.permutation(n)


def pass_rng_word(seed: int, pass_id: int) -> int:
    """One deterministic 32-bit word per (seed, pass) — the cursor's
    ``rng`` field, available to sample-level augmentation randomness so
    a restored iterator continues the exact random stream."""
    ss = np.random.SeedSequence([int(seed), int(pass_id)])
    return int(ss.generate_state(1, dtype=np.uint32)[0])


def split_positions(n: int, offset: int, world: int,
                    index: int) -> Iterator[int]:
    """Permutation positions owned by rank ``index`` of ``world`` from
    global ``offset``: ``offset + index, offset + index + world, ...``
    (strictly below ``n``).  The union over ranks is exactly
    ``[offset, n)`` — every position once."""
    if not 0 <= index < world:
        raise ValueError(f"rank index {index} out of world {world}")
    return iter(range(int(offset) + int(index), int(n), int(world)))
