"""Checkpointable shard iterator — O(1)-state resume for the input
pipeline (docs/data.md "Resume and resize").

``ShardSource`` is a reader *creator* (the ``paddle_tpu.data`` reader
protocol: calling it yields batches), plus four capabilities the trainer
duck-types on (``trainer/trainer.py``):

- ``cursor_for(pass_id, next_batch)`` — the tiny JSON cursor
  ``{seed, pass, offset, next_batch, world, rng}`` describing the state
  of the pipeline after ``next_batch`` batches of ``pass_id`` have been
  *stepped*.  Rides every checkpoint manifest (``meta["data_cursor"]``).
  Computed ARITHMETICALLY from the stepped-batch count, so prefetcher
  read-ahead can never leak into a checkpoint.
- ``restore(cursor)`` — point the source at a saved cursor;
  ``--resume=auto`` then re-enters the pass with ZERO replayed samples
  (the trainer's re-read-and-discard fast-forward survives only as the
  fallback for plain readers).
- ``seek(pass_id)`` — align to the trainer's pass loop (no-op when
  already there; rewinds/advances to the pass boundary otherwise).
- ``reshard(world, index, pass_id=..., next_batch=...)`` — adopt a new
  world split mid-pass at a batch boundary: the globally-consumed prefix
  ``offset`` is fixed under the OLD world, then the SAME permutation is
  re-split from it under the new one — no sample duplicated, none
  dropped (the elastic ``ev.Resize`` contract; see datapipe/sampler.py).

Corrupt records raise a typed :class:`~paddle_tpu.datapipe.shards
.ShardCorruptError` naming shard file + record index; with
``skip_corrupt=True`` they are skipped and counted in
``dropped_records`` (mirrored into the trainer's ``_last_extras``), the
batch simply coming up short — data loss is surfaced, never silent.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

from paddle_tpu.datapipe.sampler import (pass_permutation, pass_rng_word,
                                         split_positions)
from paddle_tpu.datapipe.shards import ShardCorruptError, ShardDataset
from paddle_tpu.utils import logger

__all__ = ["ShardSource", "is_checkpointable_source"]


def is_checkpointable_source(reader: Any) -> bool:
    """The trainer's duck-type: a reader creator whose mid-pass state is
    a restorable cursor (ShardSource or anything matching its surface)."""
    return all(callable(getattr(reader, m, None))
               for m in ("cursor_for", "restore", "seek"))


class ShardSource:
    """Deterministic, checkpointable batch source over a shard set.

    ``world``/``index`` split the seeded permutation per host
    (sampler.split_positions); the default ``(1, 0)`` reads everything —
    the right setting for replica-style gangs (the CPU test harness) and
    single-process SPMD, where ONE process feeds the global batch.  Pass
    ``shard_by_gang=True`` to let the trainer bind the split to the live
    gang (and re-bind it on elastic resizes).

    ``transform`` maps each decoded sample before batching (tokenize,
    reshape) — host-side, deterministic functions only.
    """

    def __init__(self, dataset: Union[str, ShardDataset], *,
                 batch_size: int,
                 seed: Optional[int] = None,
                 shuffle: bool = True,
                 world: int = 1,
                 index: int = 0,
                 shard_by_gang: bool = False,
                 skip_corrupt: bool = False,
                 transform: Optional[Callable[[Any], Any]] = None) -> None:
        from paddle_tpu.utils.flags import FLAGS

        self.dataset = (ShardDataset(dataset) if isinstance(dataset, str)
                        else dataset)
        self.batch_size = int(batch_size)
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.seed = int(FLAGS.shuffle_seed if seed is None else seed)
        self.shuffle = bool(shuffle)
        self.shard_by_gang = bool(shard_by_gang)
        self.skip_corrupt = bool(skip_corrupt)
        self.transform = transform
        #: corrupt records skipped under ``skip_corrupt`` (surfaced in the
        #: trainer's ``_last_extras['dropped_records']``)
        self.dropped_records = 0
        self._world = int(world)
        self._index = int(index)
        if not 0 <= self._index < self._world:
            raise ValueError(f"index {index} out of world {world}")
        # cursor: the pass, the globally-consumed offset at batch
        # ``_batch_base``, and the live count of batches yielded this
        # pass (read-ahead included — checkpoints use cursor_for, which
        # takes the STEPPED count from the trainer instead)
        self._pass = 0
        self._offset_base = 0
        self._batch_base = 0
        self._next_batch = 0
        # the just-rolled-over pass's bases (pass, offset_base,
        # batch_base): prefetch read-ahead can exhaust the generator —
        # rolling the cursor to pass+1 — while the trainer still steps
        # the tail of pass p; cursor_for/reshard for THAT pass answer
        # from this stash instead of failing (docs/data.md)
        self._prev: Optional[tuple] = None
        self._perm_key: Optional[tuple] = None
        self._perm: Optional[np.ndarray] = None

    # -- cursor protocol -------------------------------------------------

    @property
    def world(self) -> int:
        return self._world

    @property
    def index(self) -> int:
        return self._index

    @property
    def pass_id(self) -> int:
        return self._pass

    def _offset_at(self, next_batch: int) -> int:
        return (self._offset_base
                + (int(next_batch) - self._batch_base)
                * self.batch_size * self._world)

    def cursor_for(self, pass_id: int, next_batch: int) -> Dict[str, Any]:
        """The durable cursor after ``next_batch`` STEPPED batches of
        ``pass_id`` — O(1) arithmetic off the stepped count, immune to
        prefetch read-ahead."""
        pass_id, next_batch = int(pass_id), int(next_batch)
        if pass_id == self._pass:
            offset = self._offset_at(next_batch)
        elif (self._prev is not None and pass_id == self._prev[0]
              and self._pass == pass_id + 1):
            # read-ahead already rolled the cursor past this pass's end
            # while the trainer still steps its tail (e.g. a preemption
            # checkpoint with --prefetch_depth): answer from the stashed
            # bases so the manifest never loses the cursor
            _, ob, bb = self._prev
            offset = ob + (next_batch - bb) * self.batch_size * self._world
        elif next_batch == 0:
            # a pass boundary the source has not rolled onto yet (or an
            # end-of-pass save asked after rollover — handled above)
            offset = 0
        else:
            raise ValueError(
                f"cursor_for(pass={pass_id}, next_batch={next_batch}) "
                f"disagrees with the source's pass {self._pass}")
        return {"seed": self.seed, "pass": pass_id, "offset": int(offset),
                "next_batch": next_batch, "world": self._world,
                "rng": pass_rng_word(self.seed, pass_id)}

    def state(self) -> Dict[str, Any]:
        """The LIVE cursor (batches yielded, read-ahead included).
        Checkpoints should prefer ``cursor_for`` with the stepped count."""
        return self.cursor_for(self._pass, self._next_batch)

    def restore(self, cursor: Dict[str, Any]) -> None:
        """Adopt a saved cursor: the next batch read is the one the
        checkpoint recorded as next — zero replayed samples."""
        seed = int(cursor["seed"])
        if seed != self.seed:
            logger.warning(
                "ShardSource.restore: cursor seed %d overrides configured "
                "seed %d (the saved permutation defines the data order)",
                seed, self.seed)
            self.seed = seed
        self._pass = int(cursor["pass"])
        self._offset_base = int(cursor["offset"])
        self._batch_base = int(cursor.get("next_batch", 0))
        self._next_batch = self._batch_base
        self._prev = None
        self._perm = self._perm_key = None

    def seek(self, pass_id: int) -> None:
        """Align to the trainer's pass loop: entering a different pass
        resets the cursor to that pass's start."""
        if int(pass_id) != self._pass:
            self._pass = int(pass_id)
            self._offset_base = self._batch_base = self._next_batch = 0
            self._prev = None
            self._perm = self._perm_key = None

    def _unroll_to(self, pass_id: int, next_batch: int) -> bool:
        """Un-roll a read-ahead pass rollover: point the cursor back at
        ``pass_id`` with its stashed bases (the trainer is still mid-pass
        there).  Returns True when the stash applied."""
        if (self._prev is not None and int(pass_id) == self._prev[0]
                and self._pass == int(pass_id) + 1):
            self._pass, self._offset_base, self._batch_base = (
                self._prev[0], self._prev[1], self._prev[2])
            self._next_batch = int(next_batch)
            self._prev = None
            self._perm = self._perm_key = None
            return True
        return False

    def reshard(self, world: int, index: int, *, pass_id: int,
                next_batch: int) -> None:
        """Re-split the SAME permutation under a new world at a batch
        boundary: fix the globally-consumed offset under the OLD world,
        then stride from it with the new one.  ``next_batch`` is the
        stepped-batch count (prefetched-but-unstepped batches must be
        discarded by the caller — the trainer closes its prefetcher and
        re-creates the pass iterator).  A read-ahead rollover past the
        pass end is un-rolled first, so the offset is never recomputed
        from zeroed bases mid-pass."""
        world, index = int(world), int(index)
        if not 0 <= index < world:
            raise ValueError(f"index {index} out of world {world}")
        if not self._unroll_to(pass_id, next_batch):
            self.seek(pass_id)
        offset = self._offset_at(next_batch)
        self._world, self._index = world, index
        self._offset_base = offset
        self._batch_base = self._next_batch = int(next_batch)

    def bind_world(self, world: int, index: int) -> None:
        """Initial world binding (train start) — positionally identical
        to a reshard at the current cursor."""
        self.reshard(world, index, pass_id=self._pass,
                     next_batch=self._next_batch)

    # -- iteration -------------------------------------------------------

    def _permutation(self) -> np.ndarray:
        key = (self.seed, self._pass, len(self.dataset), self.shuffle)
        if self._perm is None or self._perm_key != key:
            self._perm = pass_permutation(len(self.dataset), self.seed,
                                          self._pass, shuffle=self.shuffle)
            self._perm_key = key
        return self._perm

    def batches_remaining(self) -> int:
        """Full per-rank batches left in the current pass (every rank
        agrees: the global window is ``batch_size * world`` samples)."""
        n = len(self.dataset)
        consumed = self._offset_at(self._next_batch)
        return max(0, (n - consumed) // (self.batch_size * self._world))

    def _read_batch(self, start: int, perm: np.ndarray) -> List[Any]:
        rows: List[Any] = []
        last_err = None
        for pos in split_positions(
                min(start + self.batch_size * self._world, len(perm)),
                start, self._world, self._index):
            try:
                sample = self.dataset.read(int(perm[pos]))
            except ShardCorruptError as e:
                if not self.skip_corrupt:
                    raise
                self.dropped_records += 1
                last_err = e
                logger.warning(
                    "ShardSource: dropped corrupt record (%s; %d dropped "
                    "total)", e, self.dropped_records)
                continue
            rows.append(self.transform(sample) if self.transform else sample)
        if not rows and last_err is not None:
            # EVERY record of the window was corrupt: yielding nothing
            # while still consuming the window would desync the
            # trainer's stepped-batch count from the cursor arithmetic
            # (a later resume/resize would re-train consumed samples) —
            # total corruption fails loudly instead
            raise ShardCorruptError(
                f"every record in the batch window at offset {start} is "
                f"corrupt ({self.dropped_records} dropped total; last: "
                f"{last_err})", path=last_err.path, record=last_err.record)
        return rows

    def __call__(self) -> Iterator[List[Any]]:
        """One pass of batches from the current cursor.  Natural
        exhaustion rolls the cursor to ``(pass+1, offset 0)``; abandoning
        the iterator mid-pass (preemption, resize) leaves the cursor
        restorable.  Reads live state every batch, so a ``reshard``
        between batches takes effect without rebuilding the iterator."""
        entered_pass = self._pass
        while True:
            if self._pass != entered_pass:
                return  # seek/restore moved the cursor under us
            perm = self._permutation()
            nb = self._next_batch
            start = self._offset_at(nb)
            if start + self.batch_size * self._world > len(perm):
                # end of pass: roll the cursor to the next pass boundary,
                # stashing this pass's bases — a prefetching trainer is
                # still STEPPING this pass's tail, and its checkpoints/
                # reshards must keep answering for it (cursor_for/
                # _unroll_to)
                self._prev = (self._pass, self._offset_base,
                              self._batch_base)
                self._pass += 1
                self._offset_base = self._batch_base = self._next_batch = 0
                self._perm = self._perm_key = None
                return
            rows = self._read_batch(start, perm)
            self._next_batch = nb + 1
            yield rows

    def close(self) -> None:
        self.dataset.close()
