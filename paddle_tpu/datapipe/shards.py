"""Indexed record shards — the on-disk tier of the deterministic data
pipeline (docs/data.md).

The reference's data tier streams Python generators (PyDataProvider2);
production input pipelines need an *addressable* on-disk format so a
seeded permutation over record indices — not the accident of stream
order — defines what each host reads (the tf.data / Grain index-based
determinism model).  A shard file is:

    header   : b"PTSH" + u32 version
    records  : (u32 payload_len, u32 crc32(payload), payload) ...
    index    : u64 little-endian offset per record (offset of its
               length word)
    footer   : u32 crc32(index bytes), u64 index_offset,
               u64 record_count, b"PTSX"     (fixed 24 bytes)

The fixed-size footer makes open O(1): seek to EOF-24, read the index,
and every record is one ``seek`` away (``ShardReader.read(i)``).  Every
record carries its own CRC, so corruption is detected at the exact
record — a failed check raises :class:`ShardCorruptError` naming the
shard file and record index (the chaos model: ``resilience.chaos
.corrupt_shard`` / ``truncate_shard``).

A *shard set* is a directory of ``shard-%05d-of-%05d.ptshard`` files
plus a ``manifest.json`` recording per-shard record counts, byte sizes
and whole-file CRCs.  Sets are written atomically with the same
temp-dir + fsync + rename discipline as ``resilience/checkpoint_io``:
a killed ``pack`` never leaves a half-set a reader would trust.

Payloads are pickled Python samples (protocol 4) — the same row tuples
every ``paddle_tpu.data`` reader yields, so ``write_shard_set`` (the
``pack`` step, also ``python -m paddle_tpu data pack``) converts any
existing reader into shards without a schema.
"""

from __future__ import annotations

import json
import os
import pickle
import struct
import time
import uuid
import zlib
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from paddle_tpu.resilience.errors import ReaderError
from paddle_tpu.utils import logger

__all__ = [
    "SHARD_VERSION",
    "ShardError",
    "ShardCorruptError",
    "ShardWriter",
    "ShardReader",
    "ShardDataset",
    "write_shard_set",
    "shard_name",
]

SHARD_VERSION = 1
_MAGIC = b"PTSH"
_FOOT_MAGIC = b"PTSX"
_HEADER = struct.Struct("<4sI")          # magic, version
_REC_HEAD = struct.Struct("<II")         # payload_len, crc32
_FOOTER = struct.Struct("<IQQ4s")        # index_crc, index_off, count, magic

_TMP_PREFIX = ".tmp-"


class ShardError(ReaderError):
    """A shard file or shard-set manifest is structurally unusable
    (missing, wrong magic, bad footer).  Subclasses ``ReaderError`` so the
    trainer attributes shard failures to the data tier."""


class ShardCorruptError(ShardError):
    """A specific record (or the index) failed its CRC.  ``path`` names
    the shard file; ``record`` is the record index within it (None for
    index/footer corruption) — the exact address a repair job needs."""

    def __init__(self, message: str, *, path: str,
                 record: Optional[int] = None) -> None:
        super().__init__(message)
        self.path = path
        self.record = record


def shard_name(i: int, n: int) -> str:
    return f"shard-{i:05d}-of-{n:05d}.ptshard"


def _obs_counters():
    from paddle_tpu.obs import get_registry

    reg = get_registry()
    return (reg.counter("data_shard_records_total",
                        "records decoded from shard files"),
            reg.counter("data_shard_read_bytes_total",
                        "payload bytes read from shard files"))


class ShardWriter:
    """Append records to one shard file; ``close()`` writes the index +
    footer.  Tracks a running whole-file CRC for the set manifest."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, "wb")
        self._offsets: List[int] = []
        self._crc = 0
        self._write(_HEADER.pack(_MAGIC, SHARD_VERSION))

    def _write(self, b: bytes) -> None:
        self._crc = zlib.crc32(b, self._crc)
        self._f.write(b)

    def append(self, sample: Any) -> int:
        """Write one record; returns its index within this shard."""
        payload = pickle.dumps(sample, protocol=4)
        self._offsets.append(self._f.tell())
        self._write(_REC_HEAD.pack(len(payload), zlib.crc32(payload)))
        self._write(payload)
        return len(self._offsets) - 1

    @property
    def records(self) -> int:
        return len(self._offsets)

    def close(self) -> Dict[str, Any]:
        """Finalize: index + footer, fsync.  Returns the manifest entry
        (file CRC covers everything INCLUDING the footer)."""
        index_off = self._f.tell()
        index = np.asarray(self._offsets, dtype="<u8").tobytes()
        self._write(index)
        self._write(_FOOTER.pack(zlib.crc32(index), index_off,
                                 len(self._offsets), _FOOT_MAGIC))
        self._f.flush()
        os.fsync(self._f.fileno())
        size = self._f.tell()
        self._f.close()
        return {"file": os.path.basename(self.path),
                "records": len(self._offsets),
                "bytes": size, "crc32": self._crc}

    def __enter__(self) -> "ShardWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ShardReader:
    """O(1) random access over one shard file.

    Opening reads only the footer + index (CRC-validated); ``read(i)``
    seeks straight to record ``i`` and validates its per-record CRC —
    a mismatch raises :class:`ShardCorruptError` naming this file and
    the record index.  Read volume lands on the ``data_shard_*``
    registry counters (docs/observability.md)."""

    def __init__(self, path: str) -> None:
        self.path = path
        try:
            self._f = open(path, "rb")
        except OSError as e:
            raise ShardError(f"cannot open shard {path!r}: {e}") from e
        try:
            head = self._f.read(_HEADER.size)
            if len(head) < _HEADER.size or \
                    _HEADER.unpack(head)[0] != _MAGIC:
                raise ShardCorruptError(
                    f"shard {path!r}: bad header magic", path=path)
            self._f.seek(0, os.SEEK_END)
            end = self._f.tell()
            if end < _HEADER.size + _FOOTER.size:
                raise ShardCorruptError(
                    f"shard {path!r}: truncated below footer size",
                    path=path)
            self._f.seek(end - _FOOTER.size)
            icrc, ioff, count, magic = _FOOTER.unpack(
                self._f.read(_FOOTER.size))
            if magic != _FOOT_MAGIC:
                raise ShardCorruptError(
                    f"shard {path!r}: bad footer magic (truncated or "
                    f"overwritten tail)", path=path)
            self._f.seek(ioff)
            index = self._f.read(count * 8)
            if len(index) != count * 8 or zlib.crc32(index) != icrc:
                raise ShardCorruptError(
                    f"shard {path!r}: record index failed CRC", path=path)
            self._offsets = np.frombuffer(index, dtype="<u8")
        except Exception:
            self._f.close()
            raise
        self._records_c, self._bytes_c = _obs_counters()

    def __len__(self) -> int:
        return int(self._offsets.shape[0])

    def read(self, i: int) -> Any:
        """Decode record ``i``; CRC-verified."""
        if not 0 <= i < len(self):
            raise IndexError(f"record {i} out of range for shard "
                             f"{self.path!r} ({len(self)} records)")
        self._f.seek(int(self._offsets[i]))
        head = self._f.read(_REC_HEAD.size)
        if len(head) < _REC_HEAD.size:
            raise ShardCorruptError(
                f"shard {self.path!r} record {i}: truncated header",
                path=self.path, record=i)
        ln, crc = _REC_HEAD.unpack(head)
        payload = self._f.read(ln)
        if len(payload) != ln or zlib.crc32(payload) != crc:
            raise ShardCorruptError(
                f"shard {self.path!r} record {i}: payload failed CRC",
                path=self.path, record=i)
        self._records_c.inc()
        self._bytes_c.inc(ln)
        try:
            return pickle.loads(payload)
        except Exception as e:
            raise ShardCorruptError(
                f"shard {self.path!r} record {i}: undecodable payload "
                f"({type(e).__name__}: {e})", path=self.path, record=i) from e

    def __iter__(self) -> Iterator[Any]:
        for i in range(len(self)):
            yield self.read(i)

    def close(self) -> None:
        self._f.close()


class ShardDataset:
    """A shard SET: the manifest + lazily-opened readers, addressed by
    GLOBAL record index (0..num_records) — the domain the deterministic
    sampler permutes (datapipe/sampler.py)."""

    def __init__(self, root: str) -> None:
        self.root = root
        mpath = os.path.join(root, "manifest.json")
        try:
            with open(mpath) as f:
                self.manifest = json.load(f)
        except OSError as e:
            raise ShardError(f"no shard manifest at {mpath!r}: {e}") from e
        except ValueError as e:
            raise ShardCorruptError(
                f"shard manifest {mpath!r} is not valid JSON: {e}",
                path=mpath) from e
        self.shards = list(self.manifest.get("shards", []))
        if not self.shards:
            raise ShardError(f"shard set {root!r} lists no shards")
        counts = [int(s["records"]) for s in self.shards]
        # global record index == ORIGINAL stream position: pack writes
        # round-robin, so sample g lives at (shard g % S, local g // S);
        # "concat" layout (externally-built sets) falls back to cumsum
        self.layout = self.manifest.get("layout", "concat")
        self._counts = counts
        self._cum = np.concatenate([[0], np.cumsum(counts)])
        self._readers: Dict[int, ShardReader] = {}
        #: injectable per-read delay — the chaos.slow_shard hook
        self._read_delay = 0.0

    def __len__(self) -> int:
        return int(self._cum[-1])

    def shard_path(self, i: int) -> str:
        return os.path.join(self.root, self.shards[i]["file"])

    def _reader(self, i: int) -> ShardReader:
        r = self._readers.get(i)
        if r is None:
            r = self._readers[i] = ShardReader(self.shard_path(i))
            if len(r) != int(self.shards[i]["records"]):
                raise ShardCorruptError(
                    f"shard {r.path!r}: index holds {len(r)} records, "
                    f"manifest says {self.shards[i]['records']}",
                    path=r.path)
        return r

    def locate(self, g: int) -> tuple:
        """Global record index (= original stream position for
        round-robin-packed sets) -> (shard_index, local_index)."""
        if not 0 <= g < len(self):
            raise IndexError(f"global record {g} out of range "
                             f"({len(self)} records)")
        if self.layout == "round_robin":
            n = len(self.shards)
            return g % n, g // n
        s = int(np.searchsorted(self._cum, g, side="right")) - 1
        return s, g - int(self._cum[s])

    def read(self, g: int) -> Any:
        if self._read_delay:
            time.sleep(self._read_delay)
        s, i = self.locate(g)
        return self._reader(s).read(i)

    def close(self) -> None:
        for r in self._readers.values():
            r.close()
        self._readers.clear()

    def validate(self) -> Dict[str, Any]:
        """Full verification (``python -m paddle_tpu data verify``):
        whole-file CRCs against the manifest, then every record's own
        CRC through a real decode.  Raises the FIRST failure as a typed
        :class:`ShardCorruptError` naming shard file and record index;
        returns a summary dict on success."""
        total_bytes = 0
        for i, entry in enumerate(self.shards):
            path = self.shard_path(i)
            crc = 0
            try:
                with open(path, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        crc = zlib.crc32(chunk, crc)
                size = os.path.getsize(path)
            except OSError as e:
                raise ShardError(f"shard {path!r}: unreadable: {e}") from e
            if size != int(entry["bytes"]) or crc != int(entry["crc32"]):
                raise ShardCorruptError(
                    f"shard {path!r}: file CRC/size mismatch vs manifest "
                    f"(bytes {size} vs {entry['bytes']})", path=path)
            reader = self._reader(i)
            for j in range(len(reader)):
                reader.read(j)
            total_bytes += size
        return {"shards": len(self.shards), "records": len(self),
                "bytes": total_bytes}


def write_shard_set(out_dir: str, reader: Callable[[], Iterator[Any]], *,
                    num_shards: Optional[int] = None,
                    limit: Optional[int] = None,
                    meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The ``pack`` step: drain ``reader()`` (any paddle_tpu.data reader
    creator — samples, not batches) round-robin into ``num_shards``
    indexed shard files and publish the set ATOMICALLY (temp dir + fsync
    + rename, the checkpoint_io discipline): ``out_dir`` either holds a
    complete valid set or does not exist.  Returns the manifest."""
    from paddle_tpu.utils.flags import FLAGS

    n = int(num_shards or FLAGS.data_shards)
    if n < 1:
        raise ValueError(f"num_shards must be >= 1, got {n}")
    if os.path.exists(out_dir):
        # fail in milliseconds, not after draining the whole reader (the
        # same check guards the publish rename against a concurrent pack)
        raise ShardError(f"shard set {out_dir!r} already exists — "
                         f"refusing to overwrite")
    parent = os.path.dirname(os.path.abspath(out_dir)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, _TMP_PREFIX + os.path.basename(out_dir)
                       + "-" + uuid.uuid4().hex[:8])
    os.makedirs(tmp)
    writers = [ShardWriter(os.path.join(tmp, shard_name(i, n)))
               for i in range(n)]
    count = 0
    try:
        for sample in reader():
            writers[count % n].append(sample)
            count += 1
            if limit is not None and count >= limit:
                break
        entries = [w.close() for w in writers]
        writers = []
        manifest = {
            "version": SHARD_VERSION,
            "num_records": count,
            "layout": "round_robin",
            "wall_time": time.time(),
            "shards": entries,
            "meta": dict(meta or {}),
        }
        mpath = os.path.join(tmp, "manifest.json")
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        # fsync the directory so the rename below lands durably
        dfd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        if os.path.exists(out_dir):
            raise ShardError(f"shard set {out_dir!r} already exists — "
                             f"refusing to overwrite")
        os.replace(tmp, out_dir)
    except Exception:
        for w in writers:
            try:
                w.close()
            except Exception:
                pass
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise
    logger.info("packed %d record(s) into %d shard(s) at %s",
                count, n, out_dir)
    return manifest
