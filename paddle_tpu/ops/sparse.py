"""Sparse-input compute — the TPU-native analog of the reference's CSR/CSC
sparse tier.

Reference surface being covered: the ``hl_sparse.h`` kernel family (26 fns:
CSR/CSC construction, sparse×dense matmul, transpose-matmul for the backward
pass — reference: paddle/cuda/include/hl_sparse.h), the CPU sparse matrices
(paddle/math/CpuSparseMatrix.cpp, SparseMatrix.cpp) and the
``sparse_binary_vector`` / ``sparse_float_vector`` input types consumed by fc
layers over bag-of-words features (demo/quick_start/trainer_config.lr.py;
py_paddle/dataprovider_converter.py SparseBinaryScanner).

TPU-first re-design: CSR's variable row lengths are hostile to XLA's static
shapes, so the on-device format is **padded COO rows** (a.k.a. ELL): per
sample a fixed-width id vector [B, N] + weight vector [B, N] + validity mask
[B, N], with N bucketed by the feeder the same way sequence lengths are.
Sparse×dense matmul is then gather(W rows) → weighted segment-sum — a form
XLA lowers to dynamic-gather + reduction that stays entirely on-chip, and
whose autodiff transpose is exactly the row-sparse scatter-add the reference
implements by hand (hl_sparse.h csc_mul_dense backward;
SparseRowCpuMatrix::addTo).  The gradient w.r.t. the dense weight therefore
only touches the gathered rows — composing with the row-sparse optimizer
update path (``ParamAttr(sparse_grad=True)``).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from paddle_tpu.ops.matmul import linear
from paddle_tpu.ops.numerics import acc_dtype, mxu_cast

__all__ = [
    "sparse_gather_matmul",
    "sparse_to_dense",
    "selective_columns_matmul",
]


def sparse_gather_matmul(ids, weights, mask, w, b=None):
    """Padded-sparse [B, N] × dense [V, D] -> [B, D].

    ``out[b] = sum_n weights[b,n] * w[ids[b,n]]`` over valid n — the
    hl_sparse csr_mul_dense analog.  Invalid (padding) slots must be
    masked: their ids may be arbitrary in-range values.
    """
    rows = jnp.take(w, ids, axis=0)                      # [B, N, D]
    coef = (weights * mask).astype(rows.dtype)
    rows, coef = mxu_cast(rows, coef)
    out = jnp.einsum("bnd,bn->bd", rows, coef).astype(acc_dtype())
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def sparse_to_dense(ids, weights, mask, dim: int):
    """Densify padded-sparse rows into [B, dim] (the CpuSparseMatrix ->
    dense copy analog; used for equivalence testing and for layers without
    a sparse fast path). Duplicate ids accumulate, as in COO."""
    B, N = ids.shape
    coef = (weights * mask).astype(acc_dtype())
    out = jnp.zeros((B, dim), acc_dtype())
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, N))
    return out.at[rows.ravel(), ids.ravel()].add(coef.ravel())


def selective_columns_matmul(x, sel_ids, w, b=None, sel_mask: Optional[jnp.ndarray] = None):
    """Compute only selected output columns: x [B, Din] × w [Din, V] gathered
    at sel_ids [B, C] -> [B, C].

    The sparse compute path of SelectiveFullyConnectedLayer
    (gserver/layers/SelectiveFullyConnectedLayer.cpp: with a sparse selection
    the forward multiplies only the selected columns) — for huge softmax
    fronts where C << V makes even the MXU-dense path wasteful."""
    cols = jnp.take(w, sel_ids, axis=1)                  # [Din, B, C]
    cols = jnp.moveaxis(cols, 1, 0)                      # [B, Din, C]
    xc, colsc = mxu_cast(x, cols)
    out = jnp.einsum("bd,bdc->bc", xc, colsc).astype(acc_dtype())
    if b is not None:
        out = out + jnp.take(b, sel_ids, axis=0).astype(out.dtype)
    if sel_mask is not None:
        out = out * sel_mask.astype(out.dtype)
    return out
