"""Sparse-input compute — the TPU-native analog of the reference's CSR/CSC
sparse tier.

Reference surface being covered: the ``hl_sparse.h`` kernel family (26 fns:
CSR/CSC construction, sparse×dense matmul, transpose-matmul for the backward
pass — reference: paddle/cuda/include/hl_sparse.h), the CPU sparse matrices
(paddle/math/CpuSparseMatrix.cpp, SparseMatrix.cpp) and the
``sparse_binary_vector`` / ``sparse_float_vector`` input types consumed by fc
layers over bag-of-words features (demo/quick_start/trainer_config.lr.py;
py_paddle/dataprovider_converter.py SparseBinaryScanner).

TPU-first re-design: CSR's variable row lengths are hostile to XLA's static
shapes, so the on-device format is **padded COO rows** (a.k.a. ELL): per
sample a fixed-width id vector [B, N] + weight vector [B, N] + validity mask
[B, N], with N bucketed by the feeder the same way sequence lengths are.
Sparse×dense matmul is then gather(W rows) → weighted segment-sum — a form
XLA lowers to dynamic-gather + reduction that stays entirely on-chip, and
whose autodiff transpose is exactly the row-sparse scatter-add the reference
implements by hand (hl_sparse.h csc_mul_dense backward;
SparseRowCpuMatrix::addTo).  The gradient w.r.t. the dense weight therefore
only touches the gathered rows — composing with the row-sparse optimizer
update path (``ParamAttr(sparse_grad=True)``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.matmul import linear
from paddle_tpu.ops.numerics import acc_dtype, mxu_cast

__all__ = [
    "sparse_gather_matmul",
    "sparse_to_dense",
    "selective_columns_matmul",
    "CsrMatrix",
    "CscMatrix",
    "csr_matmul",
    "matmul_dense_csc",
]


def sparse_gather_matmul(ids, weights, mask, w, b=None):
    """Padded-sparse [..., N] × dense [V, D] -> [..., D].

    ``out[b] = sum_n weights[b,n] * w[ids[b,n]]`` over valid n — the
    hl_sparse csr_mul_dense analog.  Invalid (padding) slots must be
    masked: their ids may be arbitrary in-range values.  Leading dims are
    free: sparse sequences pass ids [B, T, N] and get [B, T, D].
    """
    rows = jnp.take(w, ids, axis=0)                      # [..., N, D]
    coef = (weights * mask).astype(rows.dtype)
    rows, coef = mxu_cast(rows, coef)
    out = jnp.einsum("...nd,...n->...d", rows, coef).astype(acc_dtype())
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def sparse_to_dense(ids, weights, mask, dim: int):
    """Densify padded-sparse rows into [B, dim] (the CpuSparseMatrix ->
    dense copy analog; used for equivalence testing and for layers without
    a sparse fast path). Duplicate ids accumulate, as in COO."""
    B, N = ids.shape
    coef = (weights * mask).astype(acc_dtype())
    out = jnp.zeros((B, dim), acc_dtype())
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, N))
    return out.at[rows.ravel(), ids.ravel()].add(coef.ravel())


def selective_columns_matmul(x, sel_ids, w, b=None, sel_mask: Optional[jnp.ndarray] = None):
    """Compute only selected output columns: x [B, Din] × w [Din, V] gathered
    at sel_ids [B, C] -> [B, C].

    The sparse compute path of SelectiveFullyConnectedLayer
    (gserver/layers/SelectiveFullyConnectedLayer.cpp: with a sparse selection
    the forward multiplies only the selected columns) — for huge softmax
    fronts where C << V makes even the MXU-dense path wasteful."""
    cols = jnp.take(w, sel_ids, axis=1)                  # [Din, B, C]
    cols = jnp.moveaxis(cols, 1, 0)                      # [B, Din, C]
    xc, colsc = mxu_cast(x, cols)
    out = jnp.einsum("bd,bdc->bc", xc, colsc).astype(acc_dtype())
    if b is not None:
        out = out + jnp.take(b, sel_ids, axis=0).astype(out.dtype)
    if sel_mask is not None:
        out = out * sel_mask.astype(out.dtype)
    return out


# ---------------------------------------------------------------------------
# CSR / CSC matrices
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CsrMatrix:
    """Compressed-sparse-row matrix — the CpuSparseMatrix/GpuSparseMatrix
    analog (reference: paddle/math/CpuSparseMatrix.h:36, SparseMatrix.h;
    hl_sparse.h CSR family).

    Host-side representation: numpy ``indptr`` [R+1], ``indices`` [nnz],
    ``data`` [nnz] (``data=None`` = binary/NO_VALUE format, all ones — the
    reference's SPARSE_CSR vs SPARSE_CSR_VALUE distinction).  Compute happens
    on device through ``to_padded()``: CSR's ragged rows are re-laid-out as
    fixed-width padded rows (ELL) so XLA keeps static shapes — the TPU-native
    answer to the reference's hand-written ragged CUDA kernels.
    """

    shape: tuple
    indptr: np.ndarray
    indices: np.ndarray
    data: Optional[np.ndarray] = None

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    @classmethod
    def from_rows(cls, rows: Sequence, ncols: int, *, binary: bool = False):
        """Build from per-row entries: id lists (binary) or (id, value)
        pairs — the PyDataProvider2 sparse_binary/float_vector slot formats
        (reference: python/paddle/trainer/PyDataProvider2.py:83-120)."""
        indptr = np.zeros(len(rows) + 1, np.int64)
        ids, vals = [], []
        for i, row in enumerate(rows):
            row = list(row)
            indptr[i + 1] = indptr[i] + len(row)
            if binary:
                ids.extend(int(j) for j in row)
            else:
                for j, v in row:
                    ids.append(int(j))
                    vals.append(float(v))
        indices = np.asarray(ids, np.int32)
        data = None if binary else np.asarray(vals, np.float32)
        return cls((len(rows), ncols), indptr, indices, data)

    @classmethod
    def from_dense(cls, a) -> "CsrMatrix":
        a = np.asarray(a)
        mask = a != 0
        indptr = np.zeros(a.shape[0] + 1, np.int64)
        np.cumsum(mask.sum(1), out=indptr[1:])
        indices = np.nonzero(mask)[1].astype(np.int32)
        return cls(a.shape, indptr, indices, a[mask].astype(np.float32))

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.shape, np.float32)
        vals = self.data if self.data is not None else np.ones(self.nnz, np.float32)
        for i in range(self.shape[0]):
            lo, hi = self.indptr[i], self.indptr[i + 1]
            np.add.at(out[i], self.indices[lo:hi], vals[lo:hi])
        return out

    def to_padded(self, width: Optional[int] = None):
        """Re-lay out as padded rows: (ids [R, N], weights [R, N],
        mask [R, N]) numpy arrays ready to feed ``sparse_gather_matmul``.
        N defaults to the max row nnz (>=1); an explicit ``width`` smaller
        than a row's nnz is an error (silent truncation would corrupt the
        product)."""
        counts = np.diff(self.indptr)
        max_nnz = int(counts.max(initial=0))
        if width is not None and width < max_nnz:
            raise ValueError(
                f"to_padded(width={width}) would drop entries: a row has "
                f"{max_nnz} nonzeros")
        N = int(width or max(max_nnz, 1))
        R = self.shape[0]
        ids = np.zeros((R, N), np.int32)
        weights = np.zeros((R, N), np.float32)
        mask = np.zeros((R, N), np.float32)
        vals = self.data if self.data is not None else np.ones(self.nnz, np.float32)
        for i in range(R):
            lo, hi = int(self.indptr[i]), int(self.indptr[i + 1])
            n = min(hi - lo, N)
            ids[i, :n] = self.indices[lo : lo + n]
            weights[i, :n] = vals[lo : lo + n]
            mask[i, :n] = 1.0
        return ids, weights, mask

    def transpose(self) -> "CscMatrix":
        """O(1) view change: CSR of M is CSC of M^T (hl_sparse's
        CSR<->CSC duality)."""
        return CscMatrix((self.shape[1], self.shape[0]), self.indptr,
                         self.indices, self.data)

    @property
    def T(self) -> "CscMatrix":
        return self.transpose()


@dataclass(frozen=True)
class CscMatrix:
    """Compressed-sparse-column matrix: ``indptr`` [C+1] over columns,
    ``indices`` row ids.  Stored exactly as the CSR of its transpose."""

    shape: tuple
    indptr: np.ndarray
    indices: np.ndarray
    data: Optional[np.ndarray] = None

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    @classmethod
    def from_dense(cls, a) -> "CscMatrix":
        return CsrMatrix.from_dense(np.asarray(a).T).transpose()

    def to_dense(self) -> np.ndarray:
        return self.to_csr_of_transpose().to_dense().T

    def to_csr_of_transpose(self) -> CsrMatrix:
        return CsrMatrix((self.shape[1], self.shape[0]), self.indptr,
                         self.indices, self.data)

    def transpose(self) -> CsrMatrix:
        return self.to_csr_of_transpose()

    @property
    def T(self) -> CsrMatrix:
        return self.transpose()


def csr_matmul(m: CsrMatrix, dense, b=None):
    """General sparse x dense: CSR [R, C] x dense [C, D] -> [R, D] — the
    hl_matrix_csr_mul_dense analog (reference: paddle/cuda/include/hl_sparse.h;
    CpuSparseMatrix used as fc input, Matrix::mul dispatch).

    The padded re-layout happens host-side once; the device computation is
    gather + weighted reduction on the MXU, whose autodiff transpose is the
    row-sparse scatter the reference hand-writes for the backward."""
    ids, weights, mask = m.to_padded()
    return sparse_gather_matmul(jnp.asarray(ids), jnp.asarray(weights),
                                jnp.asarray(mask), dense, b)


def matmul_dense_csc(x, m: CscMatrix, b=None):
    """dense x sparse: x [B, R] x CSC [R, C] -> [B, C] — the
    hl_matrix_dense_mul_csc analog (sparse weight matrices, e.g. a pruned
    output projection).

    out[:, j] = sum_n w[j, n] * x[:, row_ids[j, n]]: gather x columns by the
    per-output-column row lists, weight, reduce."""
    ids, weights, mask = m.to_csr_of_transpose().to_padded()  # [C, N] over rows of x
    cols = jnp.take(x, jnp.asarray(ids), axis=1)             # [B, C, N]
    coef = jnp.asarray(weights * mask)
    cols, coef = mxu_cast(cols, coef)
    out = jnp.einsum("bcn,cn->bc", cols, coef).astype(acc_dtype())
    if b is not None:
        out = out + b.astype(out.dtype)
    return out
