"""Convolution / pooling / normalization ops — analog of the reference's CNN tier.

Reference surface: cuDNN wrappers (paddle/cuda/src/hl_cuda_cudnn.cc: conv,
pool, batch-norm descriptors) and hand CNN kernels
(paddle/cuda/src/hl_cuda_cnn.cu: hl_maxpool_forward, hl_avgpool_forward,
hl_CMRNorm_forward, bilinear, maxout).

TPU-first: NHWC layout throughout (XLA:TPU's native conv layout — channels on
the 128-lane minor dimension), ``lax.conv_general_dilated`` onto the MXU with
bf16 operands and f32 accumulation, ``lax.reduce_window`` for pooling.  The
reference's NCHW Matrix layout is *not* reproduced; the feeder delivers NHWC.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.numerics import acc_dtype, mxu_cast

__all__ = [
    "conv2d",
    "conv2d_transpose",
    "max_pool2d",
    "avg_pool2d",
    "batch_norm",
    "cmr_norm",
    "bilinear_interp",
    "maxout",
    "global_avg_pool",
]


def conv2d(x, w, *, stride=(1, 1), padding="SAME", dilation=(1, 1), groups=1):
    """NHWC conv: x [B,H,W,Cin], w [kh,kw,Cin//groups,Cout] -> [B,H',W',Cout].

    Operands in the bf16 compute dtype, output cast up to f32 explicitly
    (not via ``preferred_element_type``: conv's VJP builds transposed convs
    from the f32 cotangent + bf16 operand and conv requires matching operand
    dtypes, whereas the explicit convert's transpose downcasts the cotangent
    first — the MXU still accumulates in f32 internally either way)."""
    x, w = mxu_cast(x, w)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=tuple(stride),
        padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )
    return out.astype(acc_dtype())


def conv2d_transpose(x, w, *, stride=(1, 1), padding="SAME"):
    """Transposed NHWC conv (deconvolution) — the exconvt analog
    (reference gserver/layers/ConvTransLayerBase; hl deconv kernels).
    x [B,H,W,Cin], w [kh,kw,Cin,Cout] -> [B,H*s,W*s,Cout] for SAME."""
    x, w = mxu_cast(x, w)
    out = lax.conv_transpose(
        x,
        w,
        strides=tuple(stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out.astype(acc_dtype())  # see conv2d: keep conv VJP dtypes matched


def _pool(x, window, stride, padding, init, op):
    dims = (1, window[0], window[1], 1)
    strides = (1, stride[0], stride[1], 1)
    return lax.reduce_window(x, init, op, dims, strides, padding)


def max_pool2d(x, window=(2, 2), stride=None, padding="VALID"):
    stride = stride or window
    return _pool(x, window, stride, padding, -jnp.inf, lax.max)


def avg_pool2d(x, window=(2, 2), stride=None, padding="VALID"):
    """Average pooling; with SAME/edge padding the divisor counts only the
    in-bounds window elements (cuDNN's include-padding=false behavior)."""
    stride = stride or window
    s = _pool(x, window, stride, padding, 0.0, lax.add)
    ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
    cnt = _pool(ones, window, stride, padding, 0.0, lax.add)
    return s / cnt


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))


def batch_norm(x, scale, bias, running_mean, running_var, *, train, momentum=0.9, eps=1e-5):
    """Batch norm over all but the channel axis (last). Returns
    (y, new_running_mean, new_running_var).

    Analog of the reference's three BN impls (BatchNormalizationLayer.cpp,
    CudnnBatchNormLayer.cpp); running stats use the same EMA with
    ``movingAvgFraction`` = momentum.
    """
    axes = tuple(range(x.ndim - 1))
    if train:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        new_mean = momentum * running_mean + (1.0 - momentum) * mean
        new_var = momentum * running_var + (1.0 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var
    inv = lax.rsqrt(var + eps)
    y = (x - mean) * inv * scale + bias
    return y.astype(x.dtype), new_mean, new_var


def cmr_norm(x, *, size=5, scale=1e-4, power=0.75):
    """Cross-map (cross-channel) response normalization, NHWC.

    Analog of hl_CMRNorm_forward (paddle/cuda/src/hl_cuda_cnn.cu) /
    CMRProjectionNormLayer — AlexNet-style LRN: denominator sums squares over a
    window of ``size`` adjacent channels.
    """
    sq = jnp.square(x)
    half = size // 2
    pad = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
    # windowed channel sum via reduce_window on the channel axis
    acc = lax.reduce_window(pad, 0.0, lax.add, (1, 1, 1, size), (1, 1, 1, 1), "VALID")
    denom = jnp.power(1.0 + scale * acc, power)
    return x / denom


def bilinear_interp(x, out_h, out_w):
    """Bilinear resize NHWC (analog of hl_bilinear_forward / BilinearInterpLayer)."""
    return jax.image.resize(
        x, (x.shape[0], out_h, out_w, x.shape[3]), method="bilinear"
    ).astype(x.dtype)


def maxout(x, groups):
    """Maxout over channel groups (analog of hl_maxout_forward / MaxOutLayer):
    [B,H,W,C] -> [B,H,W,C//groups], max over each group of ``groups`` channels."""
    B, H, W, C = x.shape
    assert C % groups == 0, "channels must divide maxout groups"
    return jnp.max(x.reshape(B, H, W, C // groups, groups), axis=-1)
