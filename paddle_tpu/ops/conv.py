"""Convolution / pooling / normalization ops — analog of the reference's CNN tier.

Reference surface: cuDNN wrappers (paddle/cuda/src/hl_cuda_cudnn.cc: conv,
pool, batch-norm descriptors) and hand CNN kernels
(paddle/cuda/src/hl_cuda_cnn.cu: hl_maxpool_forward, hl_avgpool_forward,
hl_CMRNorm_forward, bilinear, maxout).

TPU-first: NHWC layout throughout (XLA:TPU's native conv layout — channels on
the 128-lane minor dimension), ``lax.conv_general_dilated`` onto the MXU with
bf16 operands and f32 accumulation, ``lax.reduce_window`` for pooling.  The
reference's NCHW Matrix layout is *not* reproduced; the feeder delivers NHWC.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.numerics import acc_dtype, mxu_cast

__all__ = [
    "conv2d",
    "conv2d_transpose",
    "max_pool2d",
    "avg_pool2d",
    "batch_norm",
    "cmr_norm",
    "bilinear_interp",
    "maxout",
    "global_avg_pool",
]


def _space_to_depth_conv(x, w, s, pads):
    """Strided low-channel conv as space-to-depth + stride-1 conv.

    The MLPerf-style stem rewrite: a [kh,kw,C<=4,Cout] stride-s conv wastes
    the MXU's 128 input lanes (C=3 pads to 8) and makes the weight-gradient
    conv pathological (profiled 0.8 ms/step on GoogLeNet's 7x7s2 stem alone).
    Re-laying x as s x s blocks ([B,H/s,W/s,s*s*C]) and the kernel as
    [ceil(k/s),ceil(k/s),s*s*C,Cout] computes the identical convolution with
    an s^2-wider contraction and stride 1 — autodiff then produces aligned
    backward convs for free.  Exactness: out[o] reads padded rows
    s*o .. s*o+K'-1 where K' = s*ceil(k/s); taps beyond k are zero-padded
    kernel entries."""
    B, H, W, C = x.shape
    k, _, _, Cout = w.shape
    (plo_h, phi_h), (plo_w, phi_w) = pads
    Kp = -(-k // s) * s
    Ho = (H + plo_h + phi_h - k) // s + 1
    Wo = (W + plo_w + phi_w - k) // s + 1
    Lh, Lw = s * (Ho - 1) + Kp, s * (Wo - 1) + Kp
    if Lh - H - plo_h < 0 or Lw - W - plo_w < 0:
        return None  # rewrite would drop input columns; use the plain conv
    xp = jnp.pad(x, ((0, 0), (plo_h, Lh - H - plo_h), (plo_w, Lw - W - plo_w),
                     (0, 0)))
    xs = xp.reshape(B, Lh // s, s, Lw // s, s, C)
    xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(B, Lh // s, Lw // s, s * s * C)
    wp = jnp.pad(w, ((0, Kp - k), (0, Kp - k), (0, 0), (0, 0)))
    ws = wp.reshape(Kp // s, s, Kp // s, s, C, Cout)
    ws = ws.transpose(0, 2, 1, 3, 4, 5).reshape(Kp // s, Kp // s, s * s * C, Cout)
    return lax.conv_general_dilated(
        xs, ws, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _explicit_pads(padding, k, s, h, w):
    """Resolve a conv padding spec to ((plo,phi),(plo,phi)) int pairs."""
    if isinstance(padding, str):
        if padding == "VALID":
            return ((0, 0), (0, 0))
        if padding == "SAME":
            out = []
            for dim in (h, w):
                o = -(-dim // s)
                total = max((o - 1) * s + k - dim, 0)
                out.append((total // 2, total - total // 2))
            return tuple(out)
        return None
    pads = tuple((int(p[0]), int(p[1])) for p in padding)
    return pads


def conv2d(x, w, *, stride=(1, 1), padding="SAME", dilation=(1, 1), groups=1):
    """NHWC conv: x [B,H,W,Cin], w [kh,kw,Cin//groups,Cout] -> [B,H',W',Cout].

    Operands AND output stay in the bf16 compute dtype (the MXU accumulates
    in f32 internally either way): activations between conv-stack layers are
    HBM traffic, and storing them at 2 bytes instead of 4 is worth ~1.2x
    end-to-end on the image benches (v5e, GoogLeNet b128 A/B).  Ops needing
    f32 internals (batch-norm statistics, LRN denominator, losses) upcast
    locally; under the tests' float32 compute dtype nothing changes.

    Strided stems with Cin<=4 (AlexNet 11x11s4, GoogLeNet 7x7s2) are
    rewritten via space-to-depth (see _space_to_depth_conv)."""
    x, w = mxu_cast(x, w)
    s = tuple(stride)
    if (x.shape[3] <= 4 and s[0] == s[1] and s[0] > 1 and groups == 1
            and tuple(dilation) == (1, 1) and w.shape[0] == w.shape[1]):
        pads = _explicit_pads(padding, w.shape[0], s[0], x.shape[1], x.shape[2])
        if pads is not None:
            out = _space_to_depth_conv(x, w, s[0], pads)
            if out is not None:
                return out
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=tuple(stride),
        padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )


def conv2d_transpose(x, w, *, stride=(1, 1), padding="SAME"):
    """Transposed NHWC conv (deconvolution) — the exconvt analog
    (reference gserver/layers/ConvTransLayerBase; hl deconv kernels).
    x [B,H,W,Cin], w [kh,kw,Cin,Cout] -> [B,H*s,W*s,Cout] for SAME."""
    x, w = mxu_cast(x, w)
    return lax.conv_transpose(  # stays in compute dtype — see conv2d
        x,
        w,
        strides=tuple(stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _pool(x, window, stride, padding, init, op):
    dims = (1, window[0], window[1], 1)
    strides = (1, stride[0], stride[1], 1)
    return lax.reduce_window(x, init, op, dims, strides, padding)


def max_pool2d(x, window=(2, 2), stride=None, padding="VALID"):
    # backward is XLA's select-and-scatter: a hand-written tap-compare VJP
    # (hl_maxpool_backward style) was A/B-tested on v5e and LOST (GoogLeNet
    # b128 29.0 vs 20.4 ms/batch) — the native lowering is near roofline
    stride = stride or window
    return _pool(x, window, stride, padding, -jnp.inf, lax.max)


def avg_pool2d(x, window=(2, 2), stride=None, padding="VALID"):
    """Average pooling; with SAME/edge padding the divisor counts only the
    in-bounds window elements (cuDNN's include-padding=false behavior)."""
    stride = stride or window
    s = _pool(x, window, stride, padding, 0.0, lax.add)
    ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
    cnt = _pool(ones, window, stride, padding, 0.0, lax.add)
    return s / cnt


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))


def batch_norm(x, scale, bias, running_mean, running_var, *, train, momentum=0.9, eps=1e-5):
    """Batch norm over all but the channel axis (last). Returns
    (y, new_running_mean, new_running_var).

    Analog of the reference's three BN impls (BatchNormalizationLayer.cpp,
    CudnnBatchNormLayer.cpp); running stats use the same EMA with
    ``movingAvgFraction`` = momentum.
    """
    axes = tuple(range(x.ndim - 1))
    if train:
        xf = x.astype(acc_dtype())  # stats in f32 even for bf16 activations
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        new_mean = momentum * running_mean + (1.0 - momentum) * mean
        new_var = momentum * running_var + (1.0 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var
    inv = lax.rsqrt(var + eps)
    y = (x - mean) * inv * scale + bias
    return y.astype(x.dtype), new_mean, new_var


def cmr_norm(x, *, size=5, scale=1e-4, power=0.75):
    """Cross-map (cross-channel) response normalization, NHWC.

    Analog of hl_CMRNorm_forward (paddle/cuda/src/hl_cuda_cnn.cu) /
    CMRProjectionNormLayer — AlexNet-style LRN: denominator sums squares over a
    window of ``size`` adjacent channels.
    """
    # denominator in f32: near 1.0 bf16 resolution is ~4e-3, which would
    # round the whole 1 + 1e-4*acc correction away for bf16 activations
    sq = jnp.square(x.astype(acc_dtype()))
    half = size // 2
    pad = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
    # windowed channel sum via reduce_window on the channel axis
    acc = lax.reduce_window(pad, 0.0, lax.add, (1, 1, 1, size), (1, 1, 1, 1), "VALID")
    denom = jnp.power(1.0 + scale * acc, power)
    return (x / denom).astype(x.dtype)


def bilinear_interp(x, out_h, out_w):
    """Bilinear resize NHWC (analog of hl_bilinear_forward / BilinearInterpLayer)."""
    return jax.image.resize(
        x, (x.shape[0], out_h, out_w, x.shape[3]), method="bilinear"
    ).astype(x.dtype)


def maxout(x, groups):
    """Maxout over channel groups (analog of hl_maxout_forward / MaxOutLayer):
    [B,H,W,C] -> [B,H,W,C//groups], max over each group of ``groups`` channels."""
    B, H, W, C = x.shape
    assert C % groups == 0, "channels must divide maxout groups"
    return jnp.max(x.reshape(B, H, W, C // groups, groups), axis=-1)
