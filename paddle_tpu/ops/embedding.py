"""Embedding / table ops — analog of the reference's table + sparse-row tier.

Reference surface: hl_table_apply (paddle/cuda/src/hl_table_apply.cu —
lookup forward, scatter-add backward) feeding TableProjection/embedding
layers, with sparse-row gradient matrices for huge vocabularies
(paddle/math/SparseRowMatrix.h) and remote prefetch
(trainer/RemoteParameterUpdater.h:265).

TPU-first: lookup is ``jnp.take``; the backward scatter-add is a custom VJP
that SORTS the flattened ids before scattering — on TPU an id-sorted
scatter-add runs ~3x faster than the unsorted one XLA autodiff emits
(duplicate ids serialize the unsorted scatter; sorting groups them so the
row accumulations coalesce; measured 0.27 vs 0.78 ms for 8k ids into a
30k x 512 f32 table on v5e).  The *sharded* vocabulary case (the pserver
prefetch analog) lives in parallel/embedding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["embedding_lookup", "one_hot"]


@jax.custom_vjp
def _lookup(table, ids):
    return jnp.take(table, ids, axis=0)


def _lookup_fwd(table, ids):
    # the table rides along only for its shape/dtype (a reference, not a copy)
    return jnp.take(table, ids, axis=0), (table, ids)


def _lookup_bwd(res, ct):
    table, ids = res
    shape, dtype = table.shape, table.dtype
    row_shape = shape[1:]
    flat_ids = ids.reshape(-1)
    flat_ct = ct.reshape((-1,) + row_shape).astype(dtype)
    order = jnp.argsort(flat_ids)
    d_table = jnp.zeros(shape, dtype).at[flat_ids[order]].add(flat_ct[order])
    return d_table, None


_lookup.defvjp(_lookup_fwd, _lookup_bwd)


def embedding_lookup(table, ids, *, pad_to_zero_id=None):
    """table [V, D], ids int [B, ...] -> [B, ..., D].

    If ``pad_to_zero_id`` is given, rows with that id produce zero vectors
    (used for padded positions so gradients don't touch the pad row).
    """
    out = _lookup(table, ids.astype(jnp.int32))
    if pad_to_zero_id is not None:
        keep = (ids != pad_to_zero_id)[..., None]
        out = out * keep.astype(out.dtype)
    return out


def one_hot(ids, depth, dtype=jnp.float32):
    return jnp.eye(depth, dtype=dtype)[ids.astype(jnp.int32)]
