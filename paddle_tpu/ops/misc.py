"""Aggregate / top-k / misc ops — analog of the reference's utility kernels.

Reference surface: row/col reductions (paddle/cuda/src/hl_cuda_aggregate.cu),
top-k (hl_top_k.cu), batched transpose (hl_batch_transpose.cu), interpolation /
convex-combination / outer-product / cos-sim layers
(gserver/layers/InterpolationLayer.cpp, CosSimLayer.cpp, OuterProdLayer.cpp,
TensorLayer.cpp), and feature-map perturbation (hl_perturbation_util.cu).
On TPU every one of these is a short jnp/lax expression XLA fuses; they exist
as named functions so the layer tier and tests have a stable kernel surface.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.matmul import matmul
from paddle_tpu.ops.numerics import acc_dtype, dot_dtype, mxu_cast

__all__ = [
    "row_sum",
    "row_max",
    "row_min",
    "col_sum",
    "top_k",
    "max_id",
    "batch_transpose",
    "cos_sim",
    "interpolation",
    "outer_prod",
    "tensor_bilinear",
    "sum_cost",
    "scaling",
    "slope_intercept",
    "power_op",
    "dropout",
]


def row_sum(x):
    return jnp.sum(x, axis=-1)


def row_max(x):
    return jnp.max(x, axis=-1)


def row_min(x):
    return jnp.min(x, axis=-1)


def col_sum(x):
    return jnp.sum(x, axis=0)


def top_k(x, k):
    """Values and indices of the k largest entries along the last axis."""
    return lax.top_k(x, k)


def max_id(x):
    return jnp.argmax(x, axis=-1).astype(jnp.int32)


def batch_transpose(x):
    """[B, M, N] -> [B, N, M] (hl_batch_transpose analog)."""
    return jnp.swapaxes(x, -1, -2)


def cos_sim(a, b, scale=1.0, eps=1e-8):
    """Row-wise cosine similarity (CosSimLayer): [B,D],[B,D] -> [B]."""
    num = jnp.sum(a * b, axis=-1)
    den = jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
    return scale * num / jnp.maximum(den, eps)


def interpolation(w, a, b):
    """w*a + (1-w)*b with per-row scalar w [B,1] (InterpolationLayer)."""
    return w * a + (1.0 - w) * b


def outer_prod(a, b):
    """[B,M],[B,N] -> [B,M*N] row-wise outer product (OuterProdLayer)."""
    out = a[:, :, None] * b[:, None, :]
    return out.reshape(a.shape[0], -1)


def tensor_bilinear(a, b, w):
    """TensorLayer: out[b, k] = a[b] @ W[k] @ b[b]; w: [K, Da, Db]."""
    ac, bc, wc = mxu_cast(a, b, w)
    return jnp.einsum("bi,kij,bj->bk", ac, wc, bc,
                      preferred_element_type=dot_dtype())


def sum_cost(x):
    return jnp.sum(x)


def scaling(scale, x):
    """Per-row scalar scaling [B,1] * [B,D] (ScalingLayer)."""
    return scale * x


def slope_intercept(x, slope=1.0, intercept=0.0):
    return slope * x + intercept


def power_op(p, x):
    """Per-row power: x ** p with p [B,1] (PowerLayer)."""
    return jnp.power(x, p)


def dropout(rng, x, rate, *, train):
    """Inverted dropout (the reference applies dropout via layer attr)."""
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
