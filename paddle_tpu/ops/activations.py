"""Activation functions — analog of the reference's activation registry.

The reference registers ~14 activation types applied in-place on layer outputs
(reference: paddle/gserver/activations/ActivationFunction.cpp:30-60,387, plus
the hl_avx/cpu twins in paddle/cuda/src/hl_avx_functions.cc).  Here each is a
pure jnp function; XLA fuses them into the producing matmul, so there is no
separate "activation kernel" tier.  ``sequence_softmax`` operates on a padded
sequence batch with a mask (the analog of softmax over a flat sequence slice).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.utils.registry import Registry

__all__ = ["ACTIVATIONS", "get_activation", "softmax", "sequence_softmax"]

ACTIVATIONS: Registry = Registry("activation")


def get_activation(name):
    """Resolve an activation by name; None / '' / 'linear' → identity."""
    if name is None or name == "":
        return ACTIVATIONS.get("linear")
    if callable(name):
        return name
    return ACTIVATIONS.get(name)


def _reg(name):
    return ACTIVATIONS.register(name)


@_reg("linear")
def linear(x):
    return x


@_reg("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@_reg("tanh")
def tanh(x):
    return jnp.tanh(x)


@_reg("relu")
def relu(x):
    return jax.nn.relu(x)


@_reg("brelu")
def brelu(x, t_min=0.0, t_max=24.0):
    # bounded relu, reference default bound 24 (hl_activation_functions.h)
    return jnp.clip(x, t_min, t_max)


@_reg("stanh")
def stanh(x, a=1.7159, b=2.0 / 3.0):
    # scaled tanh a*tanh(b*x) (reference STanhActivation)
    return a * jnp.tanh(b * x)


@_reg("softrelu")
def softrelu(x, threshold=40.0):
    # log(1+exp(x)), clipped like the reference for stability
    return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))


@_reg("exponential")
def exponential(x):
    return jnp.exp(x)


@_reg("log")
def log_act(x):
    return jnp.log(x)


@_reg("abs")
def abs_act(x):
    return jnp.abs(x)


@_reg("square")
def square(x):
    return jnp.square(x)


@_reg("sqrt")
def sqrt_act(x):
    return jnp.sqrt(x)


@_reg("reciprocal")
def reciprocal(x):
    return 1.0 / x


@_reg("softmax")
def softmax(x, axis=-1):
    # the exp/sum statistics run in f32 (the --amp allowlist: bf16
    # normalizers lose the probability mass of every small-logit tail);
    # the result returns in the caller's dtype
    out = jax.nn.softmax(x.astype(jnp.float32), axis=axis)
    return out.astype(x.dtype)


@_reg("sequence_softmax")
def sequence_softmax(x, mask=None, axis=-2):
    """Softmax along the time axis of a padded [B, T, 1]/[B, T] batch.

    Analog of the reference's per-sequence softmax over a flat slice
    (SequenceSoftmaxActivation); padding positions get probability 0.
    """
    if mask is None:
        return softmax(x, axis=axis)
    if x.ndim == mask.ndim + 1:
        m = mask[..., None]
    else:
        m = mask
    neg = jnp.finfo(x.dtype).min
    z = jnp.where(m > 0, x, neg)
    p = softmax(z, axis=axis)  # f32 statistics (--amp allowlist)
    return p * m.astype(p.dtype)
