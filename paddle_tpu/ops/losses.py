"""Cost/loss ops — analog of the reference's cost layers and CE kernels.

Reference surface: hl_matrix cross-entropy kernels
(paddle/cuda/src/hl_cuda_matrix.cu: crossEntropy/crossEntropyBp) and the cost
layer family (paddle/gserver/layers/CostLayer.cpp: multi-class CE, soft CE,
huber, MSE/sum-of-squares, smooth-l1, rank cost, multi-binary-label CE;
LambdaCost.cpp).  TPU-first: all are fused log-softmax formulations — never
materialize probabilities then log() (numerically unstable, and XLA fuses the
subtraction into the softmax reduction).

Sequence-aware variants take a mask [B, T]; padded positions contribute zero
loss and the mean is taken over *real* tokens, matching the reference's
flat-sequence costs (no padding there by construction).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = [
    "cross_entropy",
    "soft_cross_entropy",
    "binary_cross_entropy",
    "multi_binary_label_cross_entropy",
    "mse",
    "huber",
    "smooth_l1",
    "rank_cost",
    "masked_token_mean",
    "sequence_cross_entropy",
]


def _f32(x):
    """Losses and their softmax/logsumexp statistics run in f32 — the
    ``--amp`` allowlist (bf16 logsumexp loses ~3 decimal digits exactly
    where training signal lives); a no-op for f32 inputs."""
    return x.astype(jnp.float32) if jnp.issubdtype(x.dtype, jnp.floating) \
        else x


def cross_entropy(logits, labels, *, axis=-1):
    """Multi-class CE from logits and integer labels; per-example losses."""
    logp = jax.nn.log_softmax(_f32(logits), axis=axis)
    lab = jnp.expand_dims(labels.astype(jnp.int32), axis)
    nll = -jnp.take_along_axis(logp, lab, axis=axis)
    return jnp.squeeze(nll, axis)


def soft_cross_entropy(logits, target_probs, *, axis=-1):
    logp = jax.nn.log_softmax(_f32(logits), axis=axis)
    return -jnp.sum(_f32(target_probs) * logp, axis=axis)


def binary_cross_entropy(logits, labels):
    # stable BCE-with-logits
    logits, labels = _f32(logits), _f32(labels)
    z = jax.nn.log_sigmoid(logits)
    zneg = jax.nn.log_sigmoid(-logits)
    return -(labels * z + (1.0 - labels) * zneg)


def multi_binary_label_cross_entropy(logits, label_matrix):
    """Per-class independent BCE summed over classes (reference
    MultiBinaryLabelCrossEntropy)."""
    return jnp.sum(binary_cross_entropy(logits, label_matrix), axis=-1)


def mse(pred, target):
    return 0.5 * jnp.sum(jnp.square(_f32(pred) - _f32(target)), axis=-1)


def huber(pred, target, delta=1.0):
    d = _f32(pred) - _f32(target)
    a = jnp.abs(d)
    quad = 0.5 * jnp.square(d)
    lin = delta * (a - 0.5 * delta)
    return jnp.sum(jnp.where(a <= delta, quad, lin), axis=-1)


def smooth_l1(pred, target):
    return huber(pred, target, delta=1.0)


def rank_cost(score_left, score_right, label, weight=None):
    """Pairwise rank cost (reference RankingCost): -o*log(s)-(1-o)*log(1-s)
    with s = sigmoid(left-right), o = label in [0,1]."""
    d = score_left - score_right
    cost = binary_cross_entropy(d, label)
    if weight is not None:
        cost = cost * weight
    return cost


def masked_token_mean(per_token, mask):
    """Mean over real (mask>0) positions — the sequence-cost reduction."""
    mask = mask.astype(per_token.dtype)
    total = jnp.sum(per_token * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count


def sequence_cross_entropy(logits, labels, mask):
    """Token-level CE over a padded [B, T, V] batch, averaged over real tokens."""
    per_tok = cross_entropy(logits, labels)
    return masked_token_mean(per_tok, mask)


def _readout_logits(states, w, b):
    from jax import lax

    from paddle_tpu.ops.numerics import mxu_cast

    sc, wc = mxu_cast(states, w)
    logits = lax.dot_general(sc, wc, (((sc.ndim - 1,), (0,)), ((), ())))
    return logits + b.astype(logits.dtype)             # [B, T, V] compute dtype


# One-pass Pallas logsumexp for the readout: A/B-measured and LOST on v5e
# at the WMT14 headline shape (33.4 vs 22.5 ms/step, B384 T32 V30k,
# row_tile 64): the kernel's sequential row-tile grid serializes what
# XLA's fused two-pass reduction overlaps with the readout matmul.  The
# kernel + custom-VJP path is kept (with its interpret-mode equivalence
# test) as a recorded losing A/B — this switch stays off.
_USE_PALLAS_LSE_READOUT = False


@jax.custom_vjp
def _ce_readout_fused(states, w, b, labels, mask):
    """Pallas-lse variant: identical math, logits read once for the
    softmax statistics instead of twice (max pass + exp-sum pass)."""
    loss, _ = _ce_readout_fwd(states, w, b, labels, mask)
    return loss


def _ce_readout_fwd(states, w, b, labels, mask):
    import math

    from paddle_tpu.ops.pallas_kernels import logsumexp_rows_pallas

    B, T, _ = states.shape
    logits = _readout_logits(states, w, b)
    V = logits.shape[-1]
    # the kernel requires N % row_tile == 0; gcd keeps the recorded-A/B
    # path runnable at ANY B*T (ADVICE r4: row_tile=64 traced-failed when
    # B*T wasn't a multiple of 64)
    rt = math.gcd(B * T, 64)
    if rt < 8:
        # ADVICE r5: a row tile below the (8, 128) sublane makes the Pallas
        # grid as long as B*T with sublane-unaligned blocks — an untested
        # Mosaic corner that is at best very slow.  Use the XLA reduction
        # (identical statistics) instead of shrinking the tile.
        lf32 = logits.astype(jnp.float32)
        m = jnp.max(lf32, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(lf32 - m[..., None]), axis=-1))
    else:
        lse = logsumexp_rows_pallas(logits.reshape(B * T, V),
                                    row_tile=rt).reshape(B, T)
    lab = jnp.expand_dims(labels.astype(jnp.int32), -1)
    tok = jnp.squeeze(jnp.take_along_axis(logits, lab, axis=-1), -1)
    per_tok = lse - tok.astype(jnp.float32)
    loss = masked_token_mean(per_tok, mask)
    return loss, (states, w, logits, lse, labels, mask)


def _ce_readout_bwd(res, d):
    states, w, logits, lse, labels, mask = res
    f32 = jnp.float32
    mask_f = mask.astype(f32)
    denom = jnp.maximum(jnp.sum(mask_f), 1.0)
    scale = (d * mask_f / denom)                       # [B, T]
    # d_logits = (softmax - onehot) * scale, materialized once in the
    # compute dtype; softmax recomputed from the saved logits + lse
    p = jnp.exp(logits.astype(f32) - lse[..., None])
    d_logits = (p * scale[..., None]).astype(logits.dtype)
    lab = jnp.expand_dims(labels.astype(jnp.int32), -1)
    upd = jnp.take_along_axis(d_logits, lab, axis=-1) - \
        scale[..., None].astype(d_logits.dtype)
    d_logits = jnp.put_along_axis(d_logits, lab, upd, axis=-1,
                                  inplace=False)
    from paddle_tpu.ops.numerics import mxu_cast

    dl_c, w_c, s_c = mxu_cast(d_logits, w, states)
    d_states = jnp.einsum("btv,dv->btd", dl_c, w_c,
                          preferred_element_type=f32).astype(states.dtype)
    d_w = jnp.einsum("btd,btv->dv", s_c, dl_c,
                     preferred_element_type=f32).astype(w.dtype)
    d_b = jnp.sum(d_logits.astype(f32), axis=(0, 1))
    return d_states, d_w, d_b, None, None


_ce_readout_fused.defvjp(_ce_readout_fwd, _ce_readout_bwd)


def _tiled_ce_cfg(B, T, D, V):
    """Vocab-tiled Pallas CE gate: (row_block, v_tile) or None for the XLA
    path.  Needs a TPU backend, lane-aligned D, a sublane-aligned row block
    dividing B*T, and the backward's VMEM-resident working set (full-N
    d_states accumulator + states + double-buffered logits/d_l tiles +
    lane-padded per-row vectors) must fit the raised scoped-VMEM budget —
    larger shapes fall back to the XLA path instead of failing at compile.
    V itself only sets tile padding (handled in the wrapper)."""
    import jax as _jax

    from paddle_tpu.ops.numerics import compute_dtype
    from paddle_tpu.utils.flags import FLAGS

    if not FLAGS.use_pallas_ce:
        return None
    if _jax.default_backend() not in ("tpu", "axon"):
        return None
    if D % 128:
        return None
    N = B * T
    rb = next((r for r in (2048, 1024, 512, 256, 128, 64, 32, 16, 8)
               if N % r == 0), None)
    if rb is None:
        return None
    vt = 512
    cd = jnp.dtype(compute_dtype()).itemsize
    # calibrated against the measured ~102 MB at N=12288, D=512, cd=2
    est = N * (D * (4 + cd) + vt * (2 * cd + 4) + 3 * 512)
    if est > 108 * 1024 * 1024:
        return None
    return rb, vt


@functools.lru_cache(maxsize=None)
def _tiled_ce_fn(rb, vt, V, sdt, wdt, bdt):
    """custom_vjp instance for one static (row_block, v_tile, V, dtypes)
    configuration of the vocab-tiled Pallas CE (kernels in
    ops/pallas_kernels.py: ce_readout_fwd/bwd_pallas)."""
    from paddle_tpu.ops.pallas_kernels import (ce_readout_bwd_pallas,
                                               ce_readout_fwd_pallas)

    f32 = jnp.float32

    @jax.custom_vjp
    def tiled(states, w, b, labels, mask):
        loss, _ = fwd(states, w, b, labels, mask)
        return loss

    def fwd(states, w, b, labels, mask):
        from paddle_tpu.ops.numerics import mxu_cast

        B, T, D = states.shape
        N = B * T
        sc, wc = mxu_cast(states.reshape(N, D), w)
        Vp = -(-V // vt) * vt
        w_p = jnp.pad(wc, ((0, 0), (0, Vp - V)))
        # padded vocab columns get bias -1e30: exp underflows to zero so
        # the statistics and every gradient are exact
        b_p = jnp.pad(b.astype(f32).reshape(1, V), ((0, 0), (0, Vp - V)),
                      constant_values=-1e30)
        lab = labels.astype(jnp.int32).reshape(N, 1)
        per_tok, lse, logits = ce_readout_fwd_pallas(
            sc, w_p, b_p, lab, row_block=rb, v_tile=vt)
        loss = masked_token_mean(per_tok.reshape(B, T), mask)
        # residual saves the PRIMAL w (free — aliases the input); the padded
        # compute-dtype copy is re-derived in bwd rather than pinning an
        # extra [D, Vp] buffer across the fwd->bwd interval
        return loss, (sc, w, lab, lse, logits, mask)

    def bwd(res, d):
        from paddle_tpu.ops.numerics import mxu_cast

        sc, w, lab, lse, logits, mask = res
        w_p = jnp.pad(mxu_cast(w), ((0, 0), (0, logits.shape[1] - V)))
        N, D = sc.shape
        B, T = mask.shape
        mask_f = mask.astype(f32)
        denom = jnp.maximum(jnp.sum(mask_f), 1.0)
        scale = (d * mask_f / denom).reshape(N, 1)
        d_states, d_w_p, d_b_p = ce_readout_bwd_pallas(
            logits, sc, w_p, lab, lse, scale, v_tile=vt)
        return (d_states.reshape(B, T, D).astype(sdt),
                d_w_p[:, :V].astype(wdt),
                d_b_p[0, :V].astype(bdt), None, None)

    tiled.defvjp(fwd, bwd)
    return tiled


def sequence_softmax_ce_readout(states, w, b, labels, mask):
    """Fused vocab readout + token CE: states [B, T, D] x w [D, V] -> loss.

    The O(B*T*V) logits buffer dominates HBM traffic for big-vocab decoders
    (hl_matrix crossEntropy operates on an f32 prob matrix; on TPU a 30k-vocab
    readout at B=256,T=32 is ~1GB in f32).  On TPU the whole tier runs as
    the VOCAB-TILED Pallas kernel pair (ops/pallas_kernels.py): forward
    computes each [rows, v_tile] logits tile on the MXU and folds it into
    online softmax statistics in VMEM (streaming the tile out once, in
    bf16, as the backward residual); backward reads each tile once and
    contracts (softmax - onehot)*scale straight into d_states/d_w — the
    d_logits buffer never exists in HBM.  Off-TPU (or gated shapes), the
    logits are materialized once in the compute dtype and XLA's fused
    reductions produce the statistics — both match ``linear`` +
    ``sequence_cross_entropy`` numerics to bf16 rounding.
    """
    cfg = _tiled_ce_cfg(states.shape[0], states.shape[1], states.shape[2],
                        w.shape[1])
    if cfg is not None:
        fn = _tiled_ce_fn(cfg[0], cfg[1], int(w.shape[1]),
                          str(states.dtype), str(w.dtype), str(b.dtype))
        return fn(states, w, b, labels, mask)
    if _USE_PALLAS_LSE_READOUT:
        return _ce_readout_fused(states, w, b, labels, mask)
    logits = _readout_logits(states, w, b)
    lf32 = lambda: logits.astype(jnp.float32)          # fused upcast per use
    m = jnp.max(lf32(), axis=-1, keepdims=True)
    lse = m[..., 0] + jnp.log(jnp.sum(jnp.exp(lf32() - m), axis=-1))
    lab = jnp.expand_dims(labels.astype(jnp.int32), -1)
    tok = jnp.squeeze(jnp.take_along_axis(logits, lab, axis=-1), -1)
    per_tok = lse - tok.astype(jnp.float32)
    return masked_token_mean(per_tok, mask)
