"""Dense matmul ops with the TPU dtype policy.

Analog of the reference's gemm paths: GpuMatrix::mul -> hl_matrix_mul (cuBLAS)
and CpuMatrix::mul -> cblas gemm (reference: paddle/math/Matrix.cpp:501-549,
:2357; paddle/cuda/src/hl_cuda_cublas.cc).  On TPU a single ``dot_general`` with
bf16 operands and f32 accumulation maps straight onto the MXU; XLA fuses the
bias add and activation into the same kernel.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.numerics import dot_dtype, mxu_cast

__all__ = ["matmul", "linear"]


def matmul(a, b, *, transpose_a=False, transpose_b=False):
    """MXU matmul: bf16 operands, f32 accumulation, batch dims broadcast.
    Under ``--amp`` the output stays bf16 (``dot_dtype``) so activations
    never widen between MXU ops."""
    a, b = mxu_cast(a, b)
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    out = jnp.matmul(a, b, preferred_element_type=dot_dtype())
    return out


def linear(x, w, b=None):
    """x @ w (+ b) over the last axis; any leading batch/time dims."""
    xc, wc = mxu_cast(x, w)
    y = lax.dot_general(
        xc,
        wc,
        (((xc.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=dot_dtype(),
    )
    if b is not None:
        y = y + b.astype(y.dtype)
    return y
