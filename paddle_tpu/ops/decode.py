"""Fused decode engine — ONE generation implementation for every surface.

The analog of ``RecurrentGradientMachine::generateSequence`` + ``--beam_size``
(reference: gserver/gradientmachines/RecurrentGradientMachine.cpp:383; SWIG
SequenceGenerator, paddle/api/PaddleAPI.h:1002).  Every user-facing
generation path — ``models/seq2seq.py`` ``beam_search``/``greedy_decode``,
the DSL ``SequenceGenerator`` behind ``nn/recurrent.py beam_search`` (and
through it ``v2.infer`` over a beam_search layer) — drives this engine.

What it replaces (the 13% MFU decode of BENCH_r05): a fixed-``max_len``
``lax.scan`` whose every step materialized the full [B*K, V] logits in HBM,
log-softmaxed them into a second f32 [B*K, V] buffer, and ``top_k``'d over
K*V.  Here:

- **Vocab-tiled readout kernel** (``ops/pallas_kernels.py``
  ``topk_lse_readout_pallas``): the same tiling discipline as the fused
  softmax-CE readout — ``out_w`` tiles stream through VMEM, a running
  top-k and running logsumexp per row are maintained on-chip, and neither
  the logits nor any f32 log-softmax buffer ever touches HBM.  Per row,
  k values + k indices + one logsumexp come back.  Opaque step nets that
  hand the engine pre-built logits get the one-HBM-pass variant
  (``topk_lse_logits_pallas``).
- **Early-exit driver**: a ``lax.while_loop`` that stops as soon as every
  beam has emitted EOS (finished beams only extend with EOS at zero cost
  and the token buffer is EOS-prefilled, so stopping early is
  output-identical to running all ``max_len`` steps).  ``early_exit=False``
  keeps a ``lax.scan`` driver — fixed trip count, unrollable for AOT
  export (``config/deploy`` ``unroll_scans`` cannot patch a while loop).
- **True greedy fast path**: ``greedy_decode`` runs B rows with a running
  argmax + logsumexp — no beam tiling, no K*V top-k — and is
  token-identical to ``beam_size=1`` beam search.
- **Packed beam reorder**: ``beam_gather`` reorders the whole carry
  (token buffer, state pytree, finished mask) with one fused
  ``take_along_axis`` per dtype group instead of one gather per leaf.

Per-row top-k + a small second-stage ``top_k`` over the K*k candidates is
exactly equivalent to the reference's ``top_k`` over K*V (the global top-K
is contained in the union of per-row top-Ks, and both stages tie-break
toward the lower flat index like ``lax.top_k``'s stable sort), so token
ids are bit-identical to the unfused path and scores match to float
re-association (~1e-7).

Kernel gating mirrors ``losses._tiled_ce_cfg``: TPU backend + tile-aligned
shapes + ``FLAGS.use_pallas_decode``, with the XLA ``top_k`` fallback
otherwise (A/B benched as ``pallas_decode_ab`` in bench.py).  The lowered
decode fn is auditable host-transfer-free via
``paddle_tpu.analysis.audit_decode``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "LinearReadout",
    "LogitsReadout",
    "beam_decode",
    "greedy_decode",
    "beam_gather",
    "decode_kernel_config",
    "decode_step",
    "spec_verify_step",
    "init_slot_carry",
    "write_slot",
    "release_slot",
    "extract_slot",
    "restore_slot",
    "finalize_slots",
]

#: the reference's kill score for impossible candidates (nn/recurrent.py
#: used -1e9 throughout; scores must match it exactly)
NEG = -1e9

#: static unroll bound for the kernel's running top-k (k masked-argmax
#: passes per tile; beyond this the XLA fallback is the better program)
_MAX_KERNEL_K = 16

_V_TILE = 512


def _row_block(n: int) -> Optional[int]:
    return next((r for r in (512, 256, 128, 64, 32, 16, 8) if n % r == 0),
                None)


def decode_kernel_config(n_rows: int, depth: Optional[int], vocab: int,
                         k: int) -> Optional[Tuple[int, int]]:
    """Gate for the vocab-tiled top-k readout kernel: (row_block, v_tile)
    or None for the XLA ``top_k`` fallback.  ``depth`` is the readout
    contraction dim (None for the pre-materialized-logits variant, which
    has no MXU operand to align).  Needs a TPU backend, the flag on,
    lane-aligned depth, a sublane-aligned row block dividing the rows, and
    a small static k (the kernel unrolls k merge passes per tile)."""
    from paddle_tpu.utils.flags import FLAGS

    if not FLAGS.use_pallas_decode:
        return None
    if jax.default_backend() not in ("tpu", "axon"):
        return None
    return _forced_kernel_config(n_rows, depth, vocab, k)


def _forced_kernel_config(n_rows, depth, vocab, k):
    """Shape-only half of the gate (backend/flag checks skipped) — used by
    tests and the A/B bench to exercise the kernel in interpret mode."""
    if depth is not None and depth % 128:
        return None
    if not 1 <= k <= _MAX_KERNEL_K or vocab < k:
        return None
    rb = _row_block(n_rows)
    if rb is None:
        return None
    return rb, _V_TILE


def _topk_lse_xla(logits, k):
    """XLA fallback: same (vals, idx, lse) statistics from materialized
    logits — identical math to the pre-engine ``log_softmax`` + ``top_k``
    path (log_softmax(x) = x - lse(x); the shift preserves order, so token
    selection is unchanged)."""
    lf = logits.astype(jnp.float32)
    m = jnp.max(lf, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1))
    vals, idx = lax.top_k(lf, k)
    return vals, idx.astype(jnp.int32), lse


def _pad_cols(x, vp, value):
    v = x.shape[-1]
    if vp == v:
        return x
    return jnp.pad(x, ((0, 0),) * (x.ndim - 1) + ((0, vp - v),),
                   constant_values=value)


@dataclass(frozen=True)
class LinearReadout:
    """Fused-capable readout: the step net returns pre-readout states
    [N, D]; the engine owns the [D, V] projection and never materializes
    the logits (kernel path)."""

    w: Any   # [D, V]
    b: Any   # [V]

    def __call__(self, states, k, *, use_kernel: Optional[bool] = None):
        from paddle_tpu.ops.matmul import linear
        from paddle_tpu.ops.numerics import mxu_cast

        N, D = states.shape
        V = int(self.w.shape[1])
        cfg = (_forced_kernel_config(N, D, V, k) if use_kernel
               else None if use_kernel is False
               else decode_kernel_config(N, D, V, k))
        if cfg is None:
            if use_kernel:
                raise ValueError(
                    f"decode kernel forced but shapes are gated: "
                    f"N={N}, D={D}, V={V}, k={k}")
            return _topk_lse_xla(linear(states, self.w, self.b), k)
        if use_kernel is not True and V < cfg[1] // 2:
            # tiny vocabularies: tile padding costs more than it saves
            # (same call LogitsReadout makes for the same shape class)
            return _topk_lse_xla(linear(states, self.w, self.b), k)
        from paddle_tpu.ops.pallas_kernels import topk_lse_readout_pallas

        rb, vt = cfg
        sc, wc = mxu_cast(states, self.w)
        vp = -(-V // vt) * vt
        w_p = _pad_cols(wc, vp, 0)
        b_p = _pad_cols(self.b.astype(jnp.float32).reshape(1, V), vp, -1e30)
        tv, ti, lse = topk_lse_readout_pallas(sc, w_p, b_p, vocab=V, k=k,
                                              row_block=rb, v_tile=vt)
        return tv[:, :k], ti[:, :k], lse[:, 0]


@dataclass(frozen=True)
class LogitsReadout:
    """Opaque-step readout: the step net returns full logits [N, V] (the
    DSL beam_search layer ends in an arbitrary logits layer).  The kernel
    still wins one pass over XLA's three (max, exp-sum, top-k) and skips
    the f32 log-softmax buffer."""

    def __call__(self, logits, k, *, use_kernel: Optional[bool] = None):
        N, V = logits.shape
        cfg = (_forced_kernel_config(N, None, V, k) if use_kernel
               else None if use_kernel is False
               else decode_kernel_config(N, None, V, k))
        if cfg is None:
            if use_kernel:
                raise ValueError(
                    f"decode kernel forced but shapes are gated: "
                    f"N={N}, V={V}, k={k}")
            return _topk_lse_xla(logits, k)
        if use_kernel is not True and V < cfg[1] // 2:
            # tiny vocabularies (DSL toy nets): tiling buys nothing
            return _topk_lse_xla(logits, k)
        from paddle_tpu.ops.pallas_kernels import topk_lse_logits_pallas

        rb, vt = cfg
        vp = -(-V // vt) * vt
        l_p = _pad_cols(logits, vp, -1e30)
        tv, ti, lse = topk_lse_logits_pallas(l_p, vocab=V, k=k,
                                             row_block=rb, v_tile=vt)
        return tv[:, :k], ti[:, :k], lse[:, 0]


# ---------------------------------------------------------------------------
# packed beam reorder
# ---------------------------------------------------------------------------


def beam_gather(tree, beam_idx):
    """Reorder every [B*K, ...] / [B, K, ...] leaf of ``tree`` by
    ``beam_idx`` [B, K] with ONE fused ``take_along_axis`` per dtype group:
    leaves are flattened to [B, K, F], concatenated along F per dtype,
    gathered once, and split back — instead of XLA emitting one gather per
    pytree leaf (the old per-leaf ``reorder`` tree_map)."""
    B, K = beam_idx.shape
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    flats = []
    for x in leaves:
        if x.ndim >= 2 and x.shape[0] == B and x.shape[1] == K:
            flats.append(x.reshape(B, K, -1))
        elif x.shape[0] == B * K:
            flats.append(x.reshape(B, K, -1))
        else:
            raise ValueError(
                f"beam_gather leaf has no beam axis: shape {x.shape} with "
                f"B={B}, K={K}")
    groups = {}
    for i, f in enumerate(flats):
        groups.setdefault(jnp.dtype(f.dtype), []).append(i)
    out = [None] * len(leaves)
    for dt, idxs in groups.items():
        packed = (flats[idxs[0]] if len(idxs) == 1 else
                  jnp.concatenate([flats[i] for i in idxs], axis=-1))
        packed = jnp.take_along_axis(packed, beam_idx[..., None], axis=1)
        off = 0
        for i in idxs:
            w = flats[i].shape[-1]
            out[i] = packed[..., off:off + w].reshape(leaves[i].shape)
            off += w
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# candidate helpers
# ---------------------------------------------------------------------------


def _eos_candidates(vocab: int, k: int, eos: int):
    """The per-row candidate list of a FINISHED beam, in ``lax.top_k``
    order over the reference's eos-only row (EOS at 0, everything else at
    NEG): EOS first at zero cost, then the lowest non-EOS token ids at the
    kill score.  Matches the unfused path's selection bit-for-bit when a
    finished beam's junk candidates reach the global top-K."""
    toks = [eos] + [v for v in range(vocab) if v != eos][:k - 1]
    toks += [eos] * (k - len(toks))          # k > vocab: EOS filler
    vals = [0.0] + [NEG] * (k - 1)
    return (jnp.asarray(toks, jnp.int32), jnp.asarray(vals, jnp.float32))


def _loop(cond_extra, body, carry, max_len: int, early_exit: bool):
    """Driver control flow: a ``while_loop`` with the all-finished early
    exit, or a fixed-trip ``scan`` (AOT-unrollable) when ``early_exit`` is
    off.  ``carry[0]`` is the step counter."""
    if early_exit:
        return lax.while_loop(
            lambda c: (c[0] < max_len) & cond_extra(c), body, carry)

    def scan_step(c, _):
        return body(c), None

    out, _ = lax.scan(scan_step, carry, None, length=max_len)
    return out


def _resolve_early_exit(early_exit: Optional[bool]) -> bool:
    if early_exit is not None:
        return bool(early_exit)
    from paddle_tpu.utils.flags import FLAGS

    return bool(FLAGS.decode_early_exit)


# ---------------------------------------------------------------------------
# the slot-table single-step API (continuous batching; docs/serving.md)
# ---------------------------------------------------------------------------
#
# The carry of a fixed-capacity decode table of S slots, each holding one
# request's K beams — the recurrent/attention state is the KV-cache
# analogue.  A dict pytree so the whole table jits as one argument:
#
#   tokens   [S, K, max_len+1] i32   EOS-prefilled token buffers, BOS at 0
#   logp     [S, K] f32              cumulative beam log-probs
#   state    pytree, leading dim S*K (beam-tiled model carry; leaves may
#                                     also be [S, K, ...])
#   finished [S, K] bool             per-beam EOS mask
#   active   [S] bool                slot occupancy (host-managed)
#   step     [S] i32                 per-slot step count
#
# ``decode_step`` advances every ACTIVE slot by one token — inactive slots
# are frozen bit-for-bit, so a harvested-but-not-yet-refilled slot holds
# its result untouched across steps.  Because every per-row computation in
# the engine (readout matmul, top-k, gather) is row-independent, an active
# slot advances exactly as the same request would inside a solo
# ``beam_decode`` batch: per-request outputs are bit-identical regardless
# of which other requests share the table (pinned by
# tests/test_serving_slots.py).


def decode_step(step_fn: Callable, readout, carry: dict, *, vocab_size: int,
                eos: int = 1, use_kernel: Optional[bool] = None) -> dict:
    """ONE fused decode step over a slot table (the reusable body of
    ``beam_decode``'s loop).  ``step_fn(tokens [S*K] i32, state) ->
    (readout_input, new_state)`` exactly as in ``beam_decode``; per-slot
    ``active``/``step`` masks freeze finished/unoccupied slots and let
    every slot run at its own position in its token buffer."""
    # named_scope: profiler captures (paddle_tpu/obs, --profile_steps)
    # show one legible "decode_step" block per token instead of raw ops
    with jax.named_scope("decode_step"):
        return _decode_step_inner(step_fn, readout, carry,
                                  vocab_size=vocab_size, eos=eos,
                                  use_kernel=use_kernel)


def _decode_step_inner(step_fn, readout, carry, *, vocab_size, eos,
                       use_kernel):
    tokens, logp = carry["tokens"], carry["logp"]
    state, finished = carry["state"], carry["finished"]
    active, step = carry["active"], carry["step"]
    S, K, Lp1 = tokens.shape
    kr = min(K, vocab_size)        # per-row candidates: top-K needs ≤ V
    fin_toks, fin_vals = _eos_candidates(vocab_size, kr, eos)

    # each slot reads the token at ITS OWN step position
    y = jnp.take_along_axis(
        tokens, jnp.broadcast_to(step[:, None, None], (S, K, 1)).astype(
            jnp.int32), axis=2)[..., 0]
    r_in, state_new = step_fn(y.reshape(S * K), state)
    vals, idx, lse = readout(r_in, kr, use_kernel=use_kernel)
    row_logp = (vals - lse[:, None]).reshape(S, K, kr)
    row_idx = idx.reshape(S, K, kr)
    # finished beams may only emit EOS at zero cost (per-slot EOS masking)
    row_logp = jnp.where(finished[..., None], fin_vals[None, None], row_logp)
    row_idx = jnp.where(finished[..., None], fin_toks[None, None], row_idx)
    flat = (logp[..., None] + row_logp).reshape(S, K * kr)
    new_logp, flat_ix = lax.top_k(flat, K)
    beam_ix = flat_ix // kr
    tok = jnp.take_along_axis(row_idx.reshape(S, K * kr), flat_ix, axis=1)
    # one packed gather reorders the whole carry
    tokens_g, state_g, finished_g = beam_gather(
        (tokens, state_new, finished), beam_ix)
    pos = (jnp.arange(Lp1, dtype=jnp.int32)[None, :]
           == (step + 1)[:, None])                      # [S, Lp1]
    tokens_g = jnp.where(pos[:, None, :], tok[:, :, None], tokens_g)
    finished_g = finished_g | (tok == eos)

    # freeze inactive slots bit-for-bit (state leaves may be [S*K, ...] or
    # [S, K, ...] — beam_gather's contract)
    row_keep = jnp.repeat(active, K)

    def _sel(new, old):
        if new.shape[0] == S * K:
            m = row_keep.reshape((S * K,) + (1,) * (new.ndim - 1))
        else:
            m = active.reshape((S,) + (1,) * (new.ndim - 1))
        return jnp.where(m, new, old)

    return {
        "tokens": jnp.where(active[:, None, None], tokens_g, tokens),
        "logp": jnp.where(active[:, None], new_logp, logp),
        "state": jax.tree_util.tree_map(_sel, state_g, state),
        "finished": jnp.where(active[:, None], finished_g, finished),
        "active": active,
        "step": jnp.where(active, step + 1, step),
    }


def init_slot_carry(state_template, *, slots: int, beam_size: int,
                    max_len: int, eos: int = 1) -> dict:
    """An EMPTY slot table: every slot inactive and finished, token buffers
    EOS-prefilled, state leaves zero-filled at the beam-tiled shapes.
    ``state_template`` is a per-sequence state pytree with leading dim 1 on
    every leaf (arrays or ``ShapeDtypeStruct``s — e.g. from
    ``jax.eval_shape`` over a prefill)."""
    S, K = int(slots), int(beam_size)

    def make(leaf):
        return jnp.zeros((S * K,) + tuple(leaf.shape[1:]), leaf.dtype)

    return {
        "tokens": jnp.full((S, K, max_len + 1), eos, jnp.int32),
        "logp": jnp.tile(
            jnp.asarray([0.0] + [NEG] * (K - 1), jnp.float32)[None], (S, 1)),
        "state": jax.tree_util.tree_map(make, state_template),
        "finished": jnp.ones((S, K), bool),
        "active": jnp.zeros((S,), bool),
        "step": jnp.zeros((S,), jnp.int32),
    }


def write_slot(carry: dict, slot, state0, *, bos: int = 0,
               eos: int = 1, row=0) -> dict:
    """Prefill: admit one request into slot ``slot`` WITHOUT recompiling —
    ``slot`` and ``row`` are traced scalars, so one compiled program serves
    every slot index.  ``state0`` is a prefill-output pytree with a leading
    batch dim; row ``row`` of it is beam-tiled to K rows and written over
    the slot's rows [slot*K, slot*K+K).  The slot's token buffer, scores,
    and masks are reset; it comes back active at step 0."""
    tokens, logp = carry["tokens"], carry["logp"]
    S, K, Lp1 = tokens.shape
    slot = jnp.asarray(slot, jnp.int32)
    row = jnp.asarray(row, jnp.int32)

    def put(table, leaf):
        one = lax.dynamic_slice_in_dim(leaf, row, 1, axis=0)
        tiled = jnp.repeat(one, K, axis=0).astype(table.dtype)
        return lax.dynamic_update_slice_in_dim(table, tiled, slot * K, axis=0)

    row_tokens = jnp.full((1, K, Lp1), eos, jnp.int32).at[:, :, 0].set(bos)
    row_logp = jnp.asarray([0.0] + [NEG] * (K - 1), jnp.float32)[None]
    return {
        "tokens": lax.dynamic_update_slice(tokens, row_tokens, (slot, 0, 0)),
        "logp": lax.dynamic_update_slice(logp, row_logp, (slot, 0)),
        "state": jax.tree_util.tree_map(put, carry["state"], state0),
        "finished": carry["finished"].at[slot].set(jnp.zeros((K,), bool)),
        "active": carry["active"].at[slot].set(True),
        "step": carry["step"].at[slot].set(0),
    }


def release_slot(carry: dict, slot) -> dict:
    """Free slot ``slot`` (harvest or eviction): inactive + all-finished,
    so ``decode_step`` freezes it until the next ``write_slot``."""
    K = carry["tokens"].shape[1]
    slot = jnp.asarray(slot, jnp.int32)
    return dict(
        carry,
        active=carry["active"].at[slot].set(False),
        finished=carry["finished"].at[slot].set(jnp.ones((K,), bool)),
    )


def spec_verify_step(step_fn: Callable, readout, carry: dict, drafts,
                     cap, *, vocab_size: int, eos: int = 1,
                     use_kernel: Optional[bool] = None):
    """ONE fused wide-verify step for speculative decoding over a GREEDY
    (``beam_size == 1``) slot table: per active slot, score the current
    token plus ``k`` host-proposed draft tokens in one call and emit the
    longest prefix the model itself would have produced — between 1 and
    ``k + 1`` tokens per slot per dispatch.

    ``drafts`` is ``[S, k] i32`` (host draft proposals per slot —
    ``ops/speculative.py``); ``cap`` is ``[S] i32``, the per-slot
    remaining decode budget (``limit - tokens_emitted``), which bounds
    emission so cumulative scores never accumulate past the request's
    own ``max_len``.  Returns ``(new_carry, aux)`` with ``aux =
    {"emitted": [S, k+1] i32, "n": [S] i32, "accepted": [S] i32}`` —
    the emitted tokens (EOS-filled past ``n``), tokens emitted, and
    draft tokens accepted.

    Bit-identity with the one-token path is *provable*, not
    approximate, because greedy verification IS the greedy decode rule:

    - position ``j``'s input is the previous emission in the solo run;
      a draft position only stays "emitting" while every earlier draft
      matched the model's own greedy emission (or the row already
      finished, where emissions are forced EOS at zero cost regardless
      of state), so every scored-and-accepted position saw exactly the
      state the solo run would have had — readout and ``step_fn`` are
      row-independent and batch-size-invariant (the same invariant that
      makes the slot table itself bit-identical to solo decode);
    - ``logp`` accumulates sequentially position by position in the
      same float-addition order as one-token stepping;
    - the carried state is SELECTED from the scoring sweep itself: the
      recurrence is row-independent, so row ``r``'s state after chain
      position ``j`` depends only on row ``r``'s inputs ``x_0..x_j`` —
      for a row that emitted ``n`` tokens those are exactly the tokens
      the solo run would have fed, so the sweep state at position
      ``n - 1`` IS the solo state, bit for bit (positions past ``n``
      are garbage for that row and are never selected).  One recurrence
      pass total; the cost is holding ``k + 1`` transient state copies
      through the select, which XLA frees within the step; the readout
      (the [D, V] matmul that dominates) runs ONCE per position,
      batched as a single ``(k+1)·S``-row call.

    Inactive slots are frozen bit-for-bit, as in :func:`decode_step`.
    Beam search (``beam_size > 1``) has no greedy-verify equivalent —
    callers fall back to the standard :func:`decode_step` path.
    """
    with jax.named_scope("spec_verify_step"):
        return _spec_verify_inner(step_fn, readout, carry, drafts, cap,
                                  vocab_size=vocab_size, eos=eos,
                                  use_kernel=use_kernel)


def _spec_verify_inner(step_fn, readout, carry, drafts, cap, *,
                       vocab_size, eos, use_kernel):
    tokens, logp = carry["tokens"], carry["logp"]
    state, finished = carry["state"], carry["finished"]
    active, step = carry["active"], carry["step"]
    S, K, Lp1 = tokens.shape
    if K != 1:
        raise ValueError(
            f"spec_verify_step is a greedy path: beam_size must be 1, "
            f"got K={K} (beam search falls back to decode_step)")
    drafts = jnp.asarray(drafts, jnp.int32)
    cap = jnp.asarray(cap, jnp.int32)
    k = int(drafts.shape[1])

    # position inputs: x_0 = each slot's current token, x_j = draft j-1
    y0 = jnp.take_along_axis(
        tokens, jnp.broadcast_to(step[:, None, None], (S, K, 1)).astype(
            jnp.int32), axis=2)[..., 0].reshape(S)
    xs = jnp.concatenate([y0[None, :], drafts.T], axis=0)   # [k+1, S]

    # scoring sweep: scan the recurrence through all k+1 positions
    # collecting readout inputs AND the state after each position, then
    # ONE wide readout over (k+1)*S rows at k=1 (greedy).  A scan (not
    # an unrolled loop) keeps the compiled program one step-body deep
    # regardless of k — at small step shapes the program's instruction
    # count, not its flops, is what the per-position overhead tracks.
    # Row independence makes each row's (vals, idx, lse) identical to
    # the solo per-step readout.
    #
    # Pass-through state leaves — ones step_fn returns UNMODIFIED (the
    # same traced value), e.g. encoder context / attention masks — are
    # detected by object identity during the single body trace and
    # excluded from the stacked scan outputs: by induction they equal
    # the initial state at every position, so the select below would
    # always return the original anyway, and stacking k+1 copies of an
    # [S, src_len, D] encoder costs more than the recurrence itself.
    changed: List[bool] = []

    def _sweep(st_c, x):
        r_in, st_n = step_fn(x, st_c)
        in_leaves = jax.tree_util.tree_leaves(st_c)
        out_leaves = jax.tree_util.tree_leaves(st_n)
        if not changed:
            changed.extend(o is not i
                           for o, i in zip(out_leaves, in_leaves))
        ys = tuple(o for o, c in zip(out_leaves, changed) if c)
        return st_n, (r_in, ys)

    _, (r_all, st_stack) = lax.scan(_sweep, state, xs)
    vals, idx, lse = readout(r_all.reshape((-1,) + r_all.shape[2:]), 1,
                             use_kernel=use_kernel)
    # barrier: without it XLA CPU duplicates the (k+1)*S-row argmax /
    # log-sum-exp reduction into every one of the ~k*S tiny accept-mask
    # consumers below (producer-fusion), turning one readout into tens —
    # measured ~8x the whole step.  The barrier pins the readout to run
    # once; outputs are bit-identical either way.
    vals, idx, lse = jax.lax.optimization_barrier((vals, idx, lse))
    g = idx[:, 0].reshape(k + 1, S)            # greedy token per position
    lp = (vals[:, 0] - lse).reshape(k + 1, S)  # its log-prob

    # accept/emit: 'emitting' is sticky per row — a position emits only
    # while every earlier draft input matched the row's own emission
    # (or the row is finished: forced EOS at zero cost, state-independent)
    # and the budget cap is not exhausted.
    fin = finished[:, 0]
    logp_new = logp[:, 0]
    emitting = active & (cap > 0)
    n = jnp.zeros((S,), jnp.int32)
    acc = jnp.zeros((S,), jnp.int32)
    em = []
    for j in range(k + 1):
        if j:
            matched = drafts[:, j - 1] == em[j - 1]
            emitting = emitting & (fin | matched) & (n < cap)
            acc = acc + (emitting & ~fin).astype(jnp.int32)
        e_j = jnp.where(fin, eos, g[j])
        # sequential accumulation in solo order (finished rows add the
        # same 0.0 the one-token path's EOS candidate adds)
        logp_new = jnp.where(emitting,
                             logp_new + jnp.where(fin, 0.0, lp[j]),
                             logp_new)
        em.append(jnp.where(emitting, e_j, eos))
        n = n + emitting.astype(jnp.int32)
        fin = fin | (emitting & (e_j == eos))
    em_arr = jnp.stack(em, axis=1)                       # [S, k+1]

    # token-buffer epilogue: write the n emitted tokens at each slot's
    # own position (offsets past n keep the old — EOS-prefilled — buffer)
    off = jnp.arange(Lp1, dtype=jnp.int32)[None, :] - (step[:, None] + 1)
    sel = (off >= 0) & (off < n[:, None])                # [S, Lp1]
    gathered = jnp.take_along_axis(em_arr, jnp.clip(off, 0, k), axis=1)
    tokens_new = jnp.where(sel[:, None, :], gathered[:, None, :], tokens)

    # state select: fold the sweep states down to each row's own stop
    # position.  Rows that emitted n tokens keep sweep state n-1 (their
    # inputs 0..n-1 were exactly the solo inputs — row independence);
    # rows with n == 0 keep the original state, frozen bit-for-bit.
    # One gather per CHANGING leaf; pass-through leaves keep the
    # original untouched (provably equal at every sweep position).
    pos = jnp.clip(n - 1, 0, k)                          # [S]
    live = n > 0

    def _pick(stacked, orig):
        il = pos.reshape((1, S) + (1,) * (orig.ndim - 1))
        sel = jnp.take_along_axis(stacked, il, axis=0)[0]
        m = live.reshape((S,) + (1,) * (orig.ndim - 1))
        return jnp.where(m, sel, orig)

    st_leaves, st_def = jax.tree_util.tree_flatten(state)
    it = iter(st_stack)
    st_leaves = [(_pick(next(it), leaf) if ch else leaf)
                 for leaf, ch in zip(st_leaves, changed)]
    st = jax.tree_util.tree_unflatten(st_def, st_leaves)

    new_carry = {
        "tokens": tokens_new,
        "logp": logp_new[:, None],
        "state": st,
        "finished": fin[:, None],
        "active": active,
        "step": step + n,
    }
    return new_carry, {"emitted": em_arr, "n": n, "accepted": acc}


def extract_slot(carry: dict, slot) -> dict:
    """Page-out: one slot's full decode context — token buffer, scores,
    state rows, finished mask, step — as a small per-slot pytree ready
    for a host round-trip (serving/paging.py).  ``slot`` is a traced
    scalar, mirroring :func:`write_slot`'s one-program-per-table
    discipline.  The d2h/h2d round trip preserves every bit, so a
    paged-out-and-restored slot decodes exactly as if it had never
    left the table (pinned by tests)."""
    tokens = carry["tokens"]
    S, K, Lp1 = tokens.shape
    slot = jnp.asarray(slot, jnp.int32)

    def take(leaf):
        if leaf.shape[0] == S * K:
            return lax.dynamic_slice_in_dim(leaf, slot * K, K, axis=0)
        if leaf.shape[0] == S:
            return lax.dynamic_slice_in_dim(leaf, slot, 1, axis=0)
        raise ValueError(
            f"extract_slot leaf has no slot axis: shape {leaf.shape} "
            f"with S={S}, K={K}")

    return {
        "tokens": lax.dynamic_slice(tokens, (slot, 0, 0), (1, K, Lp1)),
        "logp": lax.dynamic_slice(carry["logp"], (slot, 0), (1, K)),
        "state": jax.tree_util.tree_map(take, carry["state"]),
        "finished": lax.dynamic_slice(carry["finished"], (slot, 0), (1, K)),
        "step": lax.dynamic_slice(carry["step"], (slot,), (1,)),
    }


def restore_slot(carry: dict, slot, saved: dict) -> dict:
    """Page-in: write an :func:`extract_slot` snapshot back into slot
    ``slot`` (traced scalar) and re-activate it at its saved step — the
    re-admission half of host-paged slot state.  The inverse of
    :func:`extract_slot` up to bit identity."""
    tokens = carry["tokens"]
    S, K, Lp1 = tokens.shape
    slot = jnp.asarray(slot, jnp.int32)

    def put(table, piece):
        piece = piece.astype(table.dtype)
        if table.shape[0] == S * K:
            return lax.dynamic_update_slice_in_dim(table, piece, slot * K,
                                                   axis=0)
        return lax.dynamic_update_slice_in_dim(table, piece, slot, axis=0)

    return {
        "tokens": lax.dynamic_update_slice(
            tokens, saved["tokens"].astype(jnp.int32), (slot, 0, 0)),
        "logp": lax.dynamic_update_slice(
            carry["logp"], saved["logp"].astype(jnp.float32), (slot, 0)),
        "state": jax.tree_util.tree_map(put, carry["state"],
                                        saved["state"]),
        "finished": lax.dynamic_update_slice(
            carry["finished"], saved["finished"], (slot, 0)),
        "active": carry["active"].at[slot].set(True),
        "step": lax.dynamic_update_slice(
            carry["step"], saved["step"].astype(jnp.int32), (slot,)),
    }


def _finalize(tokens, logp, *, eos: int, length_penalty: float):
    """The shared decode epilogue: strip BOS, apply the length penalty,
    sort beams best-first.  ``beam_decode`` and the slot harvest MUST go
    through this one implementation — per-request bit-identity between the
    two paths is structural, not coincidental."""
    out = tokens[:, :, 1:]
    if length_penalty > 0:
        lengths = jnp.sum((out != eos).astype(jnp.float32), axis=-1) + 1.0
        scores = logp / jnp.power(lengths, length_penalty)
    else:
        scores = logp
    order = jnp.argsort(-scores, axis=1)
    out = jnp.take_along_axis(out, order[..., None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return out, scores


def finalize_slots(carry: dict, *, eos: int = 1,
                   length_penalty: float = 0.0):
    """Harvest view of the WHOLE table: ``(tokens [S, K, max_len],
    scores [S, K])`` sorted best-first per slot — the slot analog of
    ``beam_decode``'s return.  Positions a slot never reached are
    EOS-prefilled, so slicing a harvested slot to its request's own
    ``max_len`` yields exactly the solo ``beam_decode(max_len=...)``
    output (length counts, and hence penalized scores, agree because the
    tail is all EOS)."""
    return _finalize(carry["tokens"], carry["logp"], eos=eos,
                     length_penalty=length_penalty)


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def beam_decode(step_fn: Callable, readout, state0, *, batch_size: int,
                beam_size: int, vocab_size: int, max_len: int,
                bos: int = 0, eos: int = 1, length_penalty: float = 0.0,
                early_exit: Optional[bool] = None,
                use_kernel: Optional[bool] = None):
    """Batched beam search over a functional step protocol.

    ``step_fn(tokens [B*K] i32, state) -> (readout_input, new_state)``
    where ``state`` is a pytree with leading dim B*K (``state0`` arrives
    per-sequence with leading dim B and is beam-tiled here) and
    ``readout_input`` is whatever ``readout`` consumes (pre-readout states
    for ``LinearReadout``, full logits for ``LogitsReadout``).

    Returns ``(tokens [B, K, max_len], scores [B, K])`` sorted best-first —
    the exact output contract (and, token-for-token, the exact output) of
    the pre-engine scan path.  ``early_exit``/``use_kernel`` default to
    FLAGS.decode_early_exit / the ``decode_kernel_config`` gate.

    The loop body IS :func:`decode_step` over an always-active slot table
    of B slots — the whole-batch and continuous-batching paths share one
    step implementation."""
    B, K, V = batch_size, beam_size, vocab_size
    early = _resolve_early_exit(early_exit)

    state = jax.tree_util.tree_map(lambda x: jnp.repeat(x, K, axis=0), state0)
    sc = {
        "tokens": jnp.full((B, K, max_len + 1), eos, jnp.int32)
                     .at[:, :, 0].set(bos),
        "logp": jnp.tile(
            jnp.asarray([0.0] + [NEG] * (K - 1), jnp.float32)[None], (B, 1)),
        "state": state,
        "finished": jnp.zeros((B, K), bool),
        "active": jnp.ones((B,), bool),
        "step": jnp.zeros((B,), jnp.int32),
    }

    def body(carry):
        t, sc = carry
        return t + 1, decode_step(step_fn, readout, sc, vocab_size=V,
                                  eos=eos, use_kernel=use_kernel)

    carry = (jnp.asarray(0, jnp.int32), sc)
    _, sc = _loop(
        lambda c: jnp.logical_not(jnp.all(c[1]["finished"])), body, carry,
        max_len, early)
    return _finalize(sc["tokens"], sc["logp"], eos=eos,
                     length_penalty=length_penalty)


def greedy_decode(step_fn: Callable, readout, state0, *, batch_size: int,
                  vocab_size: int, max_len: int, bos: int = 0, eos: int = 1,
                  early_exit: Optional[bool] = None,
                  use_kernel: Optional[bool] = None):
    """True greedy fast path: B rows (no beam tiling), running argmax +
    logsumexp via the same readout (k=1 — no K*V top-k anywhere), early
    exit when every row has emitted EOS.  Token-identical to
    ``beam_decode(beam_size=1)``'s best beam; returns
    ``(tokens [B, max_len], scores [B])``."""
    B, V = batch_size, vocab_size
    early = _resolve_early_exit(early_exit)

    tokens = jnp.full((B, max_len + 1), eos, jnp.int32).at[:, 0].set(bos)
    logp = jnp.zeros((B,), jnp.float32)
    finished = jnp.zeros((B,), bool)

    def body(carry):
        t, tokens, logp, state, finished = carry
        y = lax.dynamic_index_in_dim(tokens, t, axis=1, keepdims=False)
        r_in, state_new = step_fn(y, state)
        vals, idx, lse = readout(r_in, 1, use_kernel=use_kernel)
        tok = jnp.where(finished, eos, idx[:, 0])
        logp = logp + jnp.where(finished, 0.0, vals[:, 0] - lse)
        tokens = tokens.at[:, t + 1].set(tok)
        finished = finished | (tok == eos)
        return t + 1, tokens, logp, state_new, finished

    carry = (jnp.asarray(0, jnp.int32), tokens, logp, state0, finished)
    _, tokens, logp, _, _ = _loop(
        lambda c: jnp.logical_not(jnp.all(c[4])), body, carry, max_len,
        early)
    return tokens[:, 1:], logp
