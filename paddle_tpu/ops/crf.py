"""Linear-chain CRF — analog of the reference's CRF layers.

Reference: LinearChainCRF forward/backward/decode
(paddle/gserver/layers/LinearChainCRF.{h,cpp}; CRFLayer.cpp cost,
CRFDecodingLayer.cpp viterbi) with weight layout: start transition a[C],
end transition b[C], pairwise w[C,C].

TPU-first: forward algorithm and Viterbi are ``lax.scan`` over time on padded
[B,T,C] emissions with masks (carry-through past each row's length), entirely
batched — no per-sequence host loop.  All in f32 log-space.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["crf_log_likelihood", "crf_nll", "crf_decode"]


def _scan_alpha(emissions, mask, start, trans):
    """log-alpha recursion; returns final alpha [B,C] (at each row's last
    real step, via carry-through)."""
    B, T, C = emissions.shape
    e_tb = jnp.moveaxis(emissions, 1, 0)
    m_tb = jnp.moveaxis(mask, 1, 0)
    alpha0 = start[None, :] + e_tb[0]

    def step(alpha, inp):
        e_t, m_t = inp
        # [B, C_prev, 1] + [C_prev, C_next] -> logsumexp over prev
        nxt = jax.scipy.special.logsumexp(alpha[:, :, None] + trans[None], axis=1)
        new = nxt + e_t
        keep = m_t[:, None] > 0
        return jnp.where(keep, new, alpha), None

    alpha, _ = lax.scan(step, alpha0, (e_tb[1:], m_tb[1:]))
    return alpha


def crf_log_likelihood(emissions, tags, mask, start, end, trans):
    """Per-sequence log P(tags | emissions). emissions [B,T,C] (f32 logits),
    tags [B,T] int, mask [B,T]. Returns [B]."""
    emissions = emissions.astype(jnp.float32)
    B, T, C = emissions.shape
    tags = tags.astype(jnp.int32)
    m = mask.astype(jnp.float32)

    # --- numerator: score of the given path ---
    emit_sc = jnp.take_along_axis(emissions, tags[..., None], axis=-1)[..., 0]
    emit_score = jnp.sum(emit_sc * m, axis=1)
    start_score = jnp.take(start, tags[:, 0])
    # transitions where both positions are real
    pair_m = m[:, 1:] * m[:, :-1]
    tr = trans[tags[:, :-1], tags[:, 1:]]
    trans_score = jnp.sum(tr * pair_m, axis=1)
    lengths = jnp.sum(m, axis=1).astype(jnp.int32)
    last_tags = jnp.take_along_axis(tags, jnp.maximum(lengths - 1, 0)[:, None], axis=1)[:, 0]
    end_score = jnp.take(end, last_tags)
    score = emit_score + start_score + trans_score + end_score

    # --- partition function ---
    alpha = _scan_alpha(emissions, m, start, trans)
    logz = jax.scipy.special.logsumexp(alpha + end[None, :], axis=-1)
    return score - logz


def crf_nll(emissions, tags, mask, start, end, trans):
    """Mean negative log-likelihood over the batch (CRFLayer cost analog)."""
    ll = crf_log_likelihood(emissions, tags, mask, start, end, trans)
    return -jnp.mean(ll)


def crf_decode(emissions, mask, start, end, trans) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Viterbi decode. Returns (best_tags [B,T] int32, best_score [B]).
    Padded positions get tag 0. (CRFDecodingLayer analog.)"""
    emissions = emissions.astype(jnp.float32)
    B, T, C = emissions.shape
    m = mask.astype(jnp.float32)
    e_tb = jnp.moveaxis(emissions, 1, 0)
    m_tb = jnp.moveaxis(m, 1, 0)
    delta0 = start[None, :] + e_tb[0]

    def fwd(delta, inp):
        e_t, m_t = inp
        cand = delta[:, :, None] + trans[None]          # [B, prev, next]
        best_prev = jnp.argmax(cand, axis=1)            # [B, next]
        new = jnp.max(cand, axis=1) + e_t
        keep = m_t[:, None] > 0
        delta_out = jnp.where(keep, new, delta)
        # identity backpointer on padded steps keeps backtrace consistent
        bp = jnp.where(keep, best_prev, jnp.arange(C)[None, :])
        return delta_out, bp

    delta, bps = lax.scan(fwd, delta0, (e_tb[1:], m_tb[1:]))  # bps [T-1,B,C]
    final = delta + end[None, :]
    best_last = jnp.argmax(final, axis=-1).astype(jnp.int32)  # [B]
    best_score = jnp.max(final, axis=-1)

    def back(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev.astype(jnp.int32), tag

    first_tag, rest = lax.scan(back, best_last, bps, reverse=True)
    tags = jnp.concatenate([first_tag[None], jnp.moveaxis(rest, 0, 0)], axis=0)
    # rest is [T-1, B] of tags for positions 1..T-1 (scan emits carry pre-update,
    # reversed); first_tag is position 0
    tags_bt = jnp.moveaxis(tags, 0, 1)
    return (tags_bt * m.astype(jnp.int32)), best_score
