"""Recurrent cells and full-sequence RNN ops — analog of the reference's RNN tier.

Reference surface: fused LSTM/GRU cell kernels (paddle/cuda/src/hl_cuda_lstm.cu,
hl_lstm_ops.cuh / hl_gru_ops.cuh, with peephole "check" weights) and the
batch-per-timestep scheduling that keeps one matmul per step over all active
sequences (gserver/layers/SequenceToBatch.h:23-46, LstmLayer.cpp,
GatedRecurrentLayer.cpp, --rnn_use_batch).

TPU-first design:
- The input projection for *all* timesteps is hoisted out of the recurrence as
  one [B*T, D] x [D, 4H] MXU matmul (the analog of the reference pre-computing
  ``input * W`` before the frame loop).
- The recurrence itself is a ``lax.scan`` over time with a single [B, H] x
  [H, 4H] matmul per step — XLA compiles the scan once; no Python frame loop.
- Variable length is handled by masking: past a row's length the state carries
  through unchanged, which makes ``h[:, L-1]`` == final state, matching the
  reference's flat-sequence semantics without padding-dependent results.
- Cells are exposed separately (``lstm_step``/``gru_step``) for the decoder /
  recurrent-group machinery and beam search.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.matmul import linear, matmul
from paddle_tpu.ops.activations import get_activation

__all__ = [
    "lstm_step",
    "gru_step",
    "lstm_layer",
    "gru_layer",
    "bigru_layer",
    "scan_rnn",
]


def _use_pallas_rnn(batch, hidden) -> bool:
    """Fused Pallas time-loop kernels run on TPU for the default-activation
    cell (callers enforce acts; peepholes are supported in-kernel; boot
    state and reverse ride flip/flag upstream) and only for tile-aligned
    shapes: the kernels slice gate blocks out of [B, gates*H], so H must
    fill whole 128-lane tiles and B whole 8-sublane tiles or Mosaic rejects
    the lowering."""
    if hidden % 128 != 0 or batch % 8 != 0:
        return False
    # the fused kernel's per-step working set ([B, gates*H] blocks + carry)
    # must fit Mosaic's 16MB scoped VMEM; measured limit on v5e: B*H=384*512
    # compiles, 512*512 OOMs -> gate at 384*512 and fall back to the scan path
    if batch * hidden > 384 * 512:
        return False
    from paddle_tpu.utils.flags import FLAGS

    if not FLAGS.use_pallas_rnn:
        return False
    return jax.default_backend() in ("tpu", "axon")


def lstm_step(xp, h, c, w_h, *, peep_i=None, peep_f=None, peep_o=None,
              act="tanh", gate_act="sigmoid", state_act="tanh"):
    """One LSTM step. xp: [B, 4H] precomputed input projection (+bias),
    h/c: [B, H], w_h: [H, 4H]. Gate layout: [i, f, o, g].

    Peepholes (``check`` weights in the reference's hl_lstm_ops.cuh) are
    optional per-unit vectors applied as in legacy Paddle: i,f see c_{t-1},
    o sees c_t.
    """
    ga = get_activation(gate_act)
    sa = get_activation(state_act)
    aa = get_activation(act)
    z = xp + linear(h, w_h)
    i, f, o, g = jnp.split(z, 4, axis=-1)
    if peep_i is not None:
        i = i + peep_i.astype(z.dtype) * c
    if peep_f is not None:
        f = f + peep_f.astype(z.dtype) * c
    i, f = ga(i), ga(f)
    c_new = f * c + i * aa(g)
    if peep_o is not None:
        o = o + peep_o.astype(z.dtype) * c_new
    o = ga(o)
    h_new = o * sa(c_new)
    return h_new, c_new


def gru_step(xp, h, w_h, *, act="tanh", gate_act="sigmoid"):
    """One GRU step. xp: [B, 3H] input projection (+bias), gate layout
    [r, u, c]; w_h: [H, 3H] with the candidate block applied to (r * h),
    matching the reference's GatedRecurrentLayer formulation."""
    ga = get_activation(gate_act)
    aa = get_activation(act)
    H = h.shape[-1]
    w_gates = w_h[:, : 2 * H]
    w_cand = w_h[:, 2 * H :]
    zr = xp[..., : 2 * H] + linear(h, w_gates)
    r, u = jnp.split(ga(zr), 2, axis=-1)
    cand = aa(xp[..., 2 * H :] + linear(r * h, w_cand))
    h_new = u * h + (1.0 - u) * cand
    return h_new


def scan_rnn(step_fn, carry_init, xs_btd, mask_bt, *, reverse=False,
             reset_bt=None):
    """Scan ``step_fn(carry, x_t) -> (carry, out_t)`` over time with length
    masking: where mask==0 the carry is held, out is zeroed.

    xs may be a pytree of [B, T, ...] arrays; outputs are [B, T, ...].

    ``reset_bt`` ([B,T], optional) marks SEQUENCE-PACKING boundaries
    (ops/sequence.segment_starts): where it is 1 the incoming carry is
    replaced by ``carry_init`` before the step, so recurrent state never
    flows from one packed segment into the next — each segment computes
    exactly what it would alone in its own row (docs/data.md).
    """
    T = mask_bt.shape[1]
    xs_tb = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, 1, 0), xs_btd)
    mask_tb = jnp.moveaxis(mask_bt, 1, 0)
    reset_tb = (None if reset_bt is None
                else jnp.moveaxis(reset_bt, 1, 0))

    def masked_step(carry, inp):
        if reset_tb is None:
            x_t, m_t = inp
        else:
            x_t, m_t, r_t = inp

            def re(init, c):
                r = r_t.reshape(r_t.shape + (1,) * (c.ndim - 1))
                return jnp.where(r.astype(c.dtype) > 0, init, c)

            carry = jax.tree_util.tree_map(re, carry_init, carry)
        new_carry, out = step_fn(carry, x_t)

        def bmask(a):  # [B] mask broadcast against [B, ...] of any rank
            return m_t.reshape(m_t.shape + (1,) * (a.ndim - 1)).astype(a.dtype)

        def sel(new, old):
            return jnp.where(bmask(new) > 0, new, old)

        carry_out = jax.tree_util.tree_map(sel, new_carry, carry)
        out = jax.tree_util.tree_map(lambda o: o * bmask(o), out)
        return carry_out, out

    ins = (xs_tb, mask_tb) if reset_tb is None else (xs_tb, mask_tb, reset_tb)
    final, outs_tb = lax.scan(masked_step, carry_init, ins, reverse=reverse)
    outs = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, 0, 1), outs_tb)
    return final, outs


def lstm_layer(x, mask, w_x, w_h, b, *, h0=None, c0=None, reverse=False,
               peep_i=None, peep_f=None, peep_o=None,
               act="tanh", gate_act="sigmoid", state_act="tanh",
               reset=None):
    """Full LSTM over a padded batch. x: [B,T,D] -> h_seq [B,T,H], (h,c) final.

    Equivalent capability to the reference's lstmemory layer
    (trainer_config_helpers/layers.py:1121 + LstmLayer.cpp); the input
    projection is one big MXU matmul over all timesteps.  ``w_x=None``
    means x IS the [B,T,4H] pre-projection (the reference's convention,
    where a preceding mixed layer owns the input matrix).

    ``reset`` ([B,T], sequence packing — docs/data.md) zeroes the (h,c)
    carry at segment-entry positions; it routes through the lax.scan
    path (the fused/Pallas time loop has no reset port), which is the
    documented packing trade: denser rows for the scan-path step.
    """
    B, T, _ = x.shape
    H = w_h.shape[0]
    xp = (x + b.astype(x.dtype)) if w_x is None else linear(x, w_x, b)
    if reset is None and \
            (act, gate_act, state_act) == ("tanh", "sigmoid", "tanh"):
        # default cell (peepholes included — zeros degenerate exactly):
        # fused-backward sequence op (hand-written VJP batches d_w_h after
        # the reverse loop; Pallas fwd+bwd kernels when the gate allows —
        # see ops/rnn_fused.py).  reverse rides a flip: identical to
        # scan_rnn(reverse=True) including mask hold/zero semantics.
        from paddle_tpu.ops.rnn_fused import lstm_sequence_fused

        allow_pallas = h0 is None and c0 is None
        h0a = jnp.zeros((B, H), xp.dtype) if h0 is None else h0
        c0a = jnp.zeros((B, H), xp.dtype) if c0 is None else c0
        has_peeps = any(p is not None for p in (peep_i, peep_f, peep_o))
        zp = jnp.zeros((H,), xp.dtype)
        # peepholes join the carry arithmetic: f32 check params would
        # promote the bf16 scan carry under --amp (scan requires a stable
        # carry dtype) — cast at the boundary like every other operand
        pi = zp if peep_i is None else peep_i.astype(xp.dtype)
        pf = zp if peep_f is None else peep_f.astype(xp.dtype)
        po = zp if peep_o is None else peep_o.astype(xp.dtype)
        xp_r = jnp.flip(xp, 1) if reverse else xp
        m_r = jnp.flip(mask, 1) if reverse else mask
        h_seq, h_fin, c_fin = lstm_sequence_fused(xp_r, m_r, w_h, h0a, c0a,
                                                  pi, pf, po, allow_pallas,
                                                  has_peeps)
        if reverse:
            h_seq = jnp.flip(h_seq, 1)
        return h_seq, (h_fin, c_fin)
    h0 = jnp.zeros((B, H), xp.dtype) if h0 is None else h0
    c0 = jnp.zeros((B, H), xp.dtype) if c0 is None else c0

    def step(carry, xp_t):
        h, c = carry
        h2, c2 = lstm_step(
            xp_t, h, c, w_h, peep_i=peep_i, peep_f=peep_f, peep_o=peep_o,
            act=act, gate_act=gate_act, state_act=state_act,
        )
        return (h2, c2), h2

    (h_fin, c_fin), h_seq = scan_rnn(step, (h0, c0), xp, mask,
                                     reverse=reverse, reset_bt=reset)
    return h_seq, (h_fin, c_fin)


def gru_layer(x, mask, w_x, w_h, b, *, h0=None, reverse=False,
              act="tanh", gate_act="sigmoid", reset=None):
    """Full GRU over a padded batch. x: [B,T,D] -> h_seq [B,T,H], h final.

    Capability analog of grumemory (trainer_config_helpers/layers.py:1228 +
    GatedRecurrentLayer.cpp).  ``w_x=None``: x is the [B,T,3H]
    pre-projection (see lstm_layer).  ``reset`` as in ``lstm_layer``
    (sequence packing: carry zeroed at segment entries, scan path).
    """
    B, T, _ = x.shape
    H = w_h.shape[0]
    xp = (x + b.astype(x.dtype)) if w_x is None else linear(x, w_x, b)
    if reset is None and (act, gate_act) == ("tanh", "sigmoid"):
        # default cell: fused-backward sequence op (see lstm_layer above)
        from paddle_tpu.ops.rnn_fused import gru_sequence_fused

        allow_pallas = h0 is None
        h0a = jnp.zeros((B, H), xp.dtype) if h0 is None else h0
        xp_r = jnp.flip(xp, 1) if reverse else xp
        m_r = jnp.flip(mask, 1) if reverse else mask
        h_seq, h_fin = gru_sequence_fused(xp_r, m_r, w_h, h0a, allow_pallas)
        if reverse:
            h_seq = jnp.flip(h_seq, 1)
        return h_seq, h_fin
    h0 = jnp.zeros((B, H), xp.dtype) if h0 is None else h0

    def step(h, xp_t):
        h2 = gru_step(xp_t, h, w_h, act=act, gate_act=gate_act)
        return h2, h2

    h_fin, h_seq = scan_rnn(step, h0, xp, mask, reverse=reverse,
                            reset_bt=reset)
    return h_seq, h_fin


def bigru_layer(x, mask, wx_fw, wh_fw, b_fw, wx_bw, wh_bw, b_bw):
    """Bidirectional GRU over a padded batch — the encoder composition of
    the seq2seq flagship (reference: seqToseq_net.py's forward + backward
    grumemory pair) as ONE sequential time loop when the fused Pallas
    kernel is available (see rnn_fused.bigru_sequence_fused), else two
    ``gru_layer`` calls.

    Returns (h_fw [B,T,H], h_bw [B,T,H], h_bw_final [B,H]).
    """
    from paddle_tpu.ops.rnn_fused import (_use_pallas_bigru,
                                          bigru_sequence_fused)

    B, T, _ = x.shape
    H = wh_fw.shape[0]
    if not _use_pallas_bigru(B, H):
        h_fw, _ = gru_layer(x, mask, wx_fw, wh_fw, b_fw)
        h_bw, h_bw_fin = gru_layer(x, mask, wx_bw, wh_bw, b_bw, reverse=True)
        return h_fw, h_bw, h_bw_fin
    xp_fw = linear(x, wx_fw, b_fw)
    xp_bw = linear(x, wx_bw, b_bw)
    # flip the backward direction whole: padding moves to the FRONT where
    # the zero carry holds through masked steps (scan_rnn semantics), so a
    # forward pass over the flip IS the reverse GRU; outputs flip back
    xp2 = jnp.concatenate([xp_fw, jnp.flip(xp_bw, 1)], 0)
    mask2 = jnp.concatenate([mask, jnp.flip(mask, 1)], 0)
    h2, h_fin2 = bigru_sequence_fused(xp2, mask2, wh_fw, wh_bw, B)
    h_fw = h2[:B]
    h_bw = jnp.flip(h2[B:], 1)
    return h_fw, h_bw, h_fin2[B:]
