"""paddle_tpu.ops — the kernel tier.

TPU-native equivalent of the reference's `hl_*` CUDA kernel library +
device-polymorphic Matrix ops (reference: paddle/cuda/, paddle/math/,
paddle/function/ — see SURVEY.md §1.1-1.3).  Pure JAX functions; hot fused
variants live in ops/pallas_kernels.py and are selected automatically on TPU.
"""

from paddle_tpu.ops.numerics import param_dtype, compute_dtype, acc_dtype, mxu_cast
from paddle_tpu.ops.matmul import matmul, linear
from paddle_tpu.ops.activations import ACTIVATIONS, get_activation, softmax, sequence_softmax
from paddle_tpu.ops.losses import (
    cross_entropy,
    soft_cross_entropy,
    binary_cross_entropy,
    multi_binary_label_cross_entropy,
    mse,
    huber,
    smooth_l1,
    rank_cost,
    masked_token_mean,
    sequence_cross_entropy,
    sequence_softmax_ce_readout,
)
from paddle_tpu.ops.sequence import (
    PACK_KEYS,
    segment_starts,
    segment_valid,
    segment_pool,
    segment_last,
    segment_first,
    segment_expand,
    mask_from_lengths,
    seq_pool_sum,
    seq_pool_avg,
    seq_pool_sqrt,
    seq_pool_max,
    seq_last,
    seq_first,
    seq_expand,
    seq_reverse,
    seq_concat,
    context_projection,
    context_projection_trainable,
)
from paddle_tpu.ops.conv import (
    conv2d,
    conv2d_transpose,
    max_pool2d,
    avg_pool2d,
    batch_norm,
    cmr_norm,
    bilinear_interp,
    maxout,
    global_avg_pool,
)
from paddle_tpu.ops.rnn import (lstm_step, gru_step, lstm_layer,
                               gru_layer, bigru_layer, scan_rnn)
from paddle_tpu.ops.attention import (
    additive_attention_scores,
    attend,
    dot_product_attention,
)
from paddle_tpu.ops.attention_decoder import attention_gru_decoder
from paddle_tpu.ops.decode import (
    LinearReadout,
    LogitsReadout,
    beam_decode,
    greedy_decode,
    beam_gather,
    decode_kernel_config,
    decode_step,
    spec_verify_step,
    init_slot_carry,
    write_slot,
    release_slot,
    extract_slot,
    restore_slot,
    finalize_slots,
)
from paddle_tpu.ops.speculative import (
    DraftProposer,
    NGramProposer,
    CallableDraftProposer,
    AdversarialProposer,
)
from paddle_tpu.ops.embedding import embedding_lookup, one_hot
from paddle_tpu.ops.sparse import (
    sparse_gather_matmul,
    sparse_to_dense,
    selective_columns_matmul,
    CsrMatrix,
    CscMatrix,
    csr_matmul,
    matmul_dense_csc,
)
from paddle_tpu.ops.crf import crf_log_likelihood, crf_nll, crf_decode
from paddle_tpu.ops.ctc import ctc_loss
from paddle_tpu.ops.misc import (
    row_sum,
    row_max,
    col_sum,
    top_k,
    max_id,
    batch_transpose,
    cos_sim,
    interpolation,
    outer_prod,
    tensor_bilinear,
    sum_cost,
    scaling,
    slope_intercept,
    power_op,
    dropout,
)
