"""CTC loss — analog of the reference's CTC tier.

Reference: native CTCLayer (gserver/layers/CTCLayer.cpp) and the dlopen'd
warp-ctc wrapper (paddle/cuda/src/hl_warpctc_wrap.cc, WarpCTCLayer.cpp).

TPU-first: the standard alpha (forward) recursion in log space over the
extended label sequence [blank, l1, blank, ..., lL, blank], as a ``lax.scan``
over time — fully batched on padded [B,T,C] log-probs with per-row input and
label lengths; no cuDNN/warpctc dependency.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ctc_loss"]

_NEG = -1e30


def ctc_loss(log_probs, labels, input_lengths, label_lengths, *, blank: int = 0,
             norm_by_times: bool = False):
    """Per-sequence CTC negative log-likelihood.

    log_probs: [B, T, C] log-softmax outputs; labels: [B, L] int (padded);
    input_lengths: [B]; label_lengths: [B]. Returns [B] losses.
    """
    log_probs = log_probs.astype(jnp.float32)
    B, T, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    labels = labels.astype(jnp.int32)

    # extended sequence e: [B, S] = blank, l1, blank, l2, ... blank
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    # allowed skip (s-2 -> s): e[s] != blank and e[s] != e[s-2]
    can_skip = jnp.zeros((B, S), bool)
    can_skip = can_skip.at[:, 2:].set((ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    lp_tb = jnp.moveaxis(log_probs, 1, 0)  # [T, B, C]
    t_mask = (jnp.arange(T)[:, None] < input_lengths[None, :]).astype(jnp.float32)  # [T,B]

    def emit(lp_t):
        # lp_t [B, C] -> [B, S] log-prob of each extended symbol
        return jnp.take_along_axis(lp_t, ext, axis=1)

    alpha0 = jnp.full((B, S), _NEG)
    e0 = emit(lp_tb[0])
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(label_lengths > 0, e0[:, 1], _NEG))

    def step(alpha, inp):
        lp_t, m_t = inp
        a_prev = alpha
        a_shift1 = jnp.concatenate([jnp.full((B, 1), _NEG), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate([jnp.full((B, 2), _NEG), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(can_skip, a_shift2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
        new = merged + emit(lp_t)
        keep = m_t[:, None] > 0
        return jnp.where(keep, new, alpha), None

    alpha, _ = lax.scan(step, alpha0, (lp_tb[1:], t_mask[1:]))

    # final: logsumexp of alpha at s = 2*lab_len (trailing blank) and 2*lab_len-1
    sl = 2 * label_lengths
    a_end = jnp.take_along_axis(alpha, sl[:, None], axis=1)[:, 0]
    a_end2 = jnp.take_along_axis(alpha, jnp.maximum(sl - 1, 0)[:, None], axis=1)[:, 0]
    a_end2 = jnp.where(label_lengths > 0, a_end2, _NEG)
    ll = jnp.logaddexp(a_end, a_end2)
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths.astype(jnp.float32), 1.0)
    return loss
