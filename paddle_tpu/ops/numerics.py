"""Numeric policy: parameter dtype vs MXU compute dtype.

The reference compiles for float or double globally (WITH_DOUBLE,
reference: CMakeLists.txt:44; real/hl_base.h).  On TPU the idiomatic policy is
mixed precision: parameters and accumulations in float32, matmul/conv operands
in bfloat16 so they hit the MXU at full rate.  ``matmul_compute_dtype`` is
controlled by FLAGS.compute_dtype; tests pin it to float32 so finite-difference
gradient checks are meaningful.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["param_dtype", "compute_dtype", "mxu_cast", "acc_dtype"]


def param_dtype():
    from paddle_tpu.utils.flags import FLAGS

    return jnp.dtype(FLAGS.dtype)


def compute_dtype():
    from paddle_tpu.utils.flags import FLAGS

    return jnp.dtype(FLAGS.compute_dtype)


def acc_dtype():
    return jnp.float32


def mxu_cast(*arrays):
    """Cast matmul/conv operands to the compute dtype (bf16 on TPU)."""
    cd = compute_dtype()
    out = tuple(a.astype(cd) if jnp.issubdtype(a.dtype, jnp.floating) else a for a in arrays)
    return out if len(out) > 1 else out[0]
