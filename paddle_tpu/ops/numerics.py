"""Numeric policy: parameter dtype vs MXU compute dtype.

The reference compiles for float or double globally (WITH_DOUBLE,
reference: CMakeLists.txt:44; real/hl_base.h).  On TPU the idiomatic policy is
mixed precision: parameters and accumulations in float32, matmul/conv operands
in bfloat16 so they hit the MXU at full rate.  ``matmul_compute_dtype`` is
controlled by FLAGS.compute_dtype; tests pin it to float32 so finite-difference
gradient checks are meaningful.

``--amp`` (docs/mixed_precision.md) escalates this to END-TO-END bf16
compute: matmul/conv OUTPUTS also stay bf16 (``dot_dtype``), so activations
— and, because JAX cotangents carry the primal dtype, the whole backward —
live in bf16, halving activation HBM traffic.  Master weights stay f32
(``param_dtype`` is untouched; ``mxu_cast`` re-derives the bf16 operand per
use), and the f32 allowlist — BN statistics, softmax/logsumexp reductions,
the loss — is enforced by explicit upcasts at those sites, gated by
``lint --amp``.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["param_dtype", "compute_dtype", "mxu_cast", "acc_dtype",
           "dot_dtype", "amp_enabled", "bwd_mm", "bwd_einsum"]


def param_dtype():
    from paddle_tpu.utils.flags import FLAGS

    return jnp.dtype(FLAGS.dtype)


def amp_enabled() -> bool:
    """Whether ``--amp`` mixed-precision training is on (read at trace
    time, like every other dtype-policy switch here)."""
    from paddle_tpu.utils.flags import FLAGS

    return bool(FLAGS.amp)


def compute_dtype():
    from paddle_tpu.utils.flags import FLAGS

    if FLAGS.amp:
        # --amp pins the operand dtype regardless of --compute_dtype: the
        # test harness pins compute_dtype=f32 for FD checks, and amp must
        # still mean bf16 there
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(FLAGS.compute_dtype)


def acc_dtype():
    """Accumulation dtype for reductions and statistics — ALWAYS f32
    (bf16 squares overflow at ~256; BN stats and softmax/logsumexp live
    here, the --amp allowlist)."""
    return jnp.float32


def dot_dtype():
    """``preferred_element_type`` for matmul/conv: f32 accumulation by
    default; under ``--amp`` the output stays bf16 so activations (and the
    cotangents that inherit their dtype) never widen back to f32 between
    MXU ops.  The MXU accumulates partial products in f32 internally
    either way — bf16 output is one final rounding, not bf16
    accumulation."""
    if amp_enabled():
        return jnp.dtype(jnp.bfloat16)
    return acc_dtype()


def mxu_cast(*arrays):
    """Cast matmul/conv operands to the compute dtype (bf16 on TPU)."""
    cd = compute_dtype()
    out = tuple(a.astype(cd) if jnp.issubdtype(a.dtype, jnp.floating) else a for a in arrays)
    return out if len(out) > 1 else out[0]


def bwd_mm(a, b):
    """Matmul for HAND-WRITTEN backward rules (the fused RNN /
    attention-decoder custom VJPs): f32 operands by default — their
    deliberate f32 accumulation policy — but bf16 OPERANDS with f32
    accumulation under ``--amp``, so a mixed-precision step contains no
    all-f32 MXU eqns (the ``lint --amp`` gate) and the reverse loops' dots
    run at full MXU rate.  f32 result either way (gradient chains and
    scan carries stay f32-stable)."""
    if amp_enabled():
        a, b = a.astype(jnp.bfloat16), b.astype(jnp.bfloat16)
    return jnp.matmul(a, b, preferred_element_type=jnp.float32)


def bwd_einsum(expr, a, b):
    """Weight-gradient einsum with the same operand policy as ``bwd_mm``
    (f32 result either way — weight grads accumulate wide)."""
    if amp_enabled():
        a, b = a.astype(jnp.bfloat16), b.astype(jnp.bfloat16)
    return jnp.einsum(expr, a, b, preferred_element_type=jnp.float32)
