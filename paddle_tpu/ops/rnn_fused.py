"""Fused-backward GRU/LSTM sequence ops.

Same restructuring as ops/attention_decoder.py, applied to the plain
recurrent layers (the encoder of the seq2seq flagship, stacked LSTM/GRU text
models): XLA's autodiff of the time scan accumulates the recurrent weight
gradient (3-6 MB) through HBM on every reverse step; the hand-written VJP
emits the small per-step pre-activation cotangents instead and reconstructs
``d_w_h`` afterwards as one batched MXU contraction
(``einsum('tbh,tbz->hz', h_prev, d_z)``), which also serves as ``d_xp``
directly since the input projection enters the cell additively.

Forward runs the fused Pallas time-loop kernel when the shape gate allows
(ops/pallas_kernels.py), else the masked lax.scan — both inside the same
custom_vjp, so the fast backward applies either way.  Semantics match
``scan_rnn`` + ``gru_step``/``lstm_step`` exactly (carry held and outputs
zeroed at masked steps); equivalence is pinned by tests/test_rnn_fused.py.

Reference analog: the fused CUDA cells hl_cuda_lstm.cu:26-58 /
hl_gru_ops.cuh — the reference hand-writes both directions of its hot
recurrent kernels; this is the TPU rendition of the backward half.

Tradeoff: custom_vjp ops do not support forward-mode autodiff (jvp/jacfwd
through a default-cell layer raises) — reverse-mode (grad/vjp), the only
mode the trainer and checkgrad use, is unaffected.  Pass a non-default
activation to route through the plain scan if forward-mode is ever needed.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.matmul import linear

__all__ = ["gru_sequence_fused", "lstm_sequence_fused"]


# ---------------------------------------------------------------------------
# GRU
# ---------------------------------------------------------------------------


def _gru_fwd_scan(xp, mask, w_h, h0):
    """Masked forward scan; xp [B,T,3H], mask [B,T] -> h_seq [B,T,H], h_fin.
    Mirrors scan_rnn(gru_step) numerics (bf16 matmul operands in linear)."""
    H = w_h.shape[0]
    xp_tb = jnp.moveaxis(xp, 1, 0)
    m_tb = jnp.moveaxis(mask, 1, 0)

    def step(h, inp):
        xp_t, m_t = inp
        zr = xp_t[..., : 2 * H] + linear(h, w_h[:, : 2 * H])
        r, u = jnp.split(jax.nn.sigmoid(zr), 2, axis=-1)
        cand = jnp.tanh(xp_t[..., 2 * H:] + linear(r * h, w_h[:, 2 * H:]))
        h_new = u * h + (1.0 - u) * cand
        keep = (m_t > 0)[:, None]
        h_out = jnp.where(keep, h_new, h)
        return h_out, h_out * m_t[:, None].astype(h_out.dtype)

    h_fin, outs = lax.scan(step, h0, (xp_tb, m_tb))
    return jnp.moveaxis(outs, 0, 1), h_fin


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def gru_sequence_fused(xp, mask, w_h, h0, allow_pallas=False):
    """GRU over a padded batch given the input projection ``xp`` [B,T,3H].
    ``allow_pallas`` (static) lets the forward use the Pallas time-loop
    kernel — only legal when the caller statically knows h0 is zeros (the
    kernel boots from zeros)."""
    return _gru_core_fwd(xp, mask, w_h, h0, allow_pallas)


def _gru_core_fwd(xp, mask, w_h, h0, allow_pallas):
    if allow_pallas:
        from paddle_tpu.ops.rnn import _use_pallas_rnn

        B, T, H3 = xp.shape
        H = H3 // 3
        if _use_pallas_rnn(B, H, None, None, None, None, None,
                           "tanh", "sigmoid", "tanh", False):
            from paddle_tpu.ops.pallas_kernels import _gru_pallas_raw

            xp_tb = jnp.moveaxis(xp.astype(jnp.float32), 1, 0)
            m_tb = jnp.moveaxis(mask.astype(jnp.float32), 1, 0)
            h_tb, h_fin = _gru_pallas_raw(xp_tb, m_tb,
                                          w_h.astype(jnp.float32))
            return jnp.moveaxis(h_tb, 0, 1), h_fin
    return _gru_fwd_scan(xp, mask, w_h, h0)


def _gru_seq_fwd(xp, mask, w_h, h0, allow_pallas):
    h_seq, h_fin = _gru_core_fwd(xp, mask, w_h, h0, allow_pallas)
    return (h_seq, h_fin), (xp, mask, w_h, h0, h_seq)


def _gru_seq_bwd(allow_pallas, res, ct):
    xp, mask, w_h, h0, h_seq = res
    d_hseq, d_hfin = ct
    B, T, H3 = xp.shape
    H = H3 // 3
    f32 = jnp.float32
    w_f = w_h.astype(f32)

    xp_tb = jnp.moveaxis(xp, 1, 0)
    m_tb = jnp.moveaxis(mask, 1, 0)
    d_out_tb = jnp.moveaxis(d_hseq, 1, 0).astype(f32)
    # reconstruct the held carry at masked steps (saved h_seq is zeroed there)
    def carry_fix(c, om):
        out_t, m_t = om
        c_t = jnp.where((m_t > 0)[:, None], out_t, c)
        return c_t, c_t
    _, carries = lax.scan(carry_fix, h0, (jnp.moveaxis(h_seq, 1, 0), m_tb))
    h_prev = jnp.concatenate([h0[None], carries[:-1]], 0)   # [T,B,H]

    def rev_step(d_c, inp):
        d_out_t, m_t, xp_t, hp_t = inp
        mcol = (m_t > 0)[:, None].astype(f32)
        d_hnew = mcol * (d_out_t + d_c)
        hp = hp_t.astype(f32)
        zr = xp_t[..., : 2 * H].astype(f32) + linear(hp_t, w_h[:, : 2 * H]).astype(f32)
        ru = jax.nn.sigmoid(zr)
        r, u = jnp.split(ru, 2, axis=-1)
        rh = r * hp
        cand = jnp.tanh(xp_t[..., 2 * H:].astype(f32)
                        + linear((r * hp_t.astype(f32)).astype(hp_t.dtype),
                                 w_h[:, 2 * H:]).astype(f32))
        d_u = d_hnew * (hp - cand)
        d_cand = d_hnew * (1.0 - u)
        d_hp = d_hnew * u
        d_zc = d_cand * (1.0 - cand * cand)
        d_rh = d_zc @ w_f[:, 2 * H:].T
        d_r = d_rh * hp
        d_hp = d_hp + d_rh * r
        d_zr = jnp.concatenate([d_r * r * (1 - r), d_u * u * (1 - u)], -1)
        d_hp = d_hp + d_zr @ w_f[:, : 2 * H].T
        d_xp_t = jnp.concatenate([d_zr, d_zc], -1)
        d_c_out = (1.0 - mcol) * d_c + d_hp
        return d_c_out, (d_xp_t, rh)

    d_c0 = d_hfin.astype(f32)
    d_h0, (d_xp_tb, rh_tb) = lax.scan(
        rev_step, d_c0, (d_out_tb, m_tb, xp_tb, h_prev), reverse=True)

    # batched weight gradient: zr part against h_prev, cand part against r*h
    hp_f = h_prev.astype(f32)
    d_w_gates = jnp.einsum("tbh,tbz->hz", hp_f, d_xp_tb[..., : 2 * H])
    d_w_cand = jnp.einsum("tbh,tbz->hz", rh_tb, d_xp_tb[..., 2 * H:])
    d_wh = jnp.concatenate([d_w_gates, d_w_cand], axis=1).astype(w_h.dtype)
    d_xp = jnp.moveaxis(d_xp_tb, 0, 1).astype(xp.dtype)
    return d_xp, None, d_wh, d_h0.astype(h0.dtype)


gru_sequence_fused.defvjp(_gru_seq_fwd, _gru_seq_bwd)


# ---------------------------------------------------------------------------
# LSTM
# ---------------------------------------------------------------------------


def _lstm_fwd_scan(xp, mask, w_h, h0, c0):
    """Masked forward scan; xp [B,T,4H] (gate order i,f,o,g as lstm_step)."""
    H = w_h.shape[0]
    xp_tb = jnp.moveaxis(xp, 1, 0)
    m_tb = jnp.moveaxis(mask, 1, 0)

    def step(carry, inp):
        h, c = carry
        xp_t, m_t = inp
        z = xp_t + linear(h, w_h)
        i = jax.nn.sigmoid(z[..., :H])
        f = jax.nn.sigmoid(z[..., H: 2 * H])
        o = jax.nn.sigmoid(z[..., 2 * H: 3 * H])
        g = jnp.tanh(z[..., 3 * H:])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        keep = (m_t > 0)[:, None]
        h_out = jnp.where(keep, h_new, h)
        c_out = jnp.where(keep, c_new, c)
        return (h_out, c_out), h_out * m_t[:, None].astype(h_out.dtype)

    (h_fin, c_fin), outs = lax.scan(step, (h0, c0), (xp_tb, m_tb))
    return jnp.moveaxis(outs, 0, 1), h_fin, c_fin


@partial(jax.custom_vjp, nondiff_argnums=(5,))
def lstm_sequence_fused(xp, mask, w_h, h0, c0, allow_pallas=False):
    return _lstm_core_fwd(xp, mask, w_h, h0, c0, allow_pallas)


def _lstm_core_fwd(xp, mask, w_h, h0, c0, allow_pallas):
    if allow_pallas:
        from paddle_tpu.ops.rnn import _use_pallas_rnn

        B, T, H4 = xp.shape
        H = H4 // 4
        if _use_pallas_rnn(B, H, None, None, None, None, None,
                           "tanh", "sigmoid", "tanh", False):
            from paddle_tpu.ops.pallas_kernels import _lstm_pallas_raw

            xp_tb = jnp.moveaxis(xp.astype(jnp.float32), 1, 0)
            m_tb = jnp.moveaxis(mask.astype(jnp.float32), 1, 0)
            h_tb, h_fin, c_fin = _lstm_pallas_raw(xp_tb, m_tb,
                                                  w_h.astype(jnp.float32))
            return jnp.moveaxis(h_tb, 0, 1), h_fin, c_fin
    return _lstm_fwd_scan(xp, mask, w_h, h0, c0)


def _lstm_seq_fwd(xp, mask, w_h, h0, c0, allow_pallas):
    h_seq, h_fin, c_fin = _lstm_core_fwd(xp, mask, w_h, h0, c0, allow_pallas)
    return (h_seq, h_fin, c_fin), (xp, mask, w_h, h0, c0)


def _lstm_seq_bwd(allow_pallas, res, ct):
    xp, mask, w_h, h0, c0 = res
    d_hseq, d_hfin, d_cfin = ct
    B, T, H4 = xp.shape
    H = H4 // 4
    f32 = jnp.float32
    w_f = w_h.astype(f32)

    xp_tb = jnp.moveaxis(xp, 1, 0)
    m_tb = jnp.moveaxis(mask, 1, 0)
    d_out_tb = jnp.moveaxis(d_hseq, 1, 0).astype(f32)

    # forward replay: the only sequential recurrent matmul of the backward —
    # emits h_prev and the pre-activations z so rev_step is matmul-free on
    # the recompute side (the c carry is not saved by fwd, so a replay is
    # needed either way)
    def replay(carry, inp):
        h, c = carry
        xp_t, m_t = inp
        z = xp_t + linear(h, w_h)
        i = jax.nn.sigmoid(z[..., :H])
        f = jax.nn.sigmoid(z[..., H: 2 * H])
        o = jax.nn.sigmoid(z[..., 2 * H: 3 * H])
        g = jnp.tanh(z[..., 3 * H:])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        keep = (m_t > 0)[:, None]
        h_out = jnp.where(keep, h_new, h)
        c_out = jnp.where(keep, c_new, c)
        return (h_out, c_out), (h, c, z)

    _, (h_prev, c_prev, z_all) = lax.scan(replay, (h0, c0), (xp_tb, m_tb))

    def rev_step(carry, inp):
        d_h, d_c = carry
        d_out_t, m_t, z_t, cp_t = inp
        mcol = (m_t > 0)[:, None].astype(f32)
        d_hnew = mcol * (d_out_t + d_h)
        d_cnew = mcol * d_c
        cp = cp_t.astype(f32)
        z = z_t.astype(f32)
        i = jax.nn.sigmoid(z[..., :H])
        f = jax.nn.sigmoid(z[..., H: 2 * H])
        o = jax.nn.sigmoid(z[..., 2 * H: 3 * H])
        g = jnp.tanh(z[..., 3 * H:])
        c_new = f * cp + i * g
        tc = jnp.tanh(c_new)
        d_o = d_hnew * tc
        d_cnew = d_cnew + d_hnew * o * (1.0 - tc * tc)
        d_f = d_cnew * cp
        d_i = d_cnew * g
        d_g = d_cnew * i
        d_cp = d_cnew * f
        d_z = jnp.concatenate([
            d_i * i * (1 - i), d_f * f * (1 - f),
            d_o * o * (1 - o), d_g * (1 - g * g)], -1)
        d_hp = d_z @ w_f.T
        d_h_out = (1.0 - mcol) * d_h + d_hp
        d_c_out = (1.0 - mcol) * d_c + d_cp
        return (d_h_out, d_c_out), d_z

    (d_h0, d_c0), d_z_tb = lax.scan(
        rev_step, (d_hfin.astype(f32), d_cfin.astype(f32)),
        (d_out_tb, m_tb, z_all, c_prev), reverse=True)

    d_wh = jnp.einsum("tbh,tbz->hz", h_prev.astype(f32), d_z_tb).astype(w_h.dtype)
    d_xp = jnp.moveaxis(d_z_tb, 0, 1).astype(xp.dtype)
    return d_xp, None, d_wh, d_h0.astype(h0.dtype), d_c0.astype(c0.dtype)


lstm_sequence_fused.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)
