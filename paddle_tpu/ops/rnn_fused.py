"""Fused-backward GRU/LSTM sequence ops.

Same restructuring as ops/attention_decoder.py, applied to the plain
recurrent layers (the encoder of the seq2seq flagship, stacked LSTM/GRU text
models).  Two structural changes vs XLA's autodiff of the time scan:

1. The forward (Pallas kernel or masked lax.scan — one numerics source of
   truth either way) SAVES the per-step pre-activations ``z`` and the held
   carries ``h_prev``/``c_prev``.  The backward therefore needs NO forward
   replay scan: the time-sequential work drops from three T-length loops
   per layer (fwd + replay + reverse) to two (fwd + reverse), and the
   reverse step recomputes gates from ``z`` with pure elementwise math —
   its only matmul is the unavoidable ``d_z @ w_h^T`` carry propagation.
2. The recurrent weight gradient is NOT dragged through the scan: the
   reverse loop emits the small per-step cotangents ``d_z`` and ``d_w_h``
   is reconstructed afterwards as one batched MXU contraction
   (``einsum('tbh,tbz->hz', h_prev, d_z)``), which also serves as ``d_xp``
   directly since the input projection enters the cell additively.

Semantics match ``scan_rnn`` + ``gru_step``/``lstm_step`` exactly (carry
held and outputs zeroed at masked steps); equivalence is pinned by
tests/test_rnn_fused.py.

Reference analog: the fused CUDA cells hl_cuda_lstm.cu:26-58 /
hl_gru_ops.cuh — the reference hand-writes both directions of its hot
recurrent kernels; this is the TPU rendition of the backward half.

Tradeoff: custom_vjp ops do not support forward-mode autodiff (jvp/jacfwd
through a default-cell layer raises) — reverse-mode (grad/vjp), the only
mode the trainer and checkgrad use, is unaffected.  Pass a non-default
activation to route through the plain scan if forward-mode is ever needed.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.matmul import linear

__all__ = ["gru_sequence_fused", "lstm_sequence_fused",
           "bigru_sequence_fused"]


def residual_dtype(hidden: int):
    """Dtype of the z/h_prev/c_prev residual streams: bf16 under the prod
    compute policy for H <= 512 (halves backward HBM traffic and buys back
    the scoped VMEM that re-enables the Pallas reverse kernel at B384/H512),
    f32 otherwise — at large H the in-kernel bf16 cast temporaries OVERFLOW
    scoped VMEM (measured: the h1280 forward kernel jumps from <16M to
    30.6M and fails to compile with bf16 residuals)."""
    from paddle_tpu.ops.numerics import compute_dtype

    cd = compute_dtype()
    return cd if (cd == jnp.bfloat16 and hidden <= 512) else jnp.float32


# amp-aware backward matmul/einsum policy — shared with the hand-written
# attention-decoder backward (ops/numerics.bwd_mm/bwd_einsum): f32
# operands by default, bf16 operands + f32 accumulation under --amp
from paddle_tpu.ops.numerics import bwd_einsum as _bwd_einsum  # noqa: E402
from paddle_tpu.ops.numerics import bwd_mm as _bwd_mm  # noqa: E402


def _bwd_pallas_ok(batch: int, hidden: int) -> bool:
    """Backward Pallas gate: forward tile constraints PLUS a VMEM cap that
    depends on the residual stream dtype.  The reverse kernel's per-step
    working set (z + d_z [B,gates*H] blocks, the transposed weight, two
    carry scratches) is larger than the forward's: with f32 residuals,
    B*H = 384*512 (the forward's measured ceiling) OOMs scoped VMEM by
    1.6M on v5e and 256*512 is the cap.  Under the bfloat16 compute policy
    the z/h_prev/c_prev streams halve, which buys back enough VMEM that
    384*512 (the WMT14 encoder shape) compiles and runs — hence the
    dtype-dependent cap."""
    from paddle_tpu.ops.rnn import _use_pallas_rnn

    cap = (384 * 512 if residual_dtype(hidden) == jnp.bfloat16
           else 256 * 512)
    return _use_pallas_rnn(batch, hidden) and batch * hidden <= cap


# ---------------------------------------------------------------------------
# GRU
# ---------------------------------------------------------------------------


def _gru_fwd_scan(xp, mask, w_h, h0):
    """Masked forward scan; xp [B,T,3H], mask [B,T] -> (h_seq [B,T,H],
    h_fin, z [T,B,3H] pre-activations, hprev [T,B,H]).
    Mirrors scan_rnn(gru_step) numerics (bf16 matmul operands in linear).
    Residuals are stored in ``residual_dtype(H)`` (bf16 under the
    production policy for H <= 512, f32 otherwise and in tests): they
    exist only to recompute gates in the backward, and halving their HBM
    stream is worth the rounding — gradients become approximate at bf16's
    0.4% ULP, standard mixed precision practice."""
    H = w_h.shape[0]
    rd = residual_dtype(H)
    xp_tb = jnp.moveaxis(xp, 1, 0)
    m_tb = jnp.moveaxis(mask, 1, 0)

    def step(h, inp):
        xp_t, m_t = inp
        zr = xp_t[..., : 2 * H] + linear(h, w_h[:, : 2 * H])
        r, u = jnp.split(jax.nn.sigmoid(zr), 2, axis=-1)
        zc = xp_t[..., 2 * H:] + linear(r * h, w_h[:, 2 * H:])
        cand = jnp.tanh(zc)
        h_new = u * h + (1.0 - u) * cand
        keep = (m_t > 0)[:, None]
        h_out = jnp.where(keep, h_new, h)
        z = jnp.concatenate([zr, zc], -1)
        return h_out, (h_out * m_t[:, None].astype(h_out.dtype),
                       z.astype(rd), h.astype(rd))

    h_fin, (outs, z_tb, hprev_tb) = lax.scan(step, h0, (xp_tb, m_tb))
    # residuals leave TIME-major [T,B,*] — one fixed layout contract with
    # the backward regardless of which path produced them (the kernels are
    # time-major too: Mosaic wants the last two block dims tile-aligned)
    return jnp.moveaxis(outs, 0, 1), h_fin, z_tb, hprev_tb


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def gru_sequence_fused(xp, mask, w_h, h0, allow_pallas=False):
    """GRU over a padded batch given the input projection ``xp`` [B,T,3H].
    ``allow_pallas`` (static) lets the forward use the Pallas time-loop
    kernel — only legal when the caller statically knows h0 is zeros (the
    kernel boots from zeros)."""
    # primal-only call (inference, no grad pending): skip the residuals —
    # the Pallas outputs would be materialized to HBM even if unused
    h_seq, h_fin = _gru_core_fwd(xp, mask, w_h, h0, allow_pallas,
                                 residuals=False)[:2]
    return h_seq, h_fin


def _gru_core_fwd(xp, mask, w_h, h0, allow_pallas, *, residuals=True):
    if allow_pallas:
        from paddle_tpu.ops.rnn import _use_pallas_rnn

        B, T, H3 = xp.shape
        H = H3 // 3
        if _use_pallas_rnn(B, H):
            from paddle_tpu.ops.pallas_kernels import _gru_pallas_raw

            xp_tb = jnp.moveaxis(xp.astype(jnp.float32), 1, 0)
            m_tb = jnp.moveaxis(mask.astype(jnp.float32), 1, 0)
            outs = _gru_pallas_raw(xp_tb, m_tb, w_h.astype(jnp.float32),
                                   residuals=residuals)
            h_tb, h_fin = outs[0], outs[1]
            z_r, hprev_r = (outs[2], outs[3]) if residuals else (None, None)
            return jnp.moveaxis(h_tb, 0, 1), h_fin, z_r, hprev_r
    out = _gru_fwd_scan(xp, mask, w_h, h0)
    return out if residuals else (out[0], out[1], None, None)


def _gru_seq_fwd(xp, mask, w_h, h0, allow_pallas):
    h_seq, h_fin, z_tb, hprev_tb = _gru_core_fwd(xp, mask, w_h, h0,
                                                 allow_pallas)
    # zero-size sentinels carry the caller dtypes through the residual
    # pytree (dtype objects are not valid JAX residuals)
    meta = (jnp.zeros((0,), xp.dtype), jnp.zeros((0,), h0.dtype))
    return (h_seq, h_fin), (mask, w_h, z_tb, hprev_tb, meta)


def _gru_seq_bwd(allow_pallas, res, ct):
    mask, w_h, z_r, hprev_r, (xp_s, h0_s) = res
    xp_dtype, h0_dtype = xp_s.dtype, h0_s.dtype
    d_hseq, d_hfin = ct
    H = w_h.shape[0]
    B = mask.shape[0]
    f32 = jnp.float32
    w_f = w_h.astype(f32)

    hp_f = hprev_r.astype(f32)                   # residuals are [T,B,*]
    if allow_pallas and _bwd_pallas_ok(B, H):
        from paddle_tpu.ops.pallas_kernels import _gru_bwd_pallas_raw

        # residual streams enter the kernel in their STORED dtype (bf16
        # under the prod policy) — casting happens per-block in VMEM
        d_xp_tb, d_h0 = _gru_bwd_pallas_raw(
            jnp.moveaxis(d_hseq, 1, 0).astype(f32),
            jnp.moveaxis(mask, 1, 0).astype(f32),
            z_r, hprev_r, w_f.T.copy(), d_hfin.astype(f32))
    else:
        m_tb = jnp.moveaxis(mask, 1, 0)
        d_out_tb = jnp.moveaxis(d_hseq, 1, 0).astype(f32)
        # gates recomputed from the SAVED pre-activations, vectorized over
        # all timesteps at once (pure elementwise — XLA fuses; no replay)
        z_f = z_r.astype(f32)
        ru = jax.nn.sigmoid(z_f[..., : 2 * H])
        r = ru[..., :H]
        u = ru[..., H:]
        cand = jnp.tanh(z_f[..., 2 * H:])

        def rev_step(d_c, inp):
            d_out_t, m_t, r_t, u_t, cand_t, hp_t = inp
            mcol = (m_t > 0)[:, None].astype(f32)
            d_hnew = mcol * (d_out_t + d_c)
            d_u = d_hnew * (hp_t - cand_t)
            d_cand = d_hnew * (1.0 - u_t)
            d_hp = d_hnew * u_t
            d_zc = d_cand * (1.0 - cand_t * cand_t)
            d_rh = _bwd_mm(d_zc, w_f[:, 2 * H:].T)
            d_r = d_rh * hp_t
            d_hp = d_hp + d_rh * r_t
            d_zr = jnp.concatenate(
                [d_r * r_t * (1 - r_t), d_u * u_t * (1 - u_t)], -1)
            d_hp = d_hp + _bwd_mm(d_zr, w_f[:, : 2 * H].T)
            d_xp_t = jnp.concatenate([d_zr, d_zc], -1)
            d_c_out = (1.0 - mcol) * d_c + d_hp
            return d_c_out, d_xp_t

        d_h0, d_xp_tb = lax.scan(
            rev_step, d_hfin.astype(f32),
            (d_out_tb, m_tb, r, u, cand, hp_f), reverse=True)

    # shared tail — batched weight gradient: zr part against h_prev, cand
    # part against r*h (ONE copy for both reverse-loop implementations)
    rh = jax.nn.sigmoid(z_r[..., :H].astype(f32)) * hp_f
    d_w_gates = _bwd_einsum("tbh,tbz->hz", hp_f, d_xp_tb[..., : 2 * H])
    d_w_cand = _bwd_einsum("tbh,tbz->hz", rh, d_xp_tb[..., 2 * H:])
    d_wh = jnp.concatenate([d_w_gates, d_w_cand], axis=1).astype(w_h.dtype)
    d_xp = jnp.moveaxis(d_xp_tb, 0, 1).astype(xp_dtype)
    return d_xp, None, d_wh, d_h0.astype(h0_dtype)


gru_sequence_fused.defvjp(_gru_seq_fwd, _gru_seq_bwd)


# ---------------------------------------------------------------------------
# LSTM
# ---------------------------------------------------------------------------


def _lstm_fwd_scan(xp, mask, w_h, h0, c0, pi, pf, po):
    """Masked forward scan; xp [B,T,4H] (gate order i,f,o,g as lstm_step),
    pi/pf/po [H] peephole ("check") vectors (zeros = plain cell)
    -> (h_seq, h_fin, c_fin, z [T,B,4H] PRE-peephole, hprev, cprev) —
    residuals in ``residual_dtype(H)`` (see _gru_fwd_scan)."""
    H = w_h.shape[0]
    rd = residual_dtype(H)
    xp_tb = jnp.moveaxis(xp, 1, 0)
    m_tb = jnp.moveaxis(mask, 1, 0)

    def step(carry, inp):
        h, c = carry
        xp_t, m_t = inp
        z = xp_t + linear(h, w_h)
        i = jax.nn.sigmoid(z[..., :H] + pi * c)
        f = jax.nn.sigmoid(z[..., H: 2 * H] + pf * c)
        g = jnp.tanh(z[..., 3 * H:])
        c_new = f * c + i * g
        o = jax.nn.sigmoid(z[..., 2 * H: 3 * H] + po * c_new)
        h_new = o * jnp.tanh(c_new)
        keep = (m_t > 0)[:, None]
        h_out = jnp.where(keep, h_new, h)
        c_out = jnp.where(keep, c_new, c)
        return ((h_out, c_out),
                (h_out * m_t[:, None].astype(h_out.dtype),
                 z.astype(rd), h.astype(rd), c.astype(rd)))

    (h_fin, c_fin), (outs, z_tb, hprev_tb, cprev_tb) = lax.scan(
        step, (h0, c0), (xp_tb, m_tb))
    # residuals leave TIME-major (layout contract with the backward)
    return (jnp.moveaxis(outs, 0, 1), h_fin, c_fin,
            z_tb, hprev_tb, cprev_tb)


@partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def lstm_sequence_fused(xp, mask, w_h, h0, c0, pi, pf, po,
                        allow_pallas=False, has_peepholes=True):
    """pi/pf/po: [H] peephole vectors (pass zeros for the plain cell — the
    math degenerates exactly).  ``has_peepholes`` (static) lets the
    backward skip the c_new residual stream and the d_peep reductions when
    the caller statically knows the peepholes are zeros."""
    # primal-only call (inference): residual-free variant — see GRU twin
    h_seq, h_fin, c_fin = _lstm_core_fwd(xp, mask, w_h, h0, c0, pi, pf, po,
                                         allow_pallas, residuals=False)[:3]
    return h_seq, h_fin, c_fin


def _lstm_core_fwd(xp, mask, w_h, h0, c0, pi, pf, po, allow_pallas, *,
                   residuals=True):
    if allow_pallas:
        from paddle_tpu.ops.rnn import _use_pallas_rnn

        B, T, H4 = xp.shape
        H = H4 // 4
        if _use_pallas_rnn(B, H):
            from paddle_tpu.ops.pallas_kernels import _lstm_pallas_raw

            xp_tb = jnp.moveaxis(xp.astype(jnp.float32), 1, 0)
            m_tb = jnp.moveaxis(mask.astype(jnp.float32), 1, 0)
            outs = _lstm_pallas_raw(xp_tb, m_tb, w_h.astype(jnp.float32),
                                    pi.astype(jnp.float32),
                                    pf.astype(jnp.float32),
                                    po.astype(jnp.float32),
                                    residuals=residuals)
            h_tb, h_fin, c_fin = outs[0], outs[1], outs[2]
            z_r, hprev_r, cprev_r = (
                (outs[3], outs[4], outs[5]) if residuals
                else (None, None, None))
            return (jnp.moveaxis(h_tb, 0, 1), h_fin, c_fin,
                    z_r, hprev_r, cprev_r)
    out = _lstm_fwd_scan(xp, mask, w_h, h0, c0, pi, pf, po)
    return out if residuals else (out[0], out[1], out[2], None, None, None)


def _lstm_seq_fwd(xp, mask, w_h, h0, c0, pi, pf, po, allow_pallas,
                  has_peepholes):
    h_seq, h_fin, c_fin, z_tb, hprev_tb, cprev_tb = _lstm_core_fwd(
        xp, mask, w_h, h0, c0, pi, pf, po, allow_pallas)
    meta = (jnp.zeros((0,), xp.dtype), jnp.zeros((0,), h0.dtype),
            jnp.zeros((0,), c0.dtype))  # dtype sentinels (see GRU fwd)
    return ((h_seq, h_fin, c_fin),
            (mask, w_h, pi, pf, po, z_tb, hprev_tb, cprev_tb, meta))


def _lstm_seq_bwd(allow_pallas, has_peepholes, res, ct):
    mask, w_h, pi, pf, po, z_r, hprev_r, cprev_r, meta = res
    xp_s, h0_s, c0_s = meta
    xp_dt, h0_dt, c0_dt = xp_s.dtype, h0_s.dtype, c0_s.dtype
    d_hseq, d_hfin, d_cfin = ct
    H = w_h.shape[0]
    B = mask.shape[0]
    f32 = jnp.float32
    w_f = w_h.astype(f32)
    pi_f, pf_f, po_f = (p.astype(f32) for p in (pi, pf, po))

    cp_f = cprev_r.astype(f32)                   # residuals are [T,B,*]
    if allow_pallas and _bwd_pallas_ok(B, H):
        from paddle_tpu.ops.pallas_kernels import _lstm_bwd_pallas_raw

        # residual streams enter in their STORED dtype (see GRU twin)
        d_z_tb, cn_tb, d_h0, d_c0 = _lstm_bwd_pallas_raw(
            jnp.moveaxis(d_hseq, 1, 0).astype(f32),
            jnp.moveaxis(mask, 1, 0).astype(f32),
            z_r, cprev_r, w_f.T.copy(),
            pi_f[None], pf_f[None], po_f[None],
            d_hfin.astype(f32), d_cfin.astype(f32),
            want_cn=has_peepholes)
    else:
        m_tb = jnp.moveaxis(mask, 1, 0)
        d_out_tb = jnp.moveaxis(d_hseq, 1, 0).astype(f32)
        # gate math vectorized over every timestep from the saved z/c_prev —
        # the reverse scan below is left with elementwise chain math plus
        # the single unavoidable carry matmul d_z @ w^T.  z is PRE-peephole;
        # peephole ("check") terms: i,f see c_prev, o sees c_new
        # (hl_lstm_ops.cuh), so d_c picks up pi/pf feedthrough and d_o
        # feeds c_new.
        z = z_r.astype(f32)
        i = jax.nn.sigmoid(z[..., :H] + pi_f * cp_f)
        f = jax.nn.sigmoid(z[..., H: 2 * H] + pf_f * cp_f)
        g = jnp.tanh(z[..., 3 * H:])
        cn_tb = f * cp_f + i * g
        o = jax.nn.sigmoid(z[..., 2 * H: 3 * H] + po_f * cn_tb)
        tc = jnp.tanh(cn_tb)

        def rev_step(carry, inp):
            d_h, d_c = carry
            d_out_t, m_t, i_t, f_t, o_t, g_t, tc_t, cp_t = inp
            mcol = (m_t > 0)[:, None].astype(f32)
            d_hnew = mcol * (d_out_t + d_h)
            d_zo = d_hnew * tc_t * o_t * (1 - o_t)
            d_cnew = (mcol * d_c + d_hnew * o_t * (1.0 - tc_t * tc_t)
                      + d_zo * po_f)
            d_zi = d_cnew * g_t * i_t * (1 - i_t)
            d_zf = d_cnew * cp_t * f_t * (1 - f_t)
            d_zg = d_cnew * i_t * (1 - g_t * g_t)
            d_cp = d_cnew * f_t + d_zi * pi_f + d_zf * pf_f
            d_z = jnp.concatenate([d_zi, d_zf, d_zo, d_zg], -1)
            d_hp = _bwd_mm(d_z, w_f.T)
            d_h_out = (1.0 - mcol) * d_h + d_hp
            d_c_out = (1.0 - mcol) * d_c + d_cp
            return (d_h_out, d_c_out), d_z

        (d_h0, d_c0), d_z_tb = lax.scan(
            rev_step, (d_hfin.astype(f32), d_cfin.astype(f32)),
            (d_out_tb, m_tb, i, f, o, g, tc, cp_f), reverse=True)

    # shared tail (ONE copy for both reverse-loop implementations)
    if has_peepholes:
        # peephole gradients: one batched reduction each, outside the loop
        d_pi = _bwd_einsum("tbh,tbh->h", d_z_tb[..., :H],
                           cp_f).astype(pi.dtype)
        d_pf = _bwd_einsum("tbh,tbh->h",
                           d_z_tb[..., H: 2 * H], cp_f).astype(pf.dtype)
        d_po = _bwd_einsum("tbh,tbh->h",
                           d_z_tb[..., 2 * H: 3 * H], cn_tb).astype(po.dtype)
    else:
        d_pi = jnp.zeros_like(pi)
        d_pf = jnp.zeros_like(pf)
        d_po = jnp.zeros_like(po)
    d_wh = _bwd_einsum("tbh,tbz->hz",
                       hprev_r.astype(f32), d_z_tb).astype(w_h.dtype)
    d_xp = jnp.moveaxis(d_z_tb, 0, 1).astype(xp_dt)
    return (d_xp, None, d_wh, d_h0.astype(h0_dt), d_c0.astype(c0_dt),
            d_pi, d_pf, d_po)


lstm_sequence_fused.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)


# ---------------------------------------------------------------------------
# Bidirectional GRU: BOTH directions in one sequential time loop.
#
# A bidirectional encoder is two INDEPENDENT scans over the same T steps —
# run separately they serialize (one TPU core runs one kernel at a time),
# paying the per-step launch/latency floor twice.  Here the batch carries
# both directions ([fw; time-flipped bw] rows) through ONE Pallas time
# loop whose per-step recurrent matmuls split the rows across the two
# directions' weights (pallas_kernels._gru_kernel batch_split) — half the
# sequential steps for the same FLOPs.  The flip trick is exact for
# right-padded sequences: flipping moves padding to the FRONT, where the
# masked steps hold the zero initial carry (scan_rnn semantics), then the
# real tokens arrive reversed; flipping the outputs back restores the
# reverse-GRU layout, and the final carry IS the reverse direction's final
# state.
# ---------------------------------------------------------------------------


def _use_pallas_bigru(batch: int, hidden: int) -> bool:
    """Gate for the fused bidirectional kernel: the working set is the
    2B-row batch, so the (vmem_limit-raised) caps double relative to the
    unidirectional gates.

    DEFAULT OFF (FLAGS.use_pallas_bigru): A/B-measured a TIE at the WMT14
    encoder shape on v5e (full train step 21.14 ms fused vs 21.04/21.24 ms
    two-scan, same process) — halving the sequential step count is offset
    by the doubled per-step latency chain (two row-half dots + concat).
    Kept as a recorded neutral A/B with its equivalence tests; flip the
    flag to re-test on other hardware/shapes."""
    import jax as _jax

    from paddle_tpu.utils.flags import FLAGS

    if not FLAGS.use_pallas_bigru:
        return False
    if not FLAGS.use_pallas_rnn:
        return False
    if _jax.default_backend() not in ("tpu", "axon"):
        return False
    if hidden % 128 != 0 or (2 * batch) % 8 != 0:
        return False
    cap = (768 * 512 if residual_dtype(hidden) == jnp.bfloat16
           else 512 * 512)
    return 2 * batch * hidden <= cap


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def bigru_sequence_fused(xp2, mask2, w_fw, w_bw, batch: int = 0):
    """Fused bidirectional GRU core: xp2 [2B,T,3H] carries the forward
    rows then the TIME-FLIPPED backward rows (mask2 likewise), w_fw/w_bw
    are the per-direction recurrent weights.  Returns (h_seq2 [2B,T,H],
    h_fin2 [2B,H]) in the same stacked layout (caller un-flips the second
    half).  Callers must gate on ``_use_pallas_bigru`` — this core always
    takes the Pallas kernels (interpret mode off-TPU)."""
    h_seq2, h_fin2 = _bigru_fwd(xp2, mask2, w_fw, w_bw, batch)[0]
    return h_seq2, h_fin2


def _bigru_fwd(xp2, mask2, w_fw, w_bw, batch):
    from paddle_tpu.ops.pallas_kernels import _gru_pallas_raw

    f32 = jnp.float32
    w2 = jnp.concatenate([w_fw, w_bw], 0).astype(f32)    # [2H, 3H]
    xp_tb = jnp.moveaxis(xp2.astype(f32), 1, 0)
    m_tb = jnp.moveaxis(mask2.astype(f32), 1, 0)
    h_tb, h_fin, z_r, hprev_r = _gru_pallas_raw(
        xp_tb, m_tb, w2, residuals=True, batch_split=batch)
    out = (jnp.moveaxis(h_tb, 0, 1), h_fin)
    meta = (jnp.zeros((0,), xp2.dtype),)
    return out, (mask2, w_fw, w_bw, z_r, hprev_r, meta)


def _bigru_bwd(batch, res, ct):
    from paddle_tpu.ops.pallas_kernels import _gru_bwd_pallas_raw

    mask2, w_fw, w_bw, z_r, hprev_r, (xp_s,) = res
    d_hseq, d_hfin = ct
    H = w_fw.shape[0]
    f32 = jnp.float32
    # transposed weights stacked on COLUMNS [3H, 2H] (fw cols then bw)
    w_t = jnp.concatenate([w_fw.astype(f32).T, w_bw.astype(f32).T], 1).copy()
    d_xp_tb, d_h02 = _gru_bwd_pallas_raw(
        jnp.moveaxis(d_hseq, 1, 0).astype(f32),
        jnp.moveaxis(mask2, 1, 0).astype(f32),
        z_r, hprev_r, w_t, d_hfin.astype(f32), batch_split=batch)
    # per-direction weight grads: one batched contraction over each half's
    # rows (residuals are time-major [T, 2B, *])
    hp_f = hprev_r.astype(f32)
    rh = jax.nn.sigmoid(z_r[..., :H].astype(f32)) * hp_f

    def d_w(rows):
        gates = jnp.einsum("tbh,tbz->hz", hp_f[:, rows],
                           d_xp_tb[:, rows, : 2 * H])
        cand = jnp.einsum("tbh,tbz->hz", rh[:, rows],
                          d_xp_tb[:, rows, 2 * H:])
        return jnp.concatenate([gates, cand], axis=1)

    fw_rows = slice(0, batch)
    bw_rows = slice(batch, None)
    d_xp = jnp.moveaxis(d_xp_tb, 0, 1).astype(xp_s.dtype)
    return (d_xp, None,
            d_w(fw_rows).astype(w_fw.dtype), d_w(bw_rows).astype(w_bw.dtype))


bigru_sequence_fused.defvjp(
    lambda xp2, mask2, w_fw, w_bw, batch: _bigru_fwd(
        xp2, mask2, w_fw, w_bw, batch),
    _bigru_bwd)
