"""Sequence ops over padded batches — analog of the reference's sequence tier.

The reference stores variable-length sequences *flat* (one [sum_len, D] matrix
+ start positions, reference: paddle/parameter/Argument.h:29-90) and provides
scatter/gather kernels between sequence and batch layouts
(paddle/cuda/src/hl_cuda_sequence.cu, gserver/layers/SequenceToBatch.h:23-46)
plus pooling/expand/concat layers (SequencePoolLayer.cpp, ExpandLayer.cpp...).

TPU-first design: XLA wants static shapes, so the device layout is a padded
dense batch ``value: [B, T, D]`` with ``lengths: [B] int32``; masks are derived
on the fly and fuse into consuming ops.  Host-side bucketing (data/feeder)
bounds padding waste, and sequence *packing* (segment_ids) is the long-form
analog used by the attention/parallel tier.  These functions are the kernel
surface the layer tier builds on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "PACK_KEYS",
    "segment_starts",
    "segment_valid",
    "segment_pool",
    "segment_last",
    "segment_first",
    "segment_expand",
    "mask_from_lengths",
    "seq_pool_sum",
    "seq_pool_avg",
    "seq_pool_sqrt",
    "seq_pool_max",
    "seq_last",
    "seq_first",
    "seq_expand",
    "seq_reverse",
    "seq_concat",
    "context_projection",
    "context_projection_trainable",
    "seq_slice_window",
]


def mask_from_lengths(lengths, max_len):
    """[B] lengths -> [B, T] float mask (1.0 for real positions)."""
    pos = jnp.arange(max_len, dtype=jnp.int32)[None, :]
    return (pos < lengths[:, None].astype(jnp.int32)).astype(jnp.float32)


def _masked(value, mask):
    return value * mask[..., None].astype(value.dtype)


def seq_pool_sum(value, mask):
    """[B,T,D],[B,T] -> [B,D] sum over real positions."""
    return jnp.sum(_masked(value, mask), axis=1)


def seq_pool_avg(value, mask):
    s = seq_pool_sum(value, mask)
    n = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
    return s / n.astype(s.dtype)


def seq_pool_sqrt(value, mask):
    # sum / sqrt(len) — the reference's "SquareRootN" average strategy
    s = seq_pool_sum(value, mask)
    n = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
    return s / jnp.sqrt(n).astype(s.dtype)


def seq_pool_max(value, mask):
    neg = jnp.finfo(value.dtype).min
    z = jnp.where(mask[..., None] > 0, value, neg)
    return jnp.max(z, axis=1)


def seq_last(value, lengths):
    """Last real timestep of each sequence: [B,T,D],[B] -> [B,D]."""
    idx = jnp.maximum(lengths.astype(jnp.int32) - 1, 0)
    return jnp.take_along_axis(value, idx[:, None, None], axis=1)[:, 0]


def seq_first(value):
    return value[:, 0]


def seq_expand(vec, mask):
    """Broadcast a per-sequence [B,D] vector to every timestep: -> [B,T,D].

    Analog of ExpandLayer (non-seq -> seq expansion); padded positions zeroed.
    """
    out = jnp.broadcast_to(vec[:, None, :], (vec.shape[0], mask.shape[1], vec.shape[1]))
    return _masked(out, mask)


def seq_reverse(value, lengths):
    """Reverse each sequence within its real length (padding stays at the end).

    Analog of SequenceReverseLayer; needed for bidirectional RNNs.
    """
    B, T = value.shape[0], value.shape[1]
    pos = jnp.arange(T, dtype=jnp.int32)[None, :]
    L = lengths[:, None].astype(jnp.int32)
    src = jnp.where(pos < L, L - 1 - pos, pos)
    return jnp.take_along_axis(value, src[..., None], axis=1)


def seq_concat(a, a_len, b, b_len):
    """Concatenate sequences along time: each row = a_i ++ b_i, repadded.

    Analog of SequenceConcatLayer.  Output T = Ta + Tb (static).
    """
    B, Ta = a.shape[0], a.shape[1]
    Tb = b.shape[1]
    T = Ta + Tb
    D = a.shape[2]
    pos = jnp.arange(T, dtype=jnp.int32)[None, :]
    aL = a_len[:, None].astype(jnp.int32)
    in_a = pos < aL
    a_pad = jnp.pad(a, ((0, 0), (0, Tb), (0, 0)))
    b_pad = jnp.pad(b, ((0, 0), (0, Ta), (0, 0)))
    b_idx = jnp.clip(pos - aL, 0, Tb + Ta - 1)
    b_shift = jnp.take_along_axis(b_pad, b_idx[..., None], axis=1)
    out = jnp.where(in_a[..., None], a_pad, b_shift)
    out_len = a_len + b_len
    mask = mask_from_lengths(out_len, T)
    return _masked(out, mask), out_len


def context_projection(value, mask, context_len, context_start,
                       seg_ids=None):
    """Sliding window over time: output[t] = concat(value[t+start .. t+start+len-1]).

    Analog of the reference's context projection kernels
    (paddle/cuda/src/hl_cuda_sequence.cu: hl_context_projection_forward;
    gserver/layers/ContextProjection.cpp).  Out-of-range positions are zero
    (trainable start padding is handled at the layer tier).  [B,T,D] ->
    [B,T,D*context_len].

    ``seg_ids`` (packed rows — docs/data.md) fences the window at segment
    boundaries: a shifted position belonging to a DIFFERENT segment reads
    as zero, exactly as if the neighbor were row padding — so packed and
    unpacked convolutions compute the same per-sample features.
    """
    B, T, D = value.shape
    v = _masked(value, mask)

    def shift(a, off, fill=0):
        if off < 0:
            return jnp.pad(a, ((0, 0), (-off, 0)) + ((0, 0),) * (a.ndim - 2),
                           constant_values=fill)[:, :T]
        if off > 0:
            return jnp.pad(a, ((0, 0), (0, off)) + ((0, 0),) * (a.ndim - 2),
                           constant_values=fill)[:, off: off + T]
        return a

    cols = []
    for k in range(context_len):
        off = context_start + k
        shifted = shift(v, off)
        if seg_ids is not None and off != 0:
            same = (shift(seg_ids, off, fill=-2) == seg_ids)
            shifted = shifted * same[..., None].astype(shifted.dtype)
        cols.append(shifted)
    out = jnp.concatenate(cols, axis=-1)
    return _masked(out, mask)


def context_projection_trainable(value, lengths, mask, context_len, context_start,
                                 pad_weights):
    """Context projection with TRAINABLE boundary padding.

    Analog of ContextProjection with ``trainable_padding`` (reference:
    gserver/layers/ContextProjection.cpp:36-63 — ``beginPad_ = max(0,
    -context_start)``, end pad rows fill positions past the sequence end).
    ``pad_weights`` is [begin_pad + end_pad, D]: row ``p`` of the begin block
    substitutes source position ``p - begin_pad`` (< 0); row ``begin_pad + q``
    substitutes source position ``length + q`` (>= length).  [B,T,D] ->
    [B,T,D*context_len]; gradients flow into the used padding rows.
    """
    B, T, D = value.shape
    begin_pad = max(0, -context_start)
    v = _masked(value, mask)
    L = lengths[:, None].astype(jnp.int32)
    cols = []
    for k in range(context_len):
        off = context_start + k
        pos = jnp.arange(T, dtype=jnp.int32)[None, :] + off  # [1, T]
        src = jnp.clip(pos, 0, T - 1)
        shifted = jnp.take_along_axis(v, jnp.broadcast_to(src[..., None], (B, T, 1)), axis=1)
        before = pos < 0                       # [1, T] -> broadcasts
        after = pos >= L                       # [B, T]
        pad_row = jnp.where(
            pos < 0, pos + begin_pad, begin_pad + (pos - L)
        )
        pad_row = jnp.clip(pad_row, 0, pad_weights.shape[0] - 1)
        pad_vals = pad_weights[pad_row].astype(shifted.dtype)  # [B, T, D]
        use_pad = jnp.broadcast_to(before | after, (B, T))
        col = jnp.where(use_pad[..., None], pad_vals, shifted)
        cols.append(col)
    out = jnp.concatenate(cols, axis=-1)
    return _masked(out, mask)


# ---------------------------------------------------------------------------
# sequence packing (docs/data.md "Sequence packing", --data_pack)
#
# A packed row holds several whole sequences back-to-back: seg_ids [B,T]
# gives each token its 0-based segment index (-1 on padding), positions
# [B,T] its within-segment offset, seg_lengths [B,S] the token count per
# segment (0 = unused slot; S is the static max_segments).  These ops are
# the packed analogs of the padded-batch reductions above — the layer
# tier dispatches to them whenever the Act carries the pack state.
# ---------------------------------------------------------------------------

#: the Act.state keys that mark (and plumb) a packed sequence
PACK_KEYS = ("seg_ids", "positions", "seg_lengths")


def segment_valid(seg_lengths):
    """[B,S] per-segment token counts -> [B,S] float validity mask."""
    return (seg_lengths > 0).astype(jnp.float32)


def segment_starts(seg_ids, mask, *, reverse=False):
    """[B,T] mask of segment ENTRY positions for a scan direction: where
    the recurrent carry must reset so state never flows across packed
    neighbors.  Forward entry = first token of each segment; reverse
    entry = last token (a reverse scan meets segments tail-first)."""
    pad = jnp.full_like(seg_ids[:, :1], -1)
    if reverse:
        neighbor = jnp.concatenate([seg_ids[:, 1:], pad], axis=1)
    else:
        neighbor = jnp.concatenate([pad, seg_ids[:, :-1]], axis=1)
    return ((seg_ids != neighbor) & (mask > 0)).astype(jnp.float32)


def _flat_segments(seg_ids, mask, S):
    """Flatten [B,T] segment addressing to [B*T] global segment ids with
    invalid positions routed to a drop bucket (index B*S)."""
    B, T = seg_ids.shape
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    flat = rows * S + jnp.clip(seg_ids, 0, S - 1)
    valid = (mask > 0) & (seg_ids >= 0) & (seg_ids < S)
    return jnp.where(valid, flat, B * S).reshape(-1), valid


def segment_pool(value, mask, seg_ids, seg_lengths, pooling_type="max"):
    """Per-SEGMENT pooling over a packed row: [B,T,D] -> [B,S,D] (the
    packed analog of seq_pool_*).  Empty segment slots come out zero."""
    B, T, D = value.shape
    S = seg_lengths.shape[1]
    flat, valid = _flat_segments(seg_ids, mask, S)
    vmask = valid[..., None]
    counts = seg_lengths.astype(value.dtype)[..., None]
    if pooling_type == "max":
        neg = jnp.finfo(value.dtype).min
        data = jnp.where(vmask, value, neg).reshape(B * T, D)
        out = jax.ops.segment_max(data, flat,
                                  num_segments=B * S + 1)[: B * S]
        out = out.reshape(B, S, D)
        return jnp.where(counts > 0, out, jnp.zeros_like(out))
    data = (value * vmask.astype(value.dtype)).reshape(B * T, D)
    out = jax.ops.segment_sum(data, flat,
                              num_segments=B * S + 1)[: B * S]
    out = out.reshape(B, S, D)
    if pooling_type == "sum":
        return out
    n = jnp.maximum(counts, 1.0)
    if pooling_type == "avg":
        return out / n
    if pooling_type == "sqrt":
        return out / jnp.sqrt(n)
    raise ValueError(f"unknown segment pooling type {pooling_type!r}")


def _segment_starts_idx(seg_lengths):
    """[B,S] exclusive prefix sum — each segment's first token index
    (packing lays segments out contiguously, in order)."""
    return jnp.cumsum(seg_lengths, axis=1) - seg_lengths


def segment_last(value, seg_lengths):
    """Last real token of every segment: [B,T,D] -> [B,S,D] (packed
    seq_last).  Empty slots zero."""
    T = value.shape[1]
    starts = _segment_starts_idx(seg_lengths)
    idx = jnp.clip(starts + jnp.maximum(seg_lengths, 1) - 1, 0, T - 1)
    out = jnp.take_along_axis(value, idx[..., None], axis=1)
    return out * segment_valid(seg_lengths)[..., None].astype(out.dtype)


def segment_first(value, seg_lengths):
    """First token of every segment: [B,T,D] -> [B,S,D] (packed
    seq_first)."""
    T = value.shape[1]
    idx = jnp.clip(_segment_starts_idx(seg_lengths), 0, T - 1)
    out = jnp.take_along_axis(value, idx[..., None], axis=1)
    return out * segment_valid(seg_lengths)[..., None].astype(out.dtype)


def segment_expand(vec, seg_ids, mask):
    """Broadcast a per-SEGMENT [B,S,D] vector back over the packed token
    axis: -> [B,T,D], padding zeroed (packed seq_expand)."""
    S = vec.shape[1]
    idx = jnp.clip(seg_ids, 0, S - 1)[..., None]
    out = jnp.take_along_axis(vec, idx, axis=1)
    return _masked(out, mask)


def seq_slice_window(value, starts, width):
    """Gather a fixed-width window starting at per-row dynamic offsets."""
    B, T, D = value.shape
    pos = starts[:, None].astype(jnp.int32) + jnp.arange(width, dtype=jnp.int32)[None, :]
    pos = jnp.clip(pos, 0, T - 1)
    return jnp.take_along_axis(value, pos[..., None], axis=1)
