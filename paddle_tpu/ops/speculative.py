"""Draft proposers for speculative decoding.

The wide-verify step (:func:`paddle_tpu.ops.decode.spec_verify_step`)
scores k draft tokens per slot in ONE fused call and accepts the longest
prefix the model itself would have emitted greedily.  The verify side
guarantees bit-identity no matter what the drafts are — proposers only
control *acceptance rate*, i.e. how much of each wide step is useful
work.  That makes the proposer a pure host-side heuristic: it runs on
the emission history the scheduler already tracks, costs microseconds,
and needs no device state.

Built-in proposers:

- :class:`NGramProposer` — suffix-match drafting (the "prompt lookup" /
  n-gram speculation trick): find the most recent earlier occurrence of
  the last-n emitted tokens and propose whatever followed it.  Free,
  model-agnostic, and very effective on repetitive output — which is
  exactly what small-vocab greedy decodes produce.
- :class:`CallableDraftProposer` — adapt any ``history, k -> tokens``
  callable; the hook for a small-model draft (run a distilled model on
  host or a second device, return its greedy continuation).
- :class:`AdversarialProposer` — always-wrong drafts, for chaos testing
  (``resilience.chaos.bad_draft``): throughput must degrade to the
  standard ≥1 token/step, never corrupt output.

Protocol: ``propose(history, k) -> list[int]`` of length exactly k,
where ``history`` is the slot's emission history INCLUDING the BOS
token at position 0.  Proposers must be pure host code — no jax calls —
so drafting never touches the compiled surface.

``learn``/``propose_with_confidence`` additionally accept an optional
``key`` — the scheduler's content hash of the request (model
fingerprint + canonical feed bytes + session id).  Greedy decode is
deterministic, so two requests with the same key emit the SAME
sequence: a completed trajectory stored under the key can be replayed
*positionally* (draft ``seq[len(history):]``), which sidesteps the
fundamental ambiguity of n-gram drafting — the same n-gram can occur
at several positions of one trajectory with different successors
(decoder state disambiguates them; a context window cannot), capping
n-gram acceptance well below 1 even on exact repeats.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

__all__ = [
    "DraftProposer",
    "NGramProposer",
    "CallableDraftProposer",
    "AdversarialProposer",
]


class DraftProposer:
    """Base draft proposer: ``propose(history, k)`` returns exactly k
    candidate next tokens for a slot whose emissions so far (BOS
    included) are ``history``.  Default: repeat the last token.

    ``learn(seq)`` is the cross-request feedback hook: the scheduler
    feeds every completed request's emission sequence back to the
    proposer, so session/template traffic (many requests decoding the
    same or similar output) can be drafted from previously seen
    completions, not just the current slot's own history.  Default:
    no-op — stateless proposers simply ignore it."""

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        last = int(history[-1]) if history else 0
        return [last] * k

    def learn(self, seq: Sequence[int],
              key: Optional[str] = None) -> None:
        """Record a completed emission sequence (BOS included); ``key``
        is the scheduler's request content hash, or None when the
        request is unkeyable.  No-op in the base class."""

    def propose_with_confidence(self, history: Sequence[int], k: int,
                                key: Optional[str] = None,
                                ) -> "tuple[List[int], bool]":
        """``(drafts, confident)`` — ``confident`` tells the scheduler
        whether these drafts come from a real predictive source (learned
        corpus, suffix match, draft model) or are a blind fallback.
        When NO slot in a wide step has a confident draft, the scheduler
        gates speculation off for that step and runs the plain
        one-token path instead of paying the (k+1)-position verify for
        a guaranteed single emission.  Base class: never confident."""
        return self.propose(history, k), False


class NGramProposer(DraftProposer):
    """Suffix-match drafting: for n = order..1, find the most recent
    *earlier* occurrence of the last-n-token suffix in the history and
    propose the tokens that followed it (extending by repeating the
    final proposal when the match runs off the end).  Falls back to
    repeating the last token when no suffix recurs.

    ``learn`` additionally records COMPLETED emission sequences two
    ways.  (1) Keyed positional replay: when the scheduler supplies a
    request content ``key``, the WHOLE sequence is stored under it;
    a later request with the same key drafts ``seq[len(history):]``
    after an exact prefix check.  Greedy decode is deterministic, so
    positional replay is exact on repeat/template traffic — acceptance
    ~1.0 — where pure n-gram drafting tops out far lower (the same
    n-gram recurs within one trajectory with different successors,
    and newest-wins indexing can only keep one of them).  (2) A shared
    n-gram table (suffix tuple -> observed continuation, newest wins),
    consulted when there is no positional hit: near-miss traffic —
    similar but not identical requests — still drafts well from it.
    Both are plain host dicts, ``O(order · len)`` inserts per completed
    request and O(order) lookups per proposal; each self-clears past
    its bound so a long-lived server cannot grow them without limit.

    O(order · len(history)) python per call — negligible next to a
    device dispatch, and the scheduler history is capped at ``max_len``.
    """

    #: continuation tokens stored per indexed suffix (propose() slices k
    #: of them; callers wanting k > this fall back to suffix extension)
    _CONT = 32

    def __init__(self, order: int = 3, max_entries: int = 200_000,
                 max_seqs: int = 4096):
        if order < 1:
            raise ValueError(f"order must be >= 1, got {order}")
        self.order = int(order)
        self.max_entries = int(max_entries)
        self.max_seqs = int(max_seqs)
        self._index = {}
        self._seqs = {}   # request content key -> full emission sequence

    def learn(self, seq: Sequence[int],
              key: Optional[str] = None) -> None:
        s = [int(t) for t in seq]
        if key is not None:
            if len(self._seqs) > self.max_seqs:
                self._seqs.clear()   # crude but bounded; relearns fast
            self._seqs[key] = s      # newest completion wins
        if len(self._index) > self.max_entries:
            self._index.clear()
        for n in range(1, self.order + 1):
            for i in range(n, len(s)):
                self._index[(n, tuple(s[i - n:i]))] = s[i:i + self._CONT]

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        return self.propose_with_confidence(history, k)[0]

    def propose_with_confidence(self, history: Sequence[int], k: int,
                                key: Optional[str] = None):
        h = [int(t) for t in history]
        L = len(h)
        # keyed positional replay first: an identical earlier request's
        # completed trajectory.  The O(L) prefix check makes it exact —
        # if this slot's emissions have diverged (it isn't actually the
        # same request, or the model was swapped between learn and now),
        # fall through to the n-gram paths rather than replay garbage.
        if key is not None:
            seq = self._seqs.get(key)
            if seq is not None and len(seq) > L and seq[:L] == h:
                out = seq[L:L + k]
                while len(out) < k:
                    out.append(out[-1])
                return [int(t) for t in out], True
        # learned-corpus lookup first, longest context first: completed
        # requests are whole trajectories, strictly more predictive than
        # this slot's partial history
        for n in range(min(self.order, L), 0, -1):
            out = self._index.get((n, tuple(h[L - n:])))
            if out:
                out = list(out[:k])
                while len(out) < k:
                    out.append(out[-1])
                return [int(t) for t in out], True
        # in-history fallback: one-shot index of the slot's own history
        # (suffix tuple -> most recent continuation offset), then O(order)
        # lookups — O(order * L) per call.  The naive nested scan is
        # O(order * L^2) python per slot per step, which at serving
        # histories costs more than the fused wide step it feeds.
        local = {}
        for n in range(1, min(self.order, L - 1) + 1):
            for i in range(n, L):
                local[(n, tuple(h[i - n:i]))] = i
        for n in range(min(self.order, L - 1), 0, -1):
            i = local.get((n, tuple(h[L - n:])))
            if i is not None:
                out = h[i:i + k]
                while len(out) < k:
                    out.append(out[-1] if out else h[-1])
                return [int(t) for t in out], True
        return DraftProposer.propose(self, h, k), False


class CallableDraftProposer(DraftProposer):
    """Wrap a ``(history, k) -> sequence`` callable as a proposer — the
    small-model draft hook.  The callable's output is truncated/padded
    to exactly k tokens; any model-based drafter (a distilled LM run on
    host, a second-device greedy decode) plugs in here without the
    scheduler knowing."""

    def __init__(self, fn: Callable[[Sequence[int], int], Sequence[int]]):
        self._fn = fn

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        out = [int(t) for t in self._fn(history, k)][:k]
        if not out:
            return DraftProposer.propose(self, history, k)
        while len(out) < k:
            out.append(out[-1])
        return out

    def propose_with_confidence(self, history: Sequence[int], k: int,
                                key: Optional[str] = None):
        # a model-based drafter is a real predictive source: always
        # worth verifying (gating is for blind fallback drafts only)
        return self.propose(history, k), True


class AdversarialProposer(DraftProposer):
    """Always-wrong drafts (chaos hook ``bad_draft``): propose a fixed
    token so verification rejects every draft position.  The wide step
    then degrades to the standard one-token-per-step rate — output must
    stay bit-identical, only throughput suffers (pinned by tests)."""

    def __init__(self, token: int = 0):
        self.token = int(token)

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        return [self.token] * k

    def propose_with_confidence(self, history: Sequence[int], k: int,
                                key: Optional[str] = None):
        # claim confidence so the scheduler CANNOT gate these drafts
        # away — the chaos hook must actually exercise the wide-verify
        # reject path, not fall back to the plain step
        return self.propose(history, k), True
