"""Attention primitives.

The reference predates fused attention; its seq2seq demo builds Bahdanau
attention out of primitive layers — ``simple_attention`` =
fc(expand(decoder_state)) + encoded_proj -> tanh -> fc(1) -> sequence_softmax
-> weighted sum (reference: python/paddle/trainer_config_helpers/networks.py
simple_attention; demo/seqToseq/seqToseq_net.py), using
ConvexCombinationLayer / InterpolationLayer style primitives
(gserver/layers/LinearChainCRF… ConvexCombination in CostLayer neighborhood).

TPU-first: the same math as fused batched einsums over padded [B, S, D]
encodings with masks; plus modern scaled-dot-product attention as a
first-class op (the parallel tier adds the ring/sequence-parallel variant).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.ops.matmul import linear, matmul
from paddle_tpu.ops.numerics import acc_dtype, dot_dtype, mxu_cast

__all__ = ["additive_attention_scores", "attend", "dot_product_attention"]


def additive_attention_scores(enc_proj, dec_state, w_dec, v):
    """Bahdanau scores: tanh(enc_proj + dec_state @ w_dec) @ v.

    enc_proj: [B, S, A] (precomputed once per source — the reference's
    ``encoded_proj``), dec_state: [B, D], w_dec: [D, A], v: [A].
    Returns [B, S] unnormalized scores.
    """
    q = linear(dec_state, w_dec)[:, None, :]  # [B, 1, A]
    # the [B, S, A] intermediate is re-read every decode step — keep it in
    # the bf16 compute dtype so the bandwidth-bound tanh/add/dot run at half
    # the HBM traffic; scores accumulate in f32
    enc_proj, q = mxu_cast(enc_proj, q)
    e = jnp.tanh(enc_proj + q)
    return jnp.einsum("bsa,a->bs", e, v.astype(e.dtype),
                      preferred_element_type=acc_dtype())


def attend(scores, values, mask):
    """Mask + softmax scores over S, then weighted sum of values.

    scores: [B, S], values: [B, S, D], mask: [B, S] -> (context [B, D],
    weights [B, S]).
    """
    neg = jnp.finfo(scores.dtype).min
    z = jnp.where(mask > 0, scores, neg)
    w = jax.nn.softmax(z, axis=-1) * mask.astype(scores.dtype)
    w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-9)
    wc, vc = mxu_cast(w, values)
    # the context is an ACTIVATION (scores/softmax above stay f32 — the
    # --amp allowlist): it leaves at dot_dtype, bf16 under amp
    ctx = jnp.einsum("bs,bsd->bd", wc, vc,
                     preferred_element_type=dot_dtype()).astype(dot_dtype())
    return ctx, w


def dot_product_attention(q, k, v, mask=None, *, scale=None):
    """Batched multi-head SDPA: q [B,H,Tq,Dh], k/v [B,H,Tk,Dh].

    mask: broadcastable to [B, H, Tq, Tk] (1 = attend). f32 softmax, bf16
    matmuls on the MXU.
    """
    if scale is None:
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    qc, kc, vc = mxu_cast(q, k, v)
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", qc, kc, preferred_element_type=acc_dtype()
    ) * scale
    if mask is not None:
        logits = jnp.where(mask > 0, logits, jnp.finfo(logits.dtype).min)
    w = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", w.astype(vc.dtype), vc,
        preferred_element_type=dot_dtype(),
    )
    return out.astype(q.dtype)
