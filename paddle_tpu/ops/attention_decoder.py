"""Fused-backward attention GRU decoder — the seq2seq training hot loop.

Semantically identical to scanning ``additive_attention_scores`` + ``attend``
+ concat + ``linear`` + ``gru_step`` over the target sequence (the Bahdanau
decoder of demo/seqToseq, reference: demo/seqToseq/api_train_v2.py:90-189,
gserver/gradientmachines/RecurrentGradientMachine.cpp) — but with a
hand-written VJP that restructures the backward pass for TPU HBM bandwidth.

Why: XLA's autodiff of that scan carries the cotangent accumulators
``d_enc`` [B,S,2H] and the weight grads through HBM on EVERY reverse step —
at WMT14 bench shapes that is ~45+ MB of accumulator read+write per step,
~10x the cost of the forward scan (measured 4.4 ms backward vs 0.45 ms
forward on v5e).  The custom VJP instead:

- precomputes the GRU gates and attention queries for ALL steps as batched
  MXU matmuls before the reverse scan (they depend only on saved forward
  values),
- emits the SMALL per-step cotangents (``d_xp`` [B,3D], ``sum_dpre``
  [B,A]) as stacked scan outputs,
- reconstructs every weight gradient AFTER the scan as one batched MXU
  contraction each (``d_enc``, ``d_Wx``, ``d_Wh``, ``d_attw``, ``d_b``,
  ``d_y``),
- keeps only the genuinely unavoidable accumulators (``d_enc_proj`` —
  nonlinear in t — and the tiny ``d_v``) in the reverse scan.  Scan
  accumulators are f32: summing T bfloat16 terms drifts for long targets,
  and a bf16 ``d_enc_proj`` carry A/B-measured slower anyway.

Forward saves (probs [T,B,S] f32, ctx [T,B,2H] in the compute dtype,
s_prev [T,B,D] f32 — the carry entering each step, stacked so the backward
needs no sequential carry-reconstruction scan) — O(B·T·(S+2H+D)) residual
buffers alongside the primal states output, ~100-125 MB at bench shapes vs
the ~1.3 GB/step-loop accumulator traffic the restructure removes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.matmul import linear
from paddle_tpu.ops.numerics import (acc_dtype, bwd_einsum,
                                     bwd_mm, dot_dtype, mxu_cast)

__all__ = ["attention_gru_decoder"]


def _attn_pallas_block(B, S, D, A, H2):
    """Batch-block size for the VMEM-resident Pallas decoder kernels
    (ops/pallas_kernels.py: attn_dec_fwd_pallas / attn_dec_bwd_pallas), or
    None to use the XLA scan path.  Gates: flag + TPU backend + lane/tile
    alignment (the kernels slice [Bb, S, A]/[Bb, gates*D] blocks) + the
    resident working set (enc, enc_proj, the backward's d_enc_proj
    accumulator and its d_pre temporary, all per block) must fit the raised
    VMEM budget."""
    import jax as _jax

    from paddle_tpu.utils.flags import FLAGS

    if not FLAGS.use_pallas_attention:
        return None
    if _jax.default_backend() not in ("tpu", "axon"):
        return None
    if D % 128 or A % 128 or H2 % 128 or S % 8:
        return None
    for bb in (128, 96, 64, 32, 16, 8):
        # f32 worst case: enc_proj + enc resident, plus 2x [Bb,S,A] f32
        # (accumulator + d_pre temp) in the backward
        if B % bb == 0 and bb * S * (12 * A + 4 * H2) <= 48 * 1024 * 1024:
            return bb
    return None


def _fwd_step(s, xp_y_t, enc, enc_proj, src_mask, att_w, att_v, wx_c, wh):
    """One decoder step; mirrors additive_attention_scores/attend/gru_step
    numerics (bf16 matmul operands, f32 accumulation).  ``xp_y_t`` is the
    teacher-forced half of the input projection, HOISTED out of the scan as
    one [B,T,E]x[E,3D] MXU matmul (+bias) — only the context half
    (``ctx @ wx_c``) depends on the recurrent state, so only it stays in the
    loop.  Measured step-time NEUTRAL on v5e at B384 WMT14 shapes (24.5 vs
    24.6 ms — the scan is latency-bound, not FLOP-bound); kept because it
    shrinks the sequential per-step work and matches the DSL's
    separate-projection composition."""
    D = s.shape[-1]
    # --- additive_attention_scores ---
    q = linear(s, att_w)[:, None, :]
    enc_proj_c, q_c = mxu_cast(enc_proj, q)
    pre = jnp.tanh(enc_proj_c + q_c)                       # [B,S,A]
    scores = jnp.einsum("bsa,a->bs", pre, att_v.astype(pre.dtype),
                        preferred_element_type=acc_dtype())
    # --- attend ---
    neg = jnp.finfo(scores.dtype).min
    z = jnp.where(src_mask > 0, scores, neg)
    w0 = jax.nn.softmax(z, axis=-1)
    w1 = w0 * src_mask.astype(scores.dtype)
    n = jnp.maximum(jnp.sum(w1, axis=-1, keepdims=True), 1e-9)
    w = w1 / n
    wc, vc = mxu_cast(w, enc)
    # the context is an ACTIVATION (the softmax above stays f32): it
    # leaves at dot_dtype, bf16 under --amp
    ctx = jnp.einsum("bs,bsd->bd", wc, vc,
                     preferred_element_type=dot_dtype()).astype(dot_dtype())
    # --- input projection + gru_step ---
    xp = xp_y_t + linear(ctx, wx_c)
    zr = xp[..., : 2 * D] + linear(s, wh[:, : 2 * D])
    r, u = jnp.split(jax.nn.sigmoid(zr), 2, axis=-1)
    cand = jnp.tanh(xp[..., 2 * D:] + linear(r * s, wh[:, 2 * D:]))
    s_new = u * s + (1.0 - u) * cand
    return s_new, (w, ctx, pre)


@partial(jax.custom_vjp, nondiff_argnums=())
def attention_gru_decoder(y_emb, s0, enc, enc_proj, src_mask, trg_mask,
                          att_w, att_v, wx, b, wh):
    """y_emb [B,T,E], s0 [B,D], enc [B,S,2H], enc_proj [B,S,A],
    src_mask [B,S], trg_mask [B,T] -> states [B,T,D] (zeroed at padded
    target steps, carry held — scan_rnn masking semantics)."""
    states, _ = _decoder_fwd_scan(y_emb, s0, enc, enc_proj, src_mask,
                                  trg_mask, att_w, att_v, wx, b, wh)
    return states


def _decoder_fwd_scan(y_emb, s0, enc, enc_proj, src_mask, trg_mask,
                      att_w, att_v, wx, b, wh):
    E = y_emb.shape[-1]
    # hoisted teacher-forced half of the input projection (+ bias), one
    # batched MXU matmul over all steps
    xp_y = linear(y_emb, wx[:E], b)                        # [B,T,3D] f32
    xp_y_tb = jnp.moveaxis(xp_y, 1, 0)                     # [T,B,3D]
    m_tb = jnp.moveaxis(trg_mask, 1, 0)                    # [T,B]
    wx_c = wx[E:]

    from paddle_tpu.ops.numerics import compute_dtype

    rd = compute_dtype()  # residual stream dtype (bf16 under prod policy)

    B, T = trg_mask.shape
    bb = _attn_pallas_block(B, enc.shape[1], s0.shape[-1],
                            enc_proj.shape[-1], enc.shape[2])
    if bb is not None:
        from paddle_tpu.ops.pallas_kernels import attn_dec_fwd_pallas

        f32 = jnp.float32
        enc_c, encP_c, attw_c, attv_c, wxc_c, wh_c = mxu_cast(
            enc, enc_proj, att_w, att_v, wx_c, wh)
        outs, probs, ctxs, s_prev = attn_dec_fwd_pallas(
            xp_y_tb.astype(f32), m_tb.astype(f32), s0.astype(f32),
            enc_c, encP_c, src_mask.astype(f32),
            attw_c, attv_c, wxc_c, wh_c, block_b=bb)
        return jnp.moveaxis(outs, 0, 1), (probs, ctxs, s_prev)

    def step(s, inp):
        xp_y_t, m_t = inp
        s_new, (w, ctx, _pre) = _fwd_step(s, xp_y_t, enc, enc_proj, src_mask,
                                          att_w, att_v, wx_c, wh)
        keep = (m_t > 0)[:, None]
        s_out = jnp.where(keep, s_new, s)
        out = s_out * m_t[:, None].astype(s_out.dtype)
        # s (the carry ENTERING the step) is exactly the s_prev the backward
        # needs — stacking it here deletes the backward's sequential
        # carry-reconstruction scan
        return s_out, (out, w, ctx.astype(rd), s)

    _, (outs, probs, ctxs, s_prev) = lax.scan(step, s0, (xp_y_tb, m_tb))
    states = jnp.moveaxis(outs, 0, 1)                      # [B,T,D]
    return states, (probs, ctxs, s_prev)


def _agd_fwd(y_emb, s0, enc, enc_proj, src_mask, trg_mask,
             att_w, att_v, wx, b, wh):
    states, (probs, ctxs, s_prev) = _decoder_fwd_scan(
        y_emb, s0, enc, enc_proj, src_mask, trg_mask, att_w, att_v, wx, b, wh)
    res = (y_emb, s0, enc, enc_proj, src_mask, trg_mask,
           att_w, att_v, wx, b, wh, s_prev, probs, ctxs)
    return states, res


def _agd_bwd(res, d_states):
    (y_emb, s0, enc, enc_proj, src_mask, trg_mask,
     att_w, att_v, wx, b, wh, s_prev, probs, ctxs) = res
    B, T = trg_mask.shape
    D = s0.shape[-1]
    S = enc.shape[1]
    E = y_emb.shape[-1]
    f32 = jnp.float32

    y_tb = jnp.moveaxis(y_emb, 1, 0)                       # [T,B,E]
    m_tb = jnp.moveaxis(trg_mask, 1, 0)                    # [T,B]
    # recompute the hoisted y-projection (single deterministic matmul ->
    # bitwise-identical to the forward's values; cheaper than carrying a
    # [T,B,3D] f32 residual)
    xp_y_tb = jnp.moveaxis(linear(y_emb, wx[:E], b), 1, 0)
    d_out_tb = jnp.moveaxis(d_states, 1, 0).astype(f32)    # [T,B,D]
    # s_prev [T,B,D] arrives stacked straight from the forward scan (the
    # carry entering each step) — no reconstruction scan needed

    att_w_f, att_v_f = att_w.astype(f32), att_v.astype(f32)
    wx_f, wh_f = wx.astype(f32), wh.astype(f32)
    neg = jnp.finfo(f32).min
    maskb = (src_mask > 0)
    mask_f = src_mask.astype(f32)

    # ---- GRU gate recompute VECTORIZED over all steps (batched MXU
    # matmuls; was two matmuls inside every reverse step) ----
    xp_all = (xp_y_tb + linear(ctxs, wx[E:])).astype(f32)  # [T,B,3D]
    zr_all = xp_all[..., : 2 * D] + linear(s_prev, wh[:, : 2 * D]).astype(f32)
    ru_all = jax.nn.sigmoid(zr_all)
    r_all = ru_all[..., :D]
    u_all = ru_all[..., D:]
    cand_all = jnp.tanh(xp_all[..., 2 * D:]
                        + linear((r_all * s_prev.astype(f32)).astype(
                            s_prev.dtype), wh[:, 2 * D:]).astype(f32))
    # the attention query is also state-only: one batched matmul
    q_all = linear(s_prev, att_w)                          # [T,B,A]

    def rev_step(carry, inp):
        d_s, d_encP, d_v = carry
        d_out_t, m_t, w_t, sp_t, r, u, cand, q_t = inp
        mcol = (m_t > 0)[:, None].astype(f32)
        d_snew = mcol * (d_out_t + d_s)
        sp = sp_t.astype(f32)

        # ---- GRU backward (gates precomputed above) ----
        d_u = d_snew * (sp - cand)
        d_cand = d_snew * (1.0 - u)
        d_h = d_snew * u
        d_zc = d_cand * (1.0 - cand * cand)
        d_rh = bwd_mm(d_zc, wh_f[:, 2 * D:].T)
        d_r = d_rh * sp
        d_h = d_h + d_rh * r
        d_zr = jnp.concatenate([d_r * r * (1 - r), d_u * u * (1 - u)], -1)
        d_h = d_h + bwd_mm(d_zr, wh_f[:, : 2 * D].T)
        d_xp = jnp.concatenate([d_zr, d_zc], -1)           # [B,3D]
        d_ctx = bwd_mm(d_xp, wx_f[E:].T)                   # [B,2H]

        # ---- attention backward (attend) ----
        d_w = jnp.einsum("bh,bsh->bs", d_ctx.astype(enc.dtype), enc,
                         preferred_element_type=f32)
        # recompute softmax chain from the precomputed query
        enc_proj_c, q_c = mxu_cast(enc_proj, q_t[:, None, :])
        pre = jnp.tanh(enc_proj_c + q_c)                   # [B,S,A] cd
        scores = jnp.einsum("bsa,a->bs", pre, att_v.astype(pre.dtype),
                            preferred_element_type=f32)
        z = jnp.where(maskb, scores, neg)
        w0 = jax.nn.softmax(z, axis=-1)
        w1 = w0 * mask_f
        n = jnp.maximum(jnp.sum(w1, axis=-1, keepdims=True), 1e-9)
        # w = w1/n
        d_w1 = d_w / n
        d_n = -jnp.sum(d_w * w1, axis=-1, keepdims=True) / (n * n)
        d_w1 = d_w1 + d_n * (jnp.sum(w1, -1, keepdims=True) > 1e-9).astype(f32)
        d_w0 = d_w1 * mask_f
        d_z = w0 * (d_w0 - jnp.sum(w0 * d_w0, axis=-1, keepdims=True))
        d_scores = jnp.where(maskb, d_z, 0.0)
        pre_f = pre.astype(f32)
        d_pre = (1.0 - pre_f * pre_f) * (d_scores[..., None] * att_v_f)
        # accumulate in f32: summing T bf16 terms loses precision for long
        # targets, and a bf16 accumulator A/B-measured SLOWER anyway
        # (23.6 vs 22.5 ms at B384 — the per-step down-cast pass costs
        # more than the narrower carry saves)
        d_encP = d_encP + d_pre
        sum_dpre = jnp.sum(d_pre, axis=1)                  # [B,A]
        d_h = d_h + bwd_mm(sum_dpre, att_w_f.T)
        d_v = d_v + bwd_einsum("bs,bsa->a", d_scores, pre_f)

        d_s_out = (1.0 - mcol) * d_s + d_h
        return (d_s_out, d_encP, d_v), (d_xp, sum_dpre)

    A = enc_proj.shape[-1]
    bb = _attn_pallas_block(B, S, D, A, enc.shape[2])
    if bb is not None:
        from paddle_tpu.ops.pallas_kernels import attn_dec_bwd_pallas

        enc_c, encP_c, attv_c = mxu_cast(enc, enc_proj, att_v)
        d_xp_tb, sum_dpre_tb, d_encP, d_v, d_s0 = attn_dec_bwd_pallas(
            d_out_tb, m_tb.astype(f32), s_prev.astype(f32),
            r_all, u_all, cand_all, q_all,
            enc_c, encP_c, src_mask.astype(f32),
            att_w_f, attv_c, att_v_f, wh_f, wx_f[E:], block_b=bb)
    else:
        acc0 = (jnp.zeros((B, D), f32),
                jnp.zeros((B, S, A), f32),
                jnp.zeros(att_v.shape, f32))
        (d_s0, d_encP, d_v), (d_xp_tb, sum_dpre_tb) = lax.scan(
            rev_step, acc0,
            (d_out_tb, m_tb, probs, s_prev, r_all, u_all, cand_all, q_all),
            reverse=True)
    d_b = jnp.sum(d_xp_tb, axis=(0, 1))  # bias grad off the stacked output

    # ---- batched post-scan contractions (weight grads were carried
    # through the scan before — each is now ONE MXU einsum) ----
    d_ctx_tb = bwd_mm(d_xp_tb, wx_f[E:].T)                 # [T,B,2H]
    sp_f = s_prev.astype(f32)
    d_wh = jnp.concatenate(
        [bwd_einsum("tbd,tbz->dz", sp_f, d_xp_tb[..., : 2 * D]),
         bwd_einsum("tbd,tbz->dz", r_all * sp_f, d_xp_tb[..., 2 * D:])],
        axis=1)
    d_attw = bwd_einsum("tbd,tba->da", sp_f, sum_dpre_tb)
    # d_enc: the only use of enc is ctx_t = w_t @ enc
    d_enc = bwd_einsum("tbs,tbh->bsh", probs,
                       d_ctx_tb).astype(enc.dtype)
    # d_wx in two blocks (x = [y, ctx]); identical to the old einsum over
    # the concatenated x
    d_wx_y = bwd_einsum("tbi,tbo->io", y_tb.astype(f32), d_xp_tb)
    d_wx_c = bwd_einsum("tbi,tbo->io", ctxs.astype(f32), d_xp_tb)
    d_wx = jnp.concatenate([d_wx_y, d_wx_c], axis=0)
    d_y = bwd_mm(d_xp_tb, wx_f[:E].T).astype(y_emb.dtype)  # [T,B,E]
    d_y_emb = jnp.moveaxis(d_y, 0, 1)

    return (d_y_emb, d_s0.astype(s0.dtype), d_enc,
            d_encP.astype(enc_proj.dtype),
            None, None,
            d_attw.astype(att_w.dtype), d_v.astype(att_v.dtype),
            d_wx.astype(wx.dtype), d_b.astype(b.dtype),
            d_wh.astype(wh.dtype))


attention_gru_decoder.defvjp(_agd_fwd, _agd_bwd)
