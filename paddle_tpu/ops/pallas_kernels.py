"""Pallas TPU kernels for the hot ops.

The reference's performance tier is hand-written CUDA: fused LSTM cell with
intra-sequence parallelism (paddle/cuda/src/hl_cuda_lstm.cu:26-58, PTX
bar.sync), fused GRU (hl_gru_ops.cuh).  The TPU analog: the *whole* LSTM/GRU
time loop runs inside ONE Pallas kernel — the grid's sequential dimension is
time, recurrent weights stay resident in VMEM across all timesteps, and the
h/c state lives in VMEM scratch, so per-step HBM traffic is just the input
projection block in and the hidden block out.

Forward-only kernels wrapped in ``jax.custom_vjp``: the backward pass
recomputes via the pure-JAX scan implementation (rematerialization trades
FLOPs for memory, and keeps one numerics source of truth for gradients).

All kernels are shape-gated: ``lstm_layer``/``gru_layer`` in ops.rnn call
these automatically on TPU when dims are tile-aligned; otherwise the lax.scan
path runs.  CPU tests run both paths and compare (interpret mode).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pallas_available", "lstm_forward_pallas", "gru_forward_pallas",
           "attn_dec_fwd_pallas", "attn_dec_bwd_pallas",
           "topk_lse_readout_pallas", "topk_lse_logits_pallas", "TOPK_LANES"]


def _compiler_params(**kw):
    """TPU CompilerParams across jax versions: renamed from
    ``TPUCompilerParams`` to ``CompilerParams`` upstream — prefer the new
    name, fall back to the old one (same fields either way)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def pallas_available() -> bool:
    try:
        import jax.experimental.pallas  # noqa: F401

        return jax.default_backend() in ("tpu", "cpu", "axon")
    except Exception:  # pragma: no cover
        return False


def _interpret() -> bool:
    import jax

    return jax.default_backend() not in ("tpu", "axon")


# ---------------------------------------------------------------------------
# LSTM: one kernel over the whole sequence
# ---------------------------------------------------------------------------


def _lstm_kernel(xp_ref, m_ref, wh_ref, pi_ref, pf_ref, po_ref,
                 hseq_ref, hfin_ref, cfin_ref,
                 *rest, hidden: int, mxu_dtype):
    from jax.experimental import pallas as pl

    # rest carries the optional residual outputs before the two scratch
    # refs: (zseq, hprev, cprev, h_scr, c_scr) in training, (h_scr, c_scr)
    # on the residual-free inference variant
    save_residuals = len(rest) == 5
    if save_residuals:
        zseq_ref, hprev_ref, cprev_ref, h_scr, c_scr = rest
    else:
        h_scr, c_scr = rest

    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)
        c_scr[...] = jnp.zeros_like(c_scr)

    h = h_scr[...]
    c = c_scr[...]
    xp = xp_ref[0]                          # [B, 4H]
    # matmul operands follow the framework's compute-dtype policy (bf16 by
    # default) so this kernel computes the same function as the lax.scan
    # path (linear()/mxu_cast) that the custom_vjp backward differentiates
    z = xp + jnp.dot(h.astype(mxu_dtype), wh_ref[...].astype(mxu_dtype),
                     preferred_element_type=jnp.float32)
    H = hidden
    # peephole ("check") vectors ride resident [1,H] blocks; zeros = plain
    # cell (hl_lstm_ops.cuh: i,f see c_prev, o sees c_new)
    i = jax.nn.sigmoid(z[:, :H] + pi_ref[0] * c)
    f = jax.nn.sigmoid(z[:, H : 2 * H] + pf_ref[0] * c)
    g = jnp.tanh(z[:, 3 * H :])
    c_new = f * c + i * g
    o = jax.nn.sigmoid(z[:, 2 * H : 3 * H] + po_ref[0] * c_new)
    h_new = o * jnp.tanh(c_new)
    m = m_ref[0]                            # [B, 1]
    keep = m > 0
    if save_residuals:
        # backward residuals: pre-activations + held carries stream straight
        # out of the forward, so the backward pass needs NO replay scan
        zseq_ref[0] = z.astype(zseq_ref.dtype)
        hprev_ref[0] = h.astype(hprev_ref.dtype)
        cprev_ref[0] = c.astype(cprev_ref.dtype)
    h_new = jnp.where(keep, h_new, h)
    c_new = jnp.where(keep, c_new, c)
    h_scr[...] = h_new
    c_scr[...] = c_new
    # padded steps emit zeros (carry is held in scratch) — identical output
    # semantics to scan_rnn, so the recompute-backward differentiates the
    # same function the forward computes
    hseq_ref[0] = h_new * m

    @pl.when(t == T - 1)
    def _fin():
        hfin_ref[...] = h_new
        cfin_ref[...] = c_new


def _lstm_pallas_raw(xp_tb, mask_tb, w_h, pi, pf, po, *,
                     residuals: bool = True):
    """TIME-MAJOR: xp [T,B,4H], mask [T,B] — Mosaic requires the last two
    block dims tile-aligned or full, so time must lead; callers transpose
    once per layer.  ``residuals=False`` (inference / primal-only forward)
    skips the z/h_prev/c_prev outputs entirely — pallas_call is opaque to
    XLA, so unused outputs would otherwise be materialized to HBM (hundreds
    of MB at the gate ceiling), not DCE'd."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from paddle_tpu.ops.numerics import compute_dtype

    T, B, H4 = xp_tb.shape
    H = H4 // 4
    kernel = functools.partial(_lstm_kernel, hidden=H,
                               mxu_dtype=compute_dtype())
    step = lambda t: (t, 0, 0)
    out_specs = [
        pl.BlockSpec((1, B, H), step),
        pl.BlockSpec((B, H), lambda t: (0, 0)),
        pl.BlockSpec((B, H), lambda t: (0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((T, B, H), jnp.float32),
        jax.ShapeDtypeStruct((B, H), jnp.float32),
        jax.ShapeDtypeStruct((B, H), jnp.float32),
    ]
    if residuals:
        from paddle_tpu.ops.rnn_fused import residual_dtype

        rd = residual_dtype(H)
        out_specs += [
            pl.BlockSpec((1, B, H4), step),
            pl.BlockSpec((1, B, H), step),
            pl.BlockSpec((1, B, H), step),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((T, B, H4), rd),            # z residual
            jax.ShapeDtypeStruct((T, B, H), rd),             # h_prev
            jax.ShapeDtypeStruct((T, B, H), rd),             # c_prev
        ]
    return pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), step),
            pl.BlockSpec((1, B, 1), step),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((1, H), lambda t: (0, 0)),
            pl.BlockSpec((1, H), lambda t: (0, 0)),
            pl.BlockSpec((1, H), lambda t: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=_interpret(),
    )(xp_tb, mask_tb[..., None], w_h, pi.reshape(1, H), pf.reshape(1, H),
      po.reshape(1, H))


def _lstm_reference(xp, mask, w_h):
    """Pure-JAX twin (same math, same f32 compute dtype) used for the
    custom_vjp backward; differentiating through the entry casts yields
    gradients in the caller's original dtypes."""
    from paddle_tpu.ops.rnn import lstm_step, scan_rnn

    xp = xp.astype(jnp.float32)
    w_h = w_h.astype(jnp.float32)

    def step(carry, xp_t):
        h, c = carry
        h2, c2 = lstm_step(xp_t, h, c, w_h)
        return (h2, c2), h2

    B = xp.shape[0]
    H = w_h.shape[0]
    z = jnp.zeros((B, H), jnp.float32)
    (h_f, c_f), h_seq = scan_rnn(step, (z, z), xp, mask)
    return h_seq, h_f, c_f


@jax.custom_vjp
def lstm_forward_pallas(xp, mask, w_h):
    """xp: [B,T,4H] input projection (+bias), mask [B,T], w_h [H,4H].
    Returns (h_seq [B,T,H], h_final, c_final), always float32; h_seq is zero
    at padded timesteps (same semantics as the scan path). No peepholes
    (gated upstream).

    Direct kernel entry (tests exercise it in interpret mode; backward is
    autodiff-of-reference).  The PRODUCTION path is
    ops/rnn_fused.lstm_sequence_fused, which pairs the same raw kernel with
    the hand-written fast backward."""
    H = w_h.shape[0]
    zp = jnp.zeros((H,), jnp.float32)
    h_tb, h_f, c_f = _lstm_pallas_raw(
        jnp.moveaxis(xp.astype(jnp.float32), 1, 0),
        jnp.moveaxis(mask.astype(jnp.float32), 1, 0),
        w_h.astype(jnp.float32), zp, zp, zp, residuals=False)
    return jnp.moveaxis(h_tb, 0, 1), h_f, c_f


def _lstm_fwd(xp, mask, w_h):
    out = lstm_forward_pallas(xp, mask, w_h)
    return out, (xp, mask, w_h)


def _lstm_bwd(res, ct):
    xp, mask, w_h = res
    _, vjp = jax.vjp(lambda xp, w_h: _lstm_reference(xp, mask, w_h), xp, w_h)
    d_xp, d_wh = vjp(ct)
    return d_xp, None, d_wh


lstm_forward_pallas.defvjp(_lstm_fwd, _lstm_bwd)


# ---------------------------------------------------------------------------
# GRU: same structure
# ---------------------------------------------------------------------------


def _gru_kernel(xp_ref, m_ref, wh_ref, hseq_ref, hfin_ref, *rest,
                hidden: int, mxu_dtype, batch_split: int = 0):
    """``batch_split`` > 0 runs a BIDIRECTIONAL batch: rows [:split] use
    weight rows [:H] (forward direction) and rows [split:] use rows [H:]
    (backward direction, its inputs time-flipped by the caller) — both
    directions advance in ONE sequential time loop instead of two."""
    from jax.experimental import pallas as pl

    save_residuals = len(rest) == 3  # (zseq, hprev, h_scr) vs (h_scr,)
    if save_residuals:
        zseq_ref, hprev_ref, h_scr = rest
    else:
        (h_scr,) = rest

    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)

    h = h_scr[...]
    H = hidden
    xp = xp_ref[0]                                      # [B, 3H]
    w = wh_ref[...].astype(mxu_dtype)                   # [H or 2H, 3H]

    def rdot(v, lo, hi):
        vc = v.astype(mxu_dtype)
        if batch_split:
            return jnp.concatenate([
                jnp.dot(vc[:batch_split], w[:H, lo:hi],
                        preferred_element_type=jnp.float32),
                jnp.dot(vc[batch_split:], w[H:, lo:hi],
                        preferred_element_type=jnp.float32)], 0)
        return jnp.dot(vc, w[:, lo:hi], preferred_element_type=jnp.float32)

    zr = xp[:, : 2 * H] + rdot(h, 0, 2 * H)
    r = jax.nn.sigmoid(zr[:, :H])
    u = jax.nn.sigmoid(zr[:, H:])
    zc = xp[:, 2 * H :] + rdot(r * h, 2 * H, 3 * H)
    cand = jnp.tanh(zc)
    h_new = u * h + (1.0 - u) * cand
    m = m_ref[0]
    if save_residuals:
        # backward residuals (see _lstm_kernel)
        zseq_ref[0, :, : 2 * H] = zr.astype(zseq_ref.dtype)
        zseq_ref[0, :, 2 * H:] = zc.astype(zseq_ref.dtype)
        hprev_ref[0] = h.astype(hprev_ref.dtype)
    h_new = jnp.where(m > 0, h_new, h)
    h_scr[...] = h_new
    hseq_ref[0] = h_new * m

    @pl.when(t == T - 1)
    def _fin():
        hfin_ref[...] = h_new


def _gru_pallas_raw(xp_tb, mask_tb, w_h, *, residuals: bool = True,
                    batch_split: int = 0):
    """TIME-MAJOR (see _lstm_pallas_raw).  ``residuals=False``: inference
    variant without the z/h_prev outputs.  ``batch_split``: bidirectional
    batch with stacked [2H, 3H] weights (see _gru_kernel)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from paddle_tpu.ops.numerics import compute_dtype

    T, B, H3 = xp_tb.shape
    H = H3 // 3
    kernel = functools.partial(_gru_kernel, hidden=H,
                               mxu_dtype=compute_dtype(),
                               batch_split=batch_split)
    step = lambda t: (t, 0, 0)
    out_specs = [
        pl.BlockSpec((1, B, H), step),
        pl.BlockSpec((B, H), lambda t: (0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((T, B, H), jnp.float32),
        jax.ShapeDtypeStruct((B, H), jnp.float32),
    ]
    if residuals:
        from paddle_tpu.ops.rnn_fused import residual_dtype

        rd = residual_dtype(H)
        out_specs += [
            pl.BlockSpec((1, B, H3), step),
            pl.BlockSpec((1, B, H), step),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((T, B, H3), rd),            # z residual
            jax.ShapeDtypeStruct((T, B, H), rd),             # h_prev
        ]
    return pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H3), step),
            pl.BlockSpec((1, B, 1), step),
            pl.BlockSpec((w_h.shape[0], H3), lambda t: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32)],
        compiler_params=_compiler_params(
            # the bidirectional batch doubles the per-step working set past
            # Mosaic's 16 MB default scoped-VMEM limit
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_interpret(),
    )(xp_tb, mask_tb[..., None], w_h)


def _gru_reference(xp, mask, w_h):
    from paddle_tpu.ops.rnn import gru_step, scan_rnn

    xp = xp.astype(jnp.float32)
    w_h = w_h.astype(jnp.float32)

    def step(h, xp_t):
        h2 = gru_step(xp_t, h, w_h)
        return h2, h2

    B = xp.shape[0]
    H = w_h.shape[0]
    h_f, h_seq = scan_rnn(step, jnp.zeros((B, H), jnp.float32), xp, mask)
    return h_seq, h_f


@jax.custom_vjp
def gru_forward_pallas(xp, mask, w_h):
    """xp: [B,T,3H], mask [B,T], w_h [H,3H] -> (h_seq [B,T,H], h_final),
    always float32; h_seq is zero at padded timesteps.

    Direct kernel entry (tests/interpret mode); production uses
    ops/rnn_fused.gru_sequence_fused — see lstm_forward_pallas."""
    h_tb, h_f = _gru_pallas_raw(
        jnp.moveaxis(xp.astype(jnp.float32), 1, 0),
        jnp.moveaxis(mask.astype(jnp.float32), 1, 0),
        w_h.astype(jnp.float32), residuals=False)
    return jnp.moveaxis(h_tb, 0, 1), h_f


def _gru_fwd(xp, mask, w_h):
    out = gru_forward_pallas(xp, mask, w_h)
    return out, (xp, mask, w_h)


def _gru_bwd(res, ct):
    xp, mask, w_h = res
    _, vjp = jax.vjp(lambda xp, w_h: _gru_reference(xp, mask, w_h), xp, w_h)
    d_xp, d_wh = vjp(ct)
    return d_xp, None, d_wh


gru_forward_pallas.defvjp(_gru_fwd, _gru_bwd)


# ---------------------------------------------------------------------------
# Backward time-loop kernels: the reverse scans of rnn_fused as single
# Pallas programs.  Residuals (z, carries) stream in per step, the d_h/d_c
# cotangent carries live in VMEM scratch, the transposed recurrent weight
# stays resident, and the per-step d_z cotangent streams out — the
# hand-written reverse half of hl_cuda_lstm.cu, TPU-style.  The batched
# d_w_h einsum and d_xp remain outside (they are one-shot MXU ops).
# ---------------------------------------------------------------------------


def _lstm_bwd_kernel(dout_ref, m_ref, z_ref, cp_ref, wt_ref, pi_ref,
                     pf_ref, po_ref, dhfin_ref, dcfin_ref,
                     dz_ref, *rest, hidden: int):
    """One reverse step (grid runs t = T-1 .. 0 via the index maps).
    Mirrors rnn_fused._lstm_seq_bwd.rev_step numerics exactly (f32),
    including peephole feedthrough; streams c_new back out for the d_po
    reduction when peepholes are live (rest = (cn_ref, dh0, dc0, scratches)
    or (dh0, dc0, scratches))."""
    from jax.experimental import pallas as pl

    if len(rest) == 5:
        cn_ref, dh0_ref, dc0_ref, dh_scr, dc_scr = rest
    else:
        cn_ref = None
        dh0_ref, dc0_ref, dh_scr, dc_scr = rest

    t = pl.program_id(0)
    T = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)  # first grid step == last timestep: d_hfin/d_cfin seed
    def _init():
        dh_scr[...] = dhfin_ref[...]
        dc_scr[...] = dcfin_ref[...]

    d_h = dh_scr[...]
    d_c = dc_scr[...]
    z = z_ref[0].astype(jnp.float32)
    cp = cp_ref[0].astype(jnp.float32)
    pi = pi_ref[0]
    pf = pf_ref[0]
    po = po_ref[0]
    i = jax.nn.sigmoid(z[:, :H] + pi * cp)
    f = jax.nn.sigmoid(z[:, H: 2 * H] + pf * cp)
    g = jnp.tanh(z[:, 3 * H:])
    cn = f * cp + i * g
    o = jax.nn.sigmoid(z[:, 2 * H: 3 * H] + po * cn)
    tc = jnp.tanh(cn)
    m = m_ref[0]
    mcol = (m > 0).astype(jnp.float32)
    d_hnew = mcol * (dout_ref[0] + d_h)
    d_zo = d_hnew * tc * o * (1 - o)
    d_cnew = mcol * d_c + d_hnew * o * (1.0 - tc * tc) + d_zo * po
    d_zi = d_cnew * g * i * (1 - i)
    d_zf = d_cnew * cp * f * (1 - f)
    d_z = jnp.concatenate([
        d_zi, d_zf, d_zo, d_cnew * i * (1 - g * g)], -1)
    d_hp = jnp.dot(d_z, wt_ref[...], preferred_element_type=jnp.float32)
    dh_scr[...] = (1.0 - mcol) * d_h + d_hp
    dc_scr[...] = ((1.0 - mcol) * d_c + d_cnew * f
                   + d_zi * pi + d_zf * pf)
    dz_ref[0] = d_z
    if cn_ref is not None:
        cn_ref[0] = cn

    @pl.when(t == T - 1)  # last grid step == timestep 0
    def _fin():
        dh0_ref[...] = dh_scr[...]
        dc0_ref[...] = dc_scr[...]


def _lstm_bwd_pallas_raw(dout_tb, m_tb, z_tb, cp_tb, w_t, pi, pf, po,
                         d_hfin, d_cfin, *, want_cn: bool = True):
    """TIME-MAJOR: dout/m/z/cp [T,B,*] f32; w_t: [4H,H] (w_h transposed);
    pi/pf/po: [1,H] peephole rows; d_hfin/d_cfin: [B,H] cotangent seeds
    (loaded into the carry scratch at the last timestep — they propagate
    through masked tails exactly as the scan's initial carry does).
    Returns (d_z [T,B,4H], c_new [T,B,H] for the d_po reduction, d_h0,
    d_c0)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, B, H4 = z_tb.shape
    H = H4 // 4
    rev = lambda t: (T - 1 - t, 0, 0)
    kernel = functools.partial(_lstm_bwd_kernel, hidden=H)
    out_specs = [pl.BlockSpec((1, B, H4), rev)]
    out_shape = [jax.ShapeDtypeStruct((T, B, H4), jnp.float32)]
    if want_cn:  # c_new residual only feeds d_po — skip it for zero peeps
        out_specs.append(pl.BlockSpec((1, B, H), rev))
        out_shape.append(jax.ShapeDtypeStruct((T, B, H), jnp.float32))
    out_specs += [
        pl.BlockSpec((B, H), lambda t: (0, 0)),
        pl.BlockSpec((B, H), lambda t: (0, 0)),
    ]
    out_shape += [
        jax.ShapeDtypeStruct((B, H), jnp.float32),
        jax.ShapeDtypeStruct((B, H), jnp.float32),
    ]
    outs = pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((1, B, 1), rev),
            pl.BlockSpec((1, B, H4), rev),
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((H4, H), lambda t: (0, 0)),
            pl.BlockSpec((1, H), lambda t: (0, 0)),
            pl.BlockSpec((1, H), lambda t: (0, 0)),
            pl.BlockSpec((1, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=_interpret(),
    )(dout_tb, m_tb[..., None], z_tb, cp_tb, w_t, pi, pf, po,
      d_hfin, d_cfin)
    if want_cn:
        d_z, cn, d_h0, d_c0 = outs
    else:
        d_z, d_h0, d_c0 = outs
        cn = None
    return d_z, cn, d_h0, d_c0


def _gru_bwd_kernel(dout_ref, m_ref, z_ref, hp_ref, wt_ref, dhfin_ref,
                    dz_ref, dh0_ref, dh_scr, *, hidden: int,
                    batch_split: int = 0):
    """Reverse GRU step — mirrors rnn_fused._gru_seq_bwd.rev_step (f32).
    ``batch_split``: bidirectional batch; w_t carries both directions'
    transposed weights stacked on the column axis [3H, 2H]."""
    from jax.experimental import pallas as pl

    t = pl.program_id(0)
    T = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)  # d_hfin seeds the carry at the last timestep
    def _init():
        dh_scr[...] = dhfin_ref[...]

    d_c = dh_scr[...]
    z = z_ref[0].astype(jnp.float32)
    hp = hp_ref[0].astype(jnp.float32)
    r = jax.nn.sigmoid(z[:, :H])
    u = jax.nn.sigmoid(z[:, H: 2 * H])
    cand = jnp.tanh(z[:, 2 * H:])
    m = m_ref[0]
    mcol = (m > 0).astype(jnp.float32)
    d_hnew = mcol * (dout_ref[0] + d_c)
    d_u = d_hnew * (hp - cand)
    d_zc = d_hnew * (1.0 - u) * (1.0 - cand * cand)
    w_t = wt_ref[...]

    def rtdot(v, lo, hi):
        if batch_split:
            return jnp.concatenate([
                jnp.dot(v[:batch_split], w_t[lo:hi, :H],
                        preferred_element_type=jnp.float32),
                jnp.dot(v[batch_split:], w_t[lo:hi, H:],
                        preferred_element_type=jnp.float32)], 0)
        return jnp.dot(v, w_t[lo:hi, :], preferred_element_type=jnp.float32)

    d_rh = rtdot(d_zc, 2 * H, 3 * H)
    d_r = d_rh * hp
    d_zr = jnp.concatenate([d_r * r * (1 - r), d_u * u * (1 - u)], -1)
    d_hp = d_hnew * u + d_rh * r + rtdot(d_zr, 0, 2 * H)
    dh_scr[...] = (1.0 - mcol) * d_c + d_hp
    dz_ref[0, :, : 2 * H] = d_zr
    dz_ref[0, :, 2 * H:] = d_zc

    @pl.when(t == T - 1)
    def _fin():
        dh0_ref[...] = dh_scr[...]


def _gru_bwd_pallas_raw(dout_tb, m_tb, z_tb, hp_tb, w_t, d_hfin, *,
                        batch_split: int = 0):
    """TIME-MAJOR twin of _lstm_bwd_pallas_raw for the GRU.
    ``batch_split``: bidirectional batch, w_t stacked [3H, 2H]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, B, H3 = z_tb.shape
    H = H3 // 3
    rev = lambda t: (T - 1 - t, 0, 0)
    kernel = functools.partial(_gru_bwd_kernel, hidden=H,
                               batch_split=batch_split)
    return pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((1, B, 1), rev),
            pl.BlockSpec((1, B, H3), rev),
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((H3, w_t.shape[1]), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H3), rev),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H3), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32)],
        compiler_params=_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_interpret(),
    )(dout_tb, m_tb[..., None], z_tb, hp_tb, w_t, d_hfin)


# ---------------------------------------------------------------------------
# Row logsumexp with ONE HBM pass: each grid step loads a full-vocab
# [row_tile, V] block into VMEM (f32 temporaries included — size the tile
# accordingly) and reduces it there, where XLA's fused max + exp-sum
# otherwise reads the [N, V] logits buffer twice (~737 MB of bf16 per pass
# at WMT14 bench shapes).  NOTE: A/B-measured SLOWER than the XLA two-pass
# on v5e (see losses._USE_PALLAS_LSE_READOUT) — kept as a recorded losing
# A/B with its interpret-mode equivalence test.  Rows must divide into the
# tile (logsumexp_rows_pallas raises otherwise) — anyone re-running the
# A/B at new shapes must re-check that gate.
# ---------------------------------------------------------------------------


def _lse_kernel(x_ref, lse_ref):
    x = x_ref[...].astype(jnp.float32)           # [TN, V] — full row in VMEM
    m = jnp.max(x, axis=-1, keepdims=True)
    lse_ref[...] = m + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1,
                                       keepdims=True))


def logsumexp_rows_pallas(x, *, row_tile: int = 64):
    """x [N, V] -> lse [N] f32 with ONE HBM pass over x: each grid step
    loads a [row_tile, V] block (the full vocab row — V need not be
    lane-aligned when the block spans the whole axis) and reduces it in
    VMEM.  Caller gates: N % row_tile == 0 and row_tile*V*itemsize within
    VMEM incl. the f32 exp temporaries (~12 MB at bf16 row_tile=64, V=30k)."""
    from jax.experimental import pallas as pl

    N, V = x.shape
    row_tile = min(row_tile, N)
    if N % row_tile:
        raise ValueError(f"N={N} not divisible by row_tile={row_tile}")
    out = pl.pallas_call(
        _lse_kernel,
        grid=(N // row_tile,),
        in_specs=[pl.BlockSpec((row_tile, V), lambda n: (n, 0))],
        out_specs=pl.BlockSpec((row_tile, 1), lambda n: (n, 0)),
        out_shape=jax.ShapeDtypeStruct((N, 1), jnp.float32),
        interpret=_interpret(),
    )(x)
    return out[:, 0]


# ---------------------------------------------------------------------------
# Attention GRU decoder time-loop kernels — the flagship's structural
# bottleneck (VERDICT r4 item 1).  The XLA scan re-reads enc [B,S,2H] and
# enc_proj [B,S,A] from HBM on EVERY decoder step, and the backward
# additionally carries the d_enc_proj [B,S,A] f32 cotangent accumulator
# through HBM each reverse step (~88 MB/step at WMT14 bench shapes,
# ~2.8 GB per backward).  Here the grid is (batch-blocks, T) with time
# innermost: enc/enc_proj (and in the backward, the d_enc_proj accumulator
# block) stay VMEM-RESIDENT across all T steps of a batch block — per-step
# HBM traffic drops to the small [Bb,*] streams.  Mosaic's default 16 MB
# scoped-VMEM cap is raised via CompilerParams (v5e has 128 MB physical
# VMEM); block sizes are gated to fit.
#
# Numerics mirror ops/attention_decoder.py exactly: forward follows
# _fwd_step (compute-dtype MXU operands, f32 accumulation), backward
# follows _agd_bwd.rev_step (all-f32 with compute-dtype enc/enc_proj
# reads), so the interpret-mode equivalence tests compare bitwise-same
# ops on CPU (f32 policy).
# ---------------------------------------------------------------------------


def _attn_dec_fwd_kernel(xp_y_ref, m_ref, s0_ref, encP_ref, enc_ref,
                         smask_ref, attw_ref, attv_ref, wxc_ref, wh_ref,
                         out_ref, probs_ref, ctx_ref, sprev_ref,
                         s_scr, *, mxu_dtype):
    from jax.experimental import pallas as pl

    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        s_scr[...] = s0_ref[...]

    s = s_scr[...]                                   # [Bb, D] f32
    f32 = jnp.float32
    # --- additive_attention_scores (mirrors _fwd_step) ---
    q = jnp.dot(s.astype(mxu_dtype), attw_ref[...],
                preferred_element_type=f32)          # [Bb, A]
    encP = encP_ref[...]                             # [Bb, S, A] cd
    pre = jnp.tanh(encP + q[:, None, :].astype(encP.dtype))
    # score reduction on the VPU: Mosaic supports neither the
    # [Bb,S,A]->[Bb*S,A] matvec route's output fold nor batched matvecs
    scores = jnp.sum((pre * attv_ref[...][None]).astype(f32), axis=-1)
    # --- attend ---
    smask = smask_ref[...]                           # [Bb, S] f32
    neg = jnp.finfo(f32).min
    z = jnp.where(smask > 0, scores, neg)
    w0 = jax.nn.softmax(z, axis=-1)
    w1 = w0 * smask
    n = jnp.maximum(jnp.sum(w1, axis=-1, keepdims=True), 1e-9)
    w = w1 / n                                       # [Bb, S] f32
    # batched matvec ctx[b] = w[b] @ enc[b] as a VPU broadcast-multiply +
    # S-reduction: Mosaic lowers neither the [Bb,S]->[Bb,1,S] shape cast
    # nor a dot_general with no lhs non-contracting dims
    # (minor-dim insert must happen on the f32 array — Mosaic only supports
    # non-no-op minor-dim insertion for 32-bit types)
    ctx = jnp.sum((w[:, :, None].astype(mxu_dtype)
                   * enc_ref[...]).astype(f32), axis=1)     # [Bb, 2H]
    # --- input projection + gru_step ---
    D = s.shape[-1]
    xp = xp_y_ref[0] + jnp.dot(ctx.astype(mxu_dtype), wxc_ref[...],
                               preferred_element_type=f32)      # [Bb, 3D]
    zr = xp[:, : 2 * D] + jnp.dot(s.astype(mxu_dtype), wh_ref[:, : 2 * D],
                                  preferred_element_type=f32)
    r = jax.nn.sigmoid(zr[:, :D])
    u = jax.nn.sigmoid(zr[:, D:])
    cand = jnp.tanh(xp[:, 2 * D:]
                    + jnp.dot((r * s).astype(mxu_dtype), wh_ref[:, 2 * D:],
                              preferred_element_type=f32))
    s_new = u * s + (1.0 - u) * cand
    m = m_ref[0]                                     # [Bb, 1]
    s_out = jnp.where(m > 0, s_new, s)
    s_scr[...] = s_out
    out_ref[0] = s_out * m
    probs_ref[0] = w
    ctx_ref[0] = ctx.astype(ctx_ref.dtype)
    sprev_ref[0] = s


def attn_dec_fwd_pallas(xp_y_tb, m_tb, s0, enc, enc_proj, src_mask,
                        att_w, att_v, wx_c, wh, *, block_b):
    """TIME-MAJOR forward: xp_y [T,B,3D] f32 (teacher-forced half of the
    input projection, bias included), m [T,B] f32, s0 [B,D] f32; enc/
    enc_proj/att_w/att_v/wx_c/wh pre-cast to the compute dtype by the
    caller.  Returns (states [T,B,D] f32, probs [T,B,S] f32, ctx [T,B,2H]
    enc.dtype, s_prev [T,B,D] f32) — identical layout/semantics to
    attention_decoder._decoder_fwd_scan's stacked scan outputs."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from paddle_tpu.ops.numerics import compute_dtype

    T, B, D3 = xp_y_tb.shape
    D = D3 // 3
    S, H2 = enc.shape[1], enc.shape[2]
    A = enc_proj.shape[2]
    nB = B // block_b
    Bb = block_b
    kernel = functools.partial(_attn_dec_fwd_kernel,
                               mxu_dtype=compute_dtype())
    step = lambda b, t: (t, b, 0)
    blk = lambda b, t: (b, 0, 0)
    blk2 = lambda b, t: (b, 0)
    const = lambda b, t: (0, 0)
    return pl.pallas_call(
        kernel,
        grid=(nB, T),
        in_specs=[
            pl.BlockSpec((1, Bb, D3), step),         # xp_y
            pl.BlockSpec((1, Bb, 1), step),          # mask col
            pl.BlockSpec((Bb, D), blk2),             # s0
            pl.BlockSpec((Bb, S, A), blk),           # enc_proj (resident)
            pl.BlockSpec((Bb, S, H2), blk),          # enc (resident)
            pl.BlockSpec((Bb, S), blk2),             # src_mask
            pl.BlockSpec((D, A), const),             # att_w
            pl.BlockSpec((1, A), const),             # att_v row
            pl.BlockSpec((H2, D3), const),           # wx_c
            pl.BlockSpec((D, D3), const),            # wh
        ],
        out_specs=[
            pl.BlockSpec((1, Bb, D), step),
            pl.BlockSpec((1, Bb, S), step),
            pl.BlockSpec((1, Bb, H2), step),
            pl.BlockSpec((1, Bb, D), step),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, D), jnp.float32),   # states (masked)
            jax.ShapeDtypeStruct((T, B, S), jnp.float32),   # attention probs
            jax.ShapeDtypeStruct((T, B, H2), enc.dtype),    # ctx residual
            jax.ShapeDtypeStruct((T, B, D), jnp.float32),   # s_prev residual
        ],
        scratch_shapes=[pltpu.VMEM((Bb, D), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(xp_y_tb, m_tb[..., None], s0, enc_proj, enc, src_mask,
      att_w, att_v.reshape(1, A), wx_c, wh)


def _attn_dec_bwd_kernel(dout_ref, m_ref, sp_ref, r_ref, u_ref, cand_ref,
                         q_ref, encP_ref, enc_ref, smask_ref,
                         attwT_ref, attv_ref, attvf_ref,
                         whTzr_ref, whTc_ref, wxcT_ref,
                         dxp_ref, sumdpre_ref, dencP_ref, dv_ref, ds0_ref,
                         ds_scr, *, mxu_dtype):
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    T = pl.num_programs(1)
    f32 = jnp.float32

    @pl.when(t == 0)  # first grid step == LAST timestep: zero cotangent seed
    def _init():
        ds_scr[...] = jnp.zeros_like(ds_scr)
        dencP_ref[...] = jnp.zeros_like(dencP_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    d_s = ds_scr[...]                                # [Bb, D]
    m = m_ref[0]                                     # [Bb, 1]
    mcol = (m > 0).astype(f32)
    d_snew = mcol * (dout_ref[0] + d_s)
    sp = sp_ref[0]                                   # [Bb, D] f32
    r = r_ref[0]
    u = u_ref[0]
    cand = cand_ref[0]

    # ---- GRU backward (gates precomputed outside, streamed in) ----
    d_u = d_snew * (sp - cand)
    d_cand = d_snew * (1.0 - u)
    d_h = d_snew * u
    d_zc = d_cand * (1.0 - cand * cand)
    d_rh = jnp.dot(d_zc, whTc_ref[...], preferred_element_type=f32)
    d_r = d_rh * sp
    d_h = d_h + d_rh * r
    d_zr = jnp.concatenate([d_r * r * (1 - r), d_u * u * (1 - u)], -1)
    d_h = d_h + jnp.dot(d_zr, whTzr_ref[...], preferred_element_type=f32)
    d_xp = jnp.concatenate([d_zr, d_zc], -1)         # [Bb, 3D]
    d_ctx = jnp.dot(d_xp, wxcT_ref[...], preferred_element_type=f32)

    # ---- attention backward (mirrors _agd_bwd.rev_step) ----
    enc = enc_ref[...]                               # [Bb, S, 2H] cd
    # batched matvec d_w[b,s] = d_ctx[b] . enc[b,s] on the VPU (see the
    # forward kernel's ctx note)
    d_w = jnp.sum((d_ctx[:, None, :].astype(enc.dtype) * enc).astype(f32),
                  axis=-1)                           # [Bb, S]
    encP = encP_ref[...]
    q = q_ref[0]                                     # [Bb, A] f32
    pre = jnp.tanh(encP + q[:, None, :].astype(encP.dtype))
    scores = jnp.sum((pre * attv_ref[...][None]).astype(f32), axis=-1)
    smask = smask_ref[...]
    maskb = smask > 0
    neg = jnp.finfo(f32).min
    z = jnp.where(maskb, scores, neg)
    w0 = jax.nn.softmax(z, axis=-1)
    w1 = w0 * smask
    n = jnp.maximum(jnp.sum(w1, axis=-1, keepdims=True), 1e-9)
    d_w1 = d_w / n
    d_n = -jnp.sum(d_w * w1, axis=-1, keepdims=True) / (n * n)
    d_w1 = d_w1 + d_n * (jnp.sum(w1, -1, keepdims=True) > 1e-9).astype(f32)
    d_w0 = d_w1 * smask
    d_z = w0 * (d_w0 - jnp.sum(w0 * d_w0, axis=-1, keepdims=True))
    d_scores = jnp.where(maskb, d_z, 0.0)
    pre_f = pre.astype(f32)
    d_pre = (1.0 - pre_f * pre_f) * (d_scores[..., None] * attvf_ref[0])
    dencP_ref[...] += d_pre                          # VMEM-resident accum
    sum_dpre = jnp.sum(d_pre, axis=1)                # [Bb, A]
    d_h = d_h + jnp.dot(sum_dpre, attwT_ref[...], preferred_element_type=f32)
    # d_v block is [1, 8, A] (8 sublane rows purely for Mosaic tiling; only
    # row 0 carries data — the wrapper sums row 0 over blocks).  VPU
    # broadcast-reduce: Mosaic can't fold [Bb,S] into lanes for a matvec.
    dv_ref[0, 0:1, :] += jnp.sum(d_scores[:, :, None] * pre_f,
                                 axis=(0, 1))[None, :]

    ds_scr[...] = (1.0 - mcol) * d_s + d_h
    dxp_ref[0] = d_xp
    sumdpre_ref[0] = sum_dpre

    @pl.when(t == T - 1)  # last grid step == timestep 0
    def _fin():
        ds0_ref[...] = ds_scr[...]


def attn_dec_bwd_pallas(dout_tb, m_tb, sp_tb, r_tb, u_tb, cand_tb, q_tb,
                        enc, enc_proj, src_mask,
                        att_w_f, att_v_cd, att_v_f, wh_f, wx_c_f, *,
                        block_b):
    """TIME-MAJOR reverse pass.  dout/sp/r/u/cand [T,B,D] f32, q [T,B,A]
    f32, m [T,B] f32; enc/enc_proj compute dtype; *_f weights f32.
    Returns (d_xp [T,B,3D] f32, sum_dpre [T,B,A] f32, d_encP [B,S,A] f32,
    d_v [A] f32, d_s0 [B,D] f32) — the exact quantities _agd_bwd's reverse
    scan produces; every weight gradient is reconstructed outside from
    these (one batched MXU contraction each)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from paddle_tpu.ops.numerics import compute_dtype

    T, B, D = dout_tb.shape
    S, H2 = enc.shape[1], enc.shape[2]
    A = enc_proj.shape[2]
    nB = B // block_b
    Bb = block_b
    kernel = functools.partial(_attn_dec_bwd_kernel,
                               mxu_dtype=compute_dtype())
    rev = lambda b, t: (T - 1 - t, b, 0)
    blk = lambda b, t: (b, 0, 0)
    blk2 = lambda b, t: (b, 0)
    const = lambda b, t: (0, 0)
    outs = pl.pallas_call(
        kernel,
        grid=(nB, T),
        in_specs=[
            pl.BlockSpec((1, Bb, D), rev),           # d_out
            pl.BlockSpec((1, Bb, 1), rev),           # mask col
            pl.BlockSpec((1, Bb, D), rev),           # s_prev
            pl.BlockSpec((1, Bb, D), rev),           # r
            pl.BlockSpec((1, Bb, D), rev),           # u
            pl.BlockSpec((1, Bb, D), rev),           # cand
            pl.BlockSpec((1, Bb, A), rev),           # q
            pl.BlockSpec((Bb, S, A), blk),           # enc_proj (resident)
            pl.BlockSpec((Bb, S, H2), blk),          # enc (resident)
            pl.BlockSpec((Bb, S), blk2),             # src_mask
            pl.BlockSpec((A, D), const),             # att_w^T f32
            pl.BlockSpec((1, A), const),             # att_v cd row
            pl.BlockSpec((1, A), const),             # att_v f32 row
            pl.BlockSpec((2 * D, D), const),         # wh[:, :2D]^T f32
            pl.BlockSpec((D, D), const),             # wh[:, 2D:]^T f32
            pl.BlockSpec((3 * D, H2), const),        # wx_c^T f32
        ],
        out_specs=[
            pl.BlockSpec((1, Bb, 3 * D), rev),
            pl.BlockSpec((1, Bb, A), rev),
            pl.BlockSpec((Bb, S, A), blk),           # d_encP (resident accum)
            pl.BlockSpec((1, 8, A), blk),            # d_v per block (row 0)
            pl.BlockSpec((Bb, D), blk2),             # d_s0
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, 3 * D), jnp.float32),
            jax.ShapeDtypeStruct((T, B, A), jnp.float32),
            jax.ShapeDtypeStruct((B, S, A), jnp.float32),
            jax.ShapeDtypeStruct((nB, 8, A), jnp.float32),
            jax.ShapeDtypeStruct((B, D), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((Bb, D), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(dout_tb, m_tb[..., None], sp_tb, r_tb, u_tb, cand_tb, q_tb,
      enc_proj, enc, src_mask,
      jnp.transpose(att_w_f), att_v_cd.reshape(1, A),
      att_v_f.reshape(1, A),
      jnp.transpose(wh_f[:, : 2 * D]), jnp.transpose(wh_f[:, 2 * D:]),
      jnp.transpose(wx_c_f))
    d_xp_tb, sum_dpre_tb, d_encP, d_v_blocks, d_s0 = outs
    return (d_xp_tb, sum_dpre_tb, d_encP,
            jnp.sum(d_v_blocks[:, 0, :], axis=0), d_s0)


# ---------------------------------------------------------------------------
# Fused vocab-readout + softmax-CE kernels — the flagship's other
# bandwidth tier.  The XLA path materializes the [B*T, V] logits (bf16)
# and, in the backward, the same-shaped d_logits, then re-reads each for
# the softmax statistics / the two weight contractions: ~2.2 GB of HBM
# traffic per step at WMT14 bench shapes on top of the matmul FLOPs.
# Here the vocabulary is tiled:
#
# - forward, grid (row-blocks, vocab-tiles) with vocab innermost: each
#   [Rb, Vt] logits tile is computed on the MXU and consumed IN VMEM by an
#   online max/sum-exp update (flash-attention-style) + the label-logit
#   gather; the tile is also streamed out in bf16 as the backward residual
#   (one write instead of XLA's write + two stat reads).
# - backward, grid (vocab-tiles,) with the full row dimension resident:
#   each logits tile is read once, d_l = (softmax - onehot)*scale is formed
#   in VMEM and immediately contracted into BOTH d_states (resident f32
#   accumulator) and that tile's d_w column block — d_logits never exists
#   in HBM.
#
# The vocabulary is padded to a lane multiple by the wrapper with bias
# -1e30 (exp underflows to 0, so the statistics and gradients are exact).
# ---------------------------------------------------------------------------


def _ce_fwd_kernel(s_ref, w_ref, b_ref, lab_ref,
                   ptok_ref, lse_ref, ltile_ref,
                   m_scr, s_scr, tok_scr, *, v_tile: int):
    from jax.experimental import pallas as pl

    f32 = jnp.float32
    v = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(v == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        s_scr[...] = jnp.zeros_like(s_scr)
        tok_scr[...] = jnp.zeros_like(tok_scr)

    l = jnp.dot(s_ref[...], w_ref[...],
                preferred_element_type=f32) + b_ref[...]      # [Rb, Vt] f32
    ltile_ref[...] = l.astype(ltile_ref.dtype)
    m_old = m_scr[...]
    m_new = jnp.maximum(m_old, jnp.max(l, axis=-1, keepdims=True))
    s_scr[...] = (s_scr[...] * jnp.exp(m_old - m_new)
                  + jnp.sum(jnp.exp(l - m_new), axis=-1, keepdims=True))
    m_scr[...] = m_new
    col = jax.lax.broadcasted_iota(jnp.int32, l.shape, 1) + v * v_tile
    hit = col == lab_ref[...]
    tok_scr[...] += jnp.sum(jnp.where(hit, l, 0.0), axis=-1, keepdims=True)

    @pl.when(v == nv - 1)
    def _fin():
        lse = m_scr[...] + jnp.log(s_scr[...])
        lse_ref[...] = lse
        ptok_ref[...] = lse - tok_scr[...]


def ce_readout_fwd_pallas(states_c, w_c, b_f, labels, *,
                          row_block: int, v_tile: int):
    """states_c [N, D] compute dtype, w_c [D, V'] compute dtype, b_f [1, V']
    f32 (padded tail at -1e30), labels [N, 1] i32 -> (per_tok [N,1] f32,
    lse [N,1] f32, logits [N, V'] compute dtype — the backward residual)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, D = states_c.shape
    Vp = w_c.shape[1]
    nR, nV = N // row_block, Vp // v_tile
    Rb, Vt = row_block, v_tile
    kernel = functools.partial(_ce_fwd_kernel, v_tile=Vt)
    return pl.pallas_call(
        kernel,
        grid=(nR, nV),
        in_specs=[
            pl.BlockSpec((Rb, D), lambda r, v: (r, 0)),    # states (resident)
            pl.BlockSpec((D, Vt), lambda r, v: (0, v)),    # w tile
            pl.BlockSpec((1, Vt), lambda r, v: (0, v)),    # bias tile
            pl.BlockSpec((Rb, 1), lambda r, v: (r, 0)),    # labels
        ],
        out_specs=[
            pl.BlockSpec((Rb, 1), lambda r, v: (r, 0)),
            pl.BlockSpec((Rb, 1), lambda r, v: (r, 0)),
            pl.BlockSpec((Rb, Vt), lambda r, v: (r, v)),   # logits residual
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, Vp), states_c.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((Rb, 1), jnp.float32),
            pltpu.VMEM((Rb, 1), jnp.float32),
            pltpu.VMEM((Rb, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(states_c, w_c, b_f, labels)


def _ce_bwd_kernel(l_ref, s_ref, w_ref, lab_ref, lse_ref, scale_ref,
                   ds_ref, dw_ref, db_ref, *, v_tile: int, mxu_dtype):
    from jax.experimental import pallas as pl

    f32 = jnp.float32
    v = pl.program_id(0)

    @pl.when(v == 0)
    def _init():
        ds_ref[...] = jnp.zeros_like(ds_ref)

    l = l_ref[...].astype(f32)                            # [N, Vt]
    p = jnp.exp(l - lse_ref[...])
    col = jax.lax.broadcasted_iota(jnp.int32, l.shape, 1) + v * v_tile
    hit = col == lab_ref[...]
    d_l = (p - jnp.where(hit, 1.0, 0.0)) * scale_ref[...]
    db_ref[...] = jnp.sum(d_l, axis=0, keepdims=True)
    d_lc = d_l.astype(mxu_dtype)
    # d_states += d_l @ w_tile^T  (accumulates across vocab tiles in VMEM)
    ds_ref[...] += jax.lax.dot_general(
        d_lc, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=f32)
    # d_w tile = states^T @ d_l — contract the row dim
    dw_ref[...] = jax.lax.dot_general(
        s_ref[...], d_lc, (((0,), (0,)), ((), ())),
        preferred_element_type=f32)


def ce_readout_bwd_pallas(logits_c, states_c, w_c, labels, lse, scale, *,
                          v_tile: int):
    """One pass over the saved bf16 logits: d_l is formed per [N, Vt] tile
    in VMEM and contracted immediately.  Returns (d_states [N, D] f32,
    d_w [D, V'] f32, d_b [1, V'] f32)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from paddle_tpu.ops.numerics import compute_dtype

    N, Vp = logits_c.shape
    D = states_c.shape[1]
    nV = Vp // v_tile
    Vt = v_tile
    kernel = functools.partial(_ce_bwd_kernel, v_tile=Vt,
                               mxu_dtype=compute_dtype())
    return pl.pallas_call(
        kernel,
        grid=(nV,),
        in_specs=[
            pl.BlockSpec((N, Vt), lambda v: (0, v)),       # logits tile
            pl.BlockSpec((N, D), lambda v: (0, 0)),        # states (resident)
            pl.BlockSpec((D, Vt), lambda v: (0, v)),       # w tile
            pl.BlockSpec((N, 1), lambda v: (0, 0)),        # labels
            pl.BlockSpec((N, 1), lambda v: (0, 0)),        # lse
            pl.BlockSpec((N, 1), lambda v: (0, 0)),        # scale
        ],
        out_specs=[
            pl.BlockSpec((N, D), lambda v: (0, 0)),        # d_states resident
            pl.BlockSpec((D, Vt), lambda v: (0, v)),
            pl.BlockSpec((1, Vt), lambda v: (0, v)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, D), jnp.float32),
            jax.ShapeDtypeStruct((D, Vp), jnp.float32),
            jax.ShapeDtypeStruct((1, Vp), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary",),
            # the resident d_states accumulator + states + per-tile
            # temporaries measure ~102 MB at WMT14 bench shapes
            vmem_limit_bytes=112 * 1024 * 1024),
        interpret=_interpret(),
    )(logits_c, states_c, w_c, labels, lse, scale)


# ---------------------------------------------------------------------------
# Fused vocab-tiled top-k + logsumexp readout — the decode engine's kernel
# (ops/decode.py).  The unfused decode step materializes the full [B*K, V]
# logits in HBM, log-softmaxes them in f32 (a second same-shaped buffer),
# and top-k's over K*V — at the WMT14 gen shape that is ~46 MB of HBM
# round-trips per emitted token for statistics that fit in a few lanes.
# Here the vocabulary is tiled exactly like the CE readout above: each
# [Rb, Vt] logits tile is computed on the MXU (or streamed in, for the
# pre-materialized-logits variant) and consumed IN VMEM by
#
#   - an online max/sum-exp logsumexp update (flash-attention-style), and
#   - a running top-k merge: k masked-argmax passes over (tile ∪ running),
#     tie-broken toward the LOWEST vocab index so the selection is
#     bit-identical to ``lax.top_k`` over the full row (stable sort).
#
# Neither the logits nor any f32 log-softmax buffer ever exists in HBM;
# per row the kernel writes k values + k indices + one logsumexp.  The
# top-k scratch rides lane-padded [Rb, TOPK_LANES] blocks (only the first
# k lanes carry data) — Mosaic-friendly full-lane vectors instead of
# ragged k-wide tiles.  k is a static unroll; the decode gate bounds it.
# ---------------------------------------------------------------------------

#: lane padding of the top-k scratch/output blocks (first k lanes are real)
TOPK_LANES = 128

#: index sentinel for empty top-k slots (greater than any real vocab id)
_IDX_SENTINEL = 2 ** 30

#: bias/padding value for vocab columns past V: exp underflows to exactly
#: zero, so the logsumexp is exact; the top-k merge additionally masks pad
#: columns to -inf so they can never be SELECTED either (a user row may
#: carry -inf logits — constrained decoding — which would otherwise lose
#: to a -1e30 pad and leak out-of-vocab indices)
_PAD_NEG = -1e30


def _topk_lse_update(l, base_col, vocab, k, m_scr, s_scr, tv_scr, ti_scr):
    """Fold one [Rb, Vt] f32 logits tile (global column offset ``base_col``,
    real vocabulary size ``vocab``) into the running logsumexp (m/s) and
    top-k (tv/ti) scratches."""
    f32 = jnp.float32
    # --- online logsumexp ---
    # the lse path runs on FINITE-clamped values: a tile that is entirely
    # -inf for a row (ban-prefix constrained decoding) would otherwise
    # poison the running stats with exp(-inf - -inf) = nan.  Clamped
    # entries contribute exp(finfo.min - m) == 0 exactly once any finite
    # logit has been seen, so the statistics stay exact; an all--inf row
    # yields ~finfo.min instead of the reference's nan (documented edge).
    lo = jnp.finfo(f32).min
    l_lse = jnp.maximum(l, lo)
    m_old = m_scr[...]
    m_new = jnp.maximum(m_old, jnp.max(l_lse, axis=-1, keepdims=True))
    s_scr[...] = (s_scr[...] * jnp.exp(m_old - m_new)
                  + jnp.sum(jnp.exp(l_lse - m_new), axis=-1, keepdims=True))
    m_scr[...] = m_new
    # --- running top-k merge ---
    col = jax.lax.broadcasted_iota(jnp.int32, l.shape, 1) + base_col
    # pad columns drop to -inf for SELECTION (not for the lse, whose exact
    # zero contribution needs the finite -1e30): a real -inf logit then
    # still beats them on the index tie-break, so indices stay < vocab and
    # all--inf tails resolve to the lowest ids exactly like lax.top_k
    tile_v = jnp.where(col < vocab, l, -jnp.inf)
    tile_i = jnp.where(col < vocab, col, _IDX_SENTINEL)
    run_v, run_i = tv_scr[...], ti_scr[...]
    lane = jax.lax.broadcasted_iota(jnp.int32, run_v.shape, 1)
    new_v = jnp.full_like(run_v, -jnp.inf)
    new_i = jnp.full_like(run_i, _IDX_SENTINEL)
    for j in range(k):
        # the arg-min over matching entries EXCLUDES sentinel-indexed slots
        # (removed winners, empty run slots, pad columns after masking), so
        # a legitimate -inf logit is still selectable by lowest index
        t_m = jnp.max(tile_v, axis=-1, keepdims=True)
        t_i = jnp.min(jnp.where(tile_v == t_m, tile_i, _IDX_SENTINEL),
                      axis=-1, keepdims=True)
        r_m = jnp.max(run_v, axis=-1, keepdims=True)
        r_i = jnp.min(jnp.where(run_v == r_m, run_i, _IDX_SENTINEL),
                      axis=-1, keepdims=True)
        # lax.top_k tie order: equal values resolve to the lower vocab
        # index.  Running entries come from earlier tiles (smaller ids),
        # so on a value tie the tile wins only with a smaller index.
        take_tile = (t_m > r_m) | ((t_m == r_m) & (t_i < r_i))
        c_v = jnp.where(take_tile, t_m, r_m).astype(f32)
        c_i = jnp.where(take_tile, t_i, r_i)
        new_v = jnp.where(lane == j, c_v, new_v)
        new_i = jnp.where(lane == j, c_i, new_i)
        # remove the winner from its source BY INDEX (ids are unique across
        # both): value alone is ambiguous once real -inf logits exist
        hit_t, hit_r = tile_i == c_i, run_i == c_i
        tile_v = jnp.where(hit_t, -jnp.inf, tile_v)
        tile_i = jnp.where(hit_t, _IDX_SENTINEL, tile_i)
        run_v = jnp.where(hit_r, -jnp.inf, run_v)
        run_i = jnp.where(hit_r, _IDX_SENTINEL, run_i)
    tv_scr[...] = new_v
    ti_scr[...] = new_i


def _topk_init(m_scr, s_scr, tv_scr, ti_scr):
    # m starts at the finite f32 min (not -inf): see _topk_lse_update's
    # clamp note.  The top-k value scratch keeps -inf (selection wants
    # true -inf semantics for empty slots).
    m_scr[...] = jnp.full_like(m_scr, jnp.finfo(jnp.float32).min)
    s_scr[...] = jnp.zeros_like(s_scr)
    tv_scr[...] = jnp.full_like(tv_scr, -jnp.inf)
    ti_scr[...] = jnp.full_like(ti_scr, _IDX_SENTINEL)


def _topk_emit(topv_ref, topi_ref, lse_ref, m_scr, s_scr, tv_scr, ti_scr):
    lse_ref[...] = m_scr[...] + jnp.log(s_scr[...])
    topv_ref[...] = tv_scr[...]
    topi_ref[...] = ti_scr[...]


def _topk_readout_kernel(s_ref, w_ref, b_ref, topv_ref, topi_ref, lse_ref,
                         m_scr, s_scr, tv_scr, ti_scr, *, vocab, k, v_tile):
    from jax.experimental import pallas as pl

    v = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(v == 0)
    def _init():
        _topk_init(m_scr, s_scr, tv_scr, ti_scr)

    l = jnp.dot(s_ref[...], w_ref[...],
                preferred_element_type=jnp.float32) + b_ref[...]  # [Rb, Vt]
    _topk_lse_update(l, v * v_tile, vocab, k, m_scr, s_scr, tv_scr, ti_scr)

    @pl.when(v == nv - 1)
    def _fin():
        _topk_emit(topv_ref, topi_ref, lse_ref, m_scr, s_scr, tv_scr, ti_scr)


def topk_lse_readout_pallas(states_c, w_p, b_p, *, vocab: int, k: int,
                            row_block: int, v_tile: int):
    """states_c [N, D] compute dtype, w_p [D, V'] compute dtype, b_p [1, V']
    f32 (padded tail at -1e30), ``vocab`` the REAL V (columns >= vocab are
    padding and can never be selected) -> (topv [N, TOPK_LANES] f32,
    topi [N, TOPK_LANES] i32, lse [N, 1] f32).  Only the first ``k`` lanes
    of topv/topi carry data — the caller slices ``[:, :k]``.  The [N, V']
    logits never exist outside VMEM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, D = states_c.shape
    Vp = w_p.shape[1]
    nR, nV = N // row_block, Vp // v_tile
    Rb, Vt, L = row_block, v_tile, TOPK_LANES
    kernel = functools.partial(_topk_readout_kernel, vocab=vocab, k=k,
                               v_tile=Vt)
    return pl.pallas_call(
        kernel,
        grid=(nR, nV),
        in_specs=[
            pl.BlockSpec((Rb, D), lambda r, v: (r, 0)),    # states (resident)
            pl.BlockSpec((D, Vt), lambda r, v: (0, v)),    # w tile
            pl.BlockSpec((1, Vt), lambda r, v: (0, v)),    # bias tile
        ],
        out_specs=[
            pl.BlockSpec((Rb, L), lambda r, v: (r, 0)),
            pl.BlockSpec((Rb, L), lambda r, v: (r, 0)),
            pl.BlockSpec((Rb, 1), lambda r, v: (r, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, L), jnp.float32),
            jax.ShapeDtypeStruct((N, L), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((Rb, 1), jnp.float32),
            pltpu.VMEM((Rb, 1), jnp.float32),
            pltpu.VMEM((Rb, L), jnp.float32),
            pltpu.VMEM((Rb, L), jnp.int32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_interpret(),
    )(states_c, w_p, b_p)


def _topk_logits_kernel(l_ref, topv_ref, topi_ref, lse_ref,
                        m_scr, s_scr, tv_scr, ti_scr, *, vocab, k, v_tile):
    from jax.experimental import pallas as pl

    v = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(v == 0)
    def _init():
        _topk_init(m_scr, s_scr, tv_scr, ti_scr)

    l = l_ref[...].astype(jnp.float32)
    _topk_lse_update(l, v * v_tile, vocab, k, m_scr, s_scr, tv_scr, ti_scr)

    @pl.when(v == nv - 1)
    def _fin():
        _topk_emit(topv_ref, topi_ref, lse_ref, m_scr, s_scr, tv_scr, ti_scr)


def topk_lse_logits_pallas(logits, *, vocab: int, k: int, row_block: int,
                           v_tile: int):
    """Pre-materialized-logits variant (opaque step nets whose readout the
    engine cannot tile): logits [N, V'] (tail padded at -1e30, ``vocab``
    the real V) are read ONCE instead of XLA's three passes (max, exp-sum,
    top-k) and no f32 log-softmax buffer is ever built.  Same outputs as
    ``topk_lse_readout_pallas``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, Vp = logits.shape
    nR, nV = N // row_block, Vp // v_tile
    Rb, Vt, L = row_block, v_tile, TOPK_LANES
    kernel = functools.partial(_topk_logits_kernel, vocab=vocab, k=k,
                               v_tile=Vt)
    return pl.pallas_call(
        kernel,
        grid=(nR, nV),
        in_specs=[pl.BlockSpec((Rb, Vt), lambda r, v: (r, v))],
        out_specs=[
            pl.BlockSpec((Rb, L), lambda r, v: (r, 0)),
            pl.BlockSpec((Rb, L), lambda r, v: (r, 0)),
            pl.BlockSpec((Rb, 1), lambda r, v: (r, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, L), jnp.float32),
            jax.ShapeDtypeStruct((N, L), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((Rb, 1), jnp.float32),
            pltpu.VMEM((Rb, 1), jnp.float32),
            pltpu.VMEM((Rb, L), jnp.float32),
            pltpu.VMEM((Rb, L), jnp.int32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=_interpret(),
    )(logits)
