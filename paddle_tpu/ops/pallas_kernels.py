"""Pallas TPU kernels for the hot ops.

The reference's performance tier is hand-written CUDA: fused LSTM cell with
intra-sequence parallelism (paddle/cuda/src/hl_cuda_lstm.cu:26-58, PTX
bar.sync), fused GRU (hl_gru_ops.cuh).  The TPU analog: the *whole* LSTM/GRU
time loop runs inside ONE Pallas kernel — the grid's sequential dimension is
time, recurrent weights stay resident in VMEM across all timesteps, and the
h/c state lives in VMEM scratch, so per-step HBM traffic is just the input
projection block in and the hidden block out.

Forward-only kernels wrapped in ``jax.custom_vjp``: the backward pass
recomputes via the pure-JAX scan implementation (rematerialization trades
FLOPs for memory, and keeps one numerics source of truth for gradients).

All kernels are shape-gated: ``lstm_layer``/``gru_layer`` in ops.rnn call
these automatically on TPU when dims are tile-aligned; otherwise the lax.scan
path runs.  CPU tests run both paths and compare (interpret mode).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pallas_available", "lstm_forward_pallas", "gru_forward_pallas"]


def pallas_available() -> bool:
    try:
        import jax.experimental.pallas  # noqa: F401

        return jax.default_backend() in ("tpu", "cpu", "axon")
    except Exception:  # pragma: no cover
        return False


def _interpret() -> bool:
    import jax

    return jax.default_backend() not in ("tpu", "axon")


# ---------------------------------------------------------------------------
# LSTM: one kernel over the whole sequence
# ---------------------------------------------------------------------------


def _lstm_kernel(xp_ref, m_ref, wh_ref, hseq_ref, hfin_ref, cfin_ref,
                 h_scr, c_scr, *, hidden: int, mxu_dtype):
    from jax.experimental import pallas as pl

    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)
        c_scr[...] = jnp.zeros_like(c_scr)

    h = h_scr[...]
    c = c_scr[...]
    xp = xp_ref[0]                          # [B, 4H]
    # matmul operands follow the framework's compute-dtype policy (bf16 by
    # default) so this kernel computes the same function as the lax.scan
    # path (linear()/mxu_cast) that the custom_vjp backward differentiates
    z = xp + jnp.dot(h.astype(mxu_dtype), wh_ref[...].astype(mxu_dtype),
                     preferred_element_type=jnp.float32)
    H = hidden
    i = jax.nn.sigmoid(z[:, :H])
    f = jax.nn.sigmoid(z[:, H : 2 * H])
    o = jax.nn.sigmoid(z[:, 2 * H : 3 * H])
    g = jnp.tanh(z[:, 3 * H :])
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    m = m_ref[0]                            # [B, 1]
    keep = m > 0
    h_new = jnp.where(keep, h_new, h)
    c_new = jnp.where(keep, c_new, c)
    h_scr[...] = h_new
    c_scr[...] = c_new
    # padded steps emit zeros (carry is held in scratch) — identical output
    # semantics to scan_rnn, so the recompute-backward differentiates the
    # same function the forward computes
    hseq_ref[0] = h_new * m

    @pl.when(t == T - 1)
    def _fin():
        hfin_ref[...] = h_new
        cfin_ref[...] = c_new


def _lstm_pallas_raw(xp_tb, mask_tb, w_h):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from paddle_tpu.ops.numerics import compute_dtype

    T, B, H4 = xp_tb.shape
    H = H4 // 4
    kernel = functools.partial(_lstm_kernel, hidden=H,
                               mxu_dtype=compute_dtype())
    return pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, B, 1), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=_interpret(),
    )(xp_tb, mask_tb[..., None], w_h)


def _lstm_reference(xp, mask, w_h):
    """Pure-JAX twin (same math, same f32 compute dtype) used for the
    custom_vjp backward; differentiating through the entry casts yields
    gradients in the caller's original dtypes."""
    from paddle_tpu.ops.rnn import lstm_step, scan_rnn

    xp = xp.astype(jnp.float32)
    w_h = w_h.astype(jnp.float32)

    def step(carry, xp_t):
        h, c = carry
        h2, c2 = lstm_step(xp_t, h, c, w_h)
        return (h2, c2), h2

    B = xp.shape[0]
    H = w_h.shape[0]
    z = jnp.zeros((B, H), jnp.float32)
    (h_f, c_f), h_seq = scan_rnn(step, (z, z), xp, mask)
    return h_seq, h_f, c_f


@jax.custom_vjp
def lstm_forward_pallas(xp, mask, w_h):
    """xp: [B,T,4H] input projection (+bias), mask [B,T], w_h [H,4H].
    Returns (h_seq [B,T,H], h_final, c_final), always float32; h_seq is zero
    at padded timesteps (same semantics as the scan path). No peepholes
    (gated upstream).

    Direct kernel entry (tests exercise it in interpret mode; backward is
    autodiff-of-reference).  The PRODUCTION path is
    ops/rnn_fused.lstm_sequence_fused, which pairs the same raw kernel with
    the hand-written fast backward."""
    xp_tb = jnp.moveaxis(xp.astype(jnp.float32), 1, 0)
    m_tb = jnp.moveaxis(mask.astype(jnp.float32), 1, 0)
    h_tb, h_f, c_f = _lstm_pallas_raw(xp_tb, m_tb, w_h.astype(jnp.float32))
    return jnp.moveaxis(h_tb, 0, 1), h_f, c_f


def _lstm_fwd(xp, mask, w_h):
    out = lstm_forward_pallas(xp, mask, w_h)
    return out, (xp, mask, w_h)


def _lstm_bwd(res, ct):
    xp, mask, w_h = res
    _, vjp = jax.vjp(lambda xp, w_h: _lstm_reference(xp, mask, w_h), xp, w_h)
    d_xp, d_wh = vjp(ct)
    return d_xp, None, d_wh


lstm_forward_pallas.defvjp(_lstm_fwd, _lstm_bwd)


# ---------------------------------------------------------------------------
# GRU: same structure
# ---------------------------------------------------------------------------


def _gru_kernel(xp_ref, m_ref, wh_ref, hseq_ref, hfin_ref, h_scr, *,
                hidden: int, mxu_dtype):
    from jax.experimental import pallas as pl

    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = jnp.zeros_like(h_scr)

    h = h_scr[...]
    H = hidden
    xp = xp_ref[0]                                      # [B, 3H]
    w = wh_ref[...].astype(mxu_dtype)                   # [H, 3H]
    hc = h.astype(mxu_dtype)
    zr = xp[:, : 2 * H] + jnp.dot(hc, w[:, : 2 * H],
                                  preferred_element_type=jnp.float32)
    r = jax.nn.sigmoid(zr[:, :H])
    u = jax.nn.sigmoid(zr[:, H:])
    cand = jnp.tanh(xp[:, 2 * H :] + jnp.dot((r * h).astype(mxu_dtype),
                                             w[:, 2 * H :],
                                             preferred_element_type=jnp.float32))
    h_new = u * h + (1.0 - u) * cand
    m = m_ref[0]
    h_new = jnp.where(m > 0, h_new, h)
    h_scr[...] = h_new
    hseq_ref[0] = h_new * m

    @pl.when(t == T - 1)
    def _fin():
        hfin_ref[...] = h_new


def _gru_pallas_raw(xp_tb, mask_tb, w_h):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from paddle_tpu.ops.numerics import compute_dtype

    T, B, H3 = xp_tb.shape
    H = H3 // 3
    kernel = functools.partial(_gru_kernel, hidden=H,
                               mxu_dtype=compute_dtype())
    return pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H3), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, B, 1), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H3), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((B, H), jnp.float32)],
        interpret=_interpret(),
    )(xp_tb, mask_tb[..., None], w_h)


def _gru_reference(xp, mask, w_h):
    from paddle_tpu.ops.rnn import gru_step, scan_rnn

    xp = xp.astype(jnp.float32)
    w_h = w_h.astype(jnp.float32)

    def step(h, xp_t):
        h2 = gru_step(xp_t, h, w_h)
        return h2, h2

    B = xp.shape[0]
    H = w_h.shape[0]
    h_f, h_seq = scan_rnn(step, jnp.zeros((B, H), jnp.float32), xp, mask)
    return h_seq, h_f


@jax.custom_vjp
def gru_forward_pallas(xp, mask, w_h):
    """xp: [B,T,3H], mask [B,T], w_h [H,3H] -> (h_seq [B,T,H], h_final),
    always float32; h_seq is zero at padded timesteps.

    Direct kernel entry (tests/interpret mode); production uses
    ops/rnn_fused.gru_sequence_fused — see lstm_forward_pallas."""
    xp_tb = jnp.moveaxis(xp.astype(jnp.float32), 1, 0)
    m_tb = jnp.moveaxis(mask.astype(jnp.float32), 1, 0)
    h_tb, h_f = _gru_pallas_raw(xp_tb, m_tb, w_h.astype(jnp.float32))
    return jnp.moveaxis(h_tb, 0, 1), h_f


def _gru_fwd(xp, mask, w_h):
    out = gru_forward_pallas(xp, mask, w_h)
    return out, (xp, mask, w_h)


def _gru_bwd(res, ct):
    xp, mask, w_h = res
    _, vjp = jax.vjp(lambda xp, w_h: _gru_reference(xp, mask, w_h), xp, w_h)
    d_xp, d_wh = vjp(ct)
    return d_xp, None, d_wh


gru_forward_pallas.defvjp(_gru_fwd, _gru_bwd)
