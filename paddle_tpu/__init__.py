"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capabilities of legacy PaddlePaddle
(dzhwinter/Paddle; see SURVEY.md): a declarative layer-graph front-end with
first-class variable-length sequence support (LSTM/GRU, attention NMT with
beam-search generation), CNNs, sparse embeddings, a full trainer / optimizer /
evaluator / checkpoint lifecycle, and distributed training — re-architected for
TPU: ops are JAX/XLA/Pallas, graphs compile to jitted pure functions, and the
reference's MultiGradientMachine + parameter-server tier becomes SPMD sharding
over a ``jax.sharding.Mesh`` with ICI collectives.
"""

__version__ = "0.1.0"

from paddle_tpu.utils import FLAGS, logger
from paddle_tpu.utils.devices import init

__all__ = ["FLAGS", "logger", "init", "__version__"]
