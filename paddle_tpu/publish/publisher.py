"""Gated model publication: verified checkpoint -> versioned deploy bundle.

Layout of a publish directory (docs/publish.md)::

    publish_dir/
      v-00001/
        model.ptz       # the deploy bundle (config/deploy.py merge_model)
        manifest.json   # version, pass_id, train_commit_time, CRC32s,
                        # architecture fingerprint, quantize recipe
      v-00002/ ...
      ccache/           # shared compile cache (config/compile_cache.py):
                        # executables are keyed by the ARCHITECTURE
                        # fingerprint, so every published weight version
                        # of one model shares the warmed entries

The gate: a version is only ever cut from a checkpoint pass at or below
``latest_verified_pass(save_dir)`` (resilience/integrity.py) whose
directory still CRC-validates — an unverified or quarantined pass is
unpublishable by construction, and the bundle bytes come from the
verified checkpoint on disk, never from live trainer memory.  Every
publish writes through the checkpoint_io discipline: dot-prefixed temp
dir, per-file fsync, one ``os.replace``.  Attempts and refusals are
journaled (``publish_commit`` / ``publish_refused``).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
import uuid
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from paddle_tpu.utils.log import logger

__all__ = ["PublishRefused", "Publisher", "freshness_from_journal",
           "latest_version", "list_model_dirs", "list_versions",
           "model_publish_dir", "publish_cache_dir",
           "publish_from_checkpoints", "read_version_manifest",
           "validate_version", "version_dir"]

_VERSION_RE = re.compile(r"v-(\d{5,})$")
_TMP_PREFIX = ".tmp-"
#: the bundle member every version dir carries
BUNDLE_NAME = "model.ptz"
MANIFEST_NAME = "manifest.json"
#: shared compile cache for every version of the publish dir
CACHE_SUBDIR = "ccache"


class PublishRefused(RuntimeError):
    """The gate refused to cut a version: the requested pass is newer
    than ``latest_verified_pass``, its checkpoint no longer validates,
    or the quantize error gate failed.  ``reason`` is the machine-
    readable signal the refusal was journaled under."""

    def __init__(self, message: str, *, reason: str,
                 pass_id: Optional[int] = None) -> None:
        super().__init__(message)
        self.reason = reason
        self.pass_id = pass_id


def version_dir(publish_dir: str, version: int) -> str:
    return os.path.join(publish_dir, f"v-{version:05d}")


def list_versions(publish_dir: str) -> List[int]:
    """Every published version number, ascending (temp dirs and the
    shared cache are never matched)."""
    try:
        names = os.listdir(publish_dir)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        m = _VERSION_RE.fullmatch(n)
        if m and os.path.isdir(os.path.join(publish_dir, n)):
            out.append(int(m.group(1)))
    return sorted(out)


def latest_version(publish_dir: str) -> int:
    """Newest published version number, or 0 when none exist."""
    vs = list_versions(publish_dir)
    return vs[-1] if vs else 0


#: model names must be safe as directory components AND unambiguous
#: against version dirs / the shared cache
_MODEL_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._-]*$")


def model_publish_dir(publish_root: str, name: str) -> str:
    """One model's watch dir under a fleet publish root
    (``<root>/<name>/v-NNNNN/...``): each fleet model gets its own
    version sequence, manifest chain, and shared compile cache, so
    publishing model A can never perturb model B's rollout
    (docs/serving.md "Fleet serving")."""
    if not _MODEL_RE.fullmatch(name or "") or _VERSION_RE.fullmatch(name) \
            or name == CACHE_SUBDIR or name.startswith(_TMP_PREFIX):
        raise ValueError(f"invalid publish model name {name!r}")
    return os.path.join(publish_root, name)


def list_model_dirs(publish_root: str) -> List[str]:
    """Model names under a fleet publish root, sorted — a directory
    counts as a model iff it holds at least one version dir (stray
    dirs and the flat single-model layout are never misread)."""
    try:
        names = os.listdir(publish_root)
    except FileNotFoundError:
        return []
    out = []
    for n in sorted(names):
        if not _MODEL_RE.fullmatch(n) or _VERSION_RE.fullmatch(n) \
                or n == CACHE_SUBDIR or n.startswith(_TMP_PREFIX):
            continue
        if list_versions(os.path.join(publish_root, n)):
            out.append(n)
    return out


def read_version_manifest(vdir: str) -> Dict[str, Any]:
    with open(os.path.join(vdir, MANIFEST_NAME)) as f:
        return json.load(f)


def validate_version(vdir: str) -> Optional[str]:
    """Re-hash one published version against its manifest; returns the
    failure reason (naming the damaged member) or None.  The at-rest
    integrity check the reload path runs before trusting a version —
    the publish-tier analog of ``validate_checkpoint``."""
    try:
        manifest = read_version_manifest(vdir)
    except FileNotFoundError:
        return f"missing {MANIFEST_NAME}"
    except (json.JSONDecodeError, OSError) as e:
        return f"{MANIFEST_NAME} unreadable: {e}"
    for fname, want in (manifest.get("files") or {}).items():
        path = os.path.join(vdir, fname)
        try:
            with open(path, "rb") as f:
                crc = zlib.crc32(f.read())
        except OSError as e:
            return f"member {fname} unreadable: {e}"
        if crc != int(want.get("crc32", -1)):
            return (f"member {fname} CRC mismatch "
                    f"(stored {want.get('crc32')}, computed {crc})")
    if not (manifest.get("files") or {}):
        return "manifest lists no files"
    return None


def publish_cache_dir(publish_dir: str):
    """The publish directory's shared compile cache — executables keyed
    by the architecture fingerprint, shared by every weight version."""
    from paddle_tpu.config.compile_cache import CompileCacheDir

    return CompileCacheDir(os.path.join(publish_dir, CACHE_SUBDIR))


def _journal_refused(reason: str, message: str,
                     pass_id: Optional[int]) -> PublishRefused:
    from paddle_tpu.obs import journal_event

    journal_event("publish_refused", reason=reason, detail=message,
                  pass_id=pass_id)
    logger.warning("publish refused (%s): %s", reason, message)
    return PublishRefused(message, reason=reason, pass_id=pass_id)


def _load_checkpoint_trees(topology, ckpt_dir: str):
    """Restore params/state from the VERIFIED checkpoint's bytes — the
    published weights are the scrubbed artifact, not live memory."""
    import jax

    from paddle_tpu.resilience.checkpoint_io import load_pytree, read_manifest

    manifest = read_manifest(ckpt_dir)
    init_p, init_s = jax.eval_shape(
        lambda k: topology.init(k), jax.random.PRNGKey(0))

    def dtypes_of(fname: str) -> Dict[str, str]:
        arrays = ((manifest.get("files") or {}).get(fname) or {}).get(
            "arrays") or {}
        return {k: v.get("orig_dtype") for k, v in arrays.items()
                if v.get("orig_dtype")}

    params = load_pytree(os.path.join(ckpt_dir, "params.npz"), init_p,
                         dtypes_of("params.npz"))
    state = {}
    if init_s and manifest.get("has_state"):
        state = load_pytree(os.path.join(ckpt_dir, "state.npz"), init_s,
                            dtypes_of("state.npz"))
    return params, state, manifest


def publish_from_checkpoints(
    publish_dir: str,
    topology,
    save_dir: str,
    *,
    pass_id: Optional[int] = None,
    name: str = "model",
    quantize: Optional[str] = None,
    quantize_tol: float = 0.05,
    example_feed: Optional[Dict[str, Any]] = None,
    warm_cache: bool = True,
    warm_max_batch: int = 8,
    meta: Optional[dict] = None,
) -> str:
    """Cut one gated, versioned publish from the checkpoint tier.

    ``pass_id`` defaults to ``latest_verified_pass(save_dir)``; an
    explicit pass NEWER than the verified tip — or one whose checkpoint
    dir is quarantined or no longer CRC-validates — raises the typed
    :class:`PublishRefused` (journaled as ``publish_refused``), so an
    unverified pass is unpublishable by construction.

    The bundle export runs the full ``merge_model`` plane (quantize
    error gate, optional lint audit via ``example_feed``); with
    ``warm_cache`` the new model's bucket compile surfaces are primed
    into the publish dir's SHARED cache (architecture-fingerprint keys),
    so a reload — or a fresh boot of any version — pays zero XLA
    compiles.  Returns the published version directory."""
    from paddle_tpu.config.deploy import load_inference_model, merge_model
    from paddle_tpu.obs import journal_event
    from paddle_tpu.resilience.checkpoint_io import (_fsync_dir, _fsync_file,
                                                     pass_dir,
                                                     quarantine_reason,
                                                     validate_checkpoint)
    from paddle_tpu.resilience.integrity import latest_verified_pass

    t_publish0 = time.time()
    verified = latest_verified_pass(save_dir)
    requested = verified if pass_id is None else int(pass_id)
    if requested < 0:
        raise _journal_refused(
            "no_verified_pass",
            f"no verified checkpoint under {save_dir!r} to publish",
            requested)
    if requested > verified:
        raise _journal_refused(
            "pass_not_verified",
            f"pass {requested} is newer than the latest verified pass "
            f"{verified} — the scrubber has not blessed it", requested)
    ckpt_dir = pass_dir(save_dir, requested)
    q = quarantine_reason(ckpt_dir)
    if q is not None:
        raise _journal_refused(
            "pass_quarantined",
            f"pass {requested} is quarantined: {q}", requested)
    bad = validate_checkpoint(ckpt_dir)
    if bad is not None:
        raise _journal_refused(
            "checkpoint_invalid",
            f"pass {requested} no longer validates: {bad}", requested)
    params, state, ckpt_manifest = _load_checkpoint_trees(topology, ckpt_dir)
    #: the freshness SLO's clock zero — the wall-clock the checkpoint
    #: tier committed this state at
    train_commit_time = float(ckpt_manifest.get("time") or t_publish0)

    os.makedirs(publish_dir, exist_ok=True)
    tmp = os.path.join(publish_dir, f"{_TMP_PREFIX}{uuid.uuid4().hex[:8]}")
    os.makedirs(tmp)
    try:
        bundle_meta = {
            **(meta or {}),
            "pass_id": requested,
            "train_commit_time": train_commit_time,
        }
        try:
            merge_model(os.path.join(tmp, BUNDLE_NAME), topology,
                        params, state or None, name=name, meta=bundle_meta,
                        example_feed=example_feed, quantize=quantize,
                        quantize_tol=quantize_tol)
        except ValueError as e:
            # the quantize error gate (or a structural export failure)
            # refuses typed like the verification gate — a bundle that
            # would serve degraded predictions is never published
            raise _journal_refused("export_gate", str(e), requested) from e
        # the architecture fingerprint is the compile-cache identity every
        # weight version shares (params ride compiled calls as arguments)
        model = load_inference_model(os.path.join(tmp, BUNDLE_NAME),
                                     arch_fingerprint=True)
        if warm_cache:
            _prime_bundle(model, publish_dir, warm_max_batch)
        with open(os.path.join(tmp, BUNDLE_NAME), "rb") as f:
            crc = zlib.crc32(f.read())
        version = latest_version(publish_dir) + 1
        manifest = {
            "version": version,
            "name": name,
            "pass_id": requested,
            "train_commit_time": train_commit_time,
            "publish_time": time.time(),
            "fingerprint": model.fingerprint,
            "quantize": (model.manifest.get("quantize") or {}).get("mode"),
            "files": {BUNDLE_NAME: {"crc32": crc}},
        }
        mpath = os.path.join(tmp, MANIFEST_NAME)
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        _fsync_file(os.path.join(tmp, BUNDLE_NAME))
        _fsync_dir(tmp)
        final = version_dir(publish_dir, version)
        os.replace(tmp, final)
        _fsync_dir(publish_dir)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    # fsync'd: the durable anchor the freshness SLO is reconstructed
    # against (freshness_from_journal)
    journal_event("publish_commit", fsync=True, version=version,
                  pass_id=requested, dir=final,
                  train_commit_time=train_commit_time,
                  fingerprint=model.fingerprint,
                  publish_s=round(time.time() - t_publish0, 3))
    logger.info("published v%d (pass %d) -> %s", version, requested, final)
    return final


def _prime_bundle(model, publish_dir: str, max_batch: int) -> None:
    """Warm the publish dir's shared compile cache with the new model's
    bucket executables (PR 12 machinery): the server's reload — and any
    fresh boot of this or a later version — loads instead of compiling."""
    from paddle_tpu.serving.batching import batch_bucket, warmup_bucket_feeds
    from paddle_tpu.serving.feeds import example_feed

    cache = publish_cache_dir(publish_dir)
    feed = example_feed(model.topology)
    buckets = sorted({batch_bucket(r, max_batch)
                      for r in range(1, max_batch + 1)})
    for padded in warmup_bucket_feeds(feed, buckets):
        model.prime(padded, cache=cache)


class Publisher:
    """Bound publisher: one publish directory + topology, republished
    every call (the trainer's ``--publish_every`` hook)."""

    def __init__(self, publish_dir: str, topology, *, name: str = "model",
                 quantize: Optional[str] = None, quantize_tol: float = 0.05,
                 warm_cache: bool = True, warm_max_batch: int = 8) -> None:
        self.publish_dir = publish_dir
        self.topology = topology
        self.name = name
        self.quantize = quantize
        self.quantize_tol = quantize_tol
        self.warm_cache = warm_cache
        self.warm_max_batch = warm_max_batch

    def publish(self, save_dir: str,
                pass_id: Optional[int] = None) -> str:
        return publish_from_checkpoints(
            self.publish_dir, self.topology, save_dir, pass_id=pass_id,
            name=self.name, quantize=self.quantize,
            quantize_tol=self.quantize_tol, warm_cache=self.warm_cache,
            warm_max_batch=self.warm_max_batch)


def freshness_from_journal(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Reconstruct the train-commit -> serving-ready freshness SLO from a
    merged journal timeline: one row per successful publish, carrying the
    publish latency (``train_commit_time`` -> ``publish_commit``), the
    swap time (``reload_commit``), and serving-ready (``probation_passed``
    — or the swap itself when no probation record exists yet).

    Input is ``merge_journals()`` output (or any list of journal records
    with ``kind``/``t`` fields)."""
    rows: Dict[int, Dict[str, Any]] = {}
    for r in events:
        kind, v = r.get("kind"), r.get("version")
        if v is None:
            continue
        v = int(v)
        if kind == "publish_commit":
            rows[v] = {
                "version": v,
                "pass_id": r.get("pass_id"),
                "train_commit_time": r.get("train_commit_time"),
                "published_at": r.get("t"),
                "swapped_at": None,
                "serving_ready_at": None,
                "rolled_back": False,
            }
        elif kind == "reload_commit" and v in rows:
            rows[v]["swapped_at"] = r.get("t")
            rows[v]["serving_ready_at"] = r.get("t")
        elif kind == "probation_passed" and v in rows:
            rows[v]["serving_ready_at"] = r.get("t")
        elif kind == "publish_rollback" and v in rows:
            rows[v]["rolled_back"] = True
            rows[v]["serving_ready_at"] = None
    out = []
    for v in sorted(rows):
        row = rows[v]
        t0, t1 = row.get("train_commit_time"), row.get("serving_ready_at")
        row["freshness_s"] = (round(float(t1) - float(t0), 3)
                              if t0 is not None and t1 is not None else None)
        out.append(row)
    return out
