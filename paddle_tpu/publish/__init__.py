"""Continuous model publication (docs/publish.md).

The gated publisher closes the train->serve loop: every
``--publish_every`` passes the trainer exports a quantize-gated deploy
bundle into a versioned, CRC-manifested publish directory — but ONLY
from a checkpoint pass the SDC firewall has verified
(``latest_verified_pass``; resilience/integrity.py).  A live server
watches the directory and hot-swaps new versions with zero dropped
requests (serving/reload.py).
"""

from paddle_tpu.publish.publisher import (PublishRefused, Publisher,
                                          freshness_from_journal,
                                          latest_version, list_model_dirs,
                                          list_versions, model_publish_dir,
                                          publish_cache_dir,
                                          publish_from_checkpoints,
                                          read_version_manifest,
                                          validate_version, version_dir)

__all__ = [
    "PublishRefused", "Publisher", "freshness_from_journal",
    "latest_version", "list_model_dirs", "list_versions",
    "model_publish_dir", "publish_cache_dir",
    "publish_from_checkpoints", "read_version_manifest",
    "validate_version", "version_dir",
]
