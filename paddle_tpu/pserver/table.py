"""Sharded embedding table store — the pserver's parameter memory.

A table is a [V_pad, D] array row-sharded over one mesh axis
(``P(axis, None)``): each of the n shard devices holds ``V_pad / n``
contiguous rows and the full table never exists on one host.  Three
invariants the rest of the tier builds on:

- **vocab padding**: V is padded UP to a multiple of the shard count
  (``pad_vocab``); tail rows are masked to zero at init and can never be
  requested (ids are always < V), so they stay zero forever and cost only
  the padding bytes.  Padding can be disabled, in which case a non-dividing
  vocab raises a typed ``ConfigError`` naming the table instead of failing
  later inside ``device_put`` with a shape error.
- **per-shard deterministic init**: shard s draws its rows from
  ``fold_in(PRNGKey(seed), s)`` — init happens shard-locally under
  shard_map (no [V, D] materialization), yet any host can re-derive any
  shard bit-exactly (threefry is backend-deterministic), which is what lets
  incremental snapshots replay on top of a re-init instead of requiring a
  full base dump (snapshot.py).
- **f32 master / optional bf16 compute** (ROADMAP item 3 conventions): the
  stored master table keeps ``dtype`` (f32 default); lookups may cast the
  gathered rows to ``compute_dtype`` on the way out while gradients and
  updates stay in master precision.
"""

from __future__ import annotations

import functools
from dataclasses import asdict, dataclass, field, replace
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import compat
from paddle_tpu.utils.error import ConfigError

__all__ = ["TableSpec", "ShardedTable", "pad_vocab", "init_shard_rows"]


def pad_vocab(vocab: int, shards: int, *, pad: bool = True,
              name: str = "table") -> int:
    """Vocab rows padded up to a multiple of ``shards``; with ``pad=False``
    a non-dividing vocab is a typed config error naming the table."""
    if vocab <= 0:
        raise ConfigError(f"table {name!r}: vocab must be positive, got {vocab}")
    if shards <= 0:
        raise ConfigError(f"table {name!r}: shard count must be positive, "
                          f"got {shards}")
    rem = vocab % shards
    if rem == 0:
        return vocab
    if not pad:
        raise ConfigError(
            f"table {name!r}: vocab {vocab} does not divide evenly over "
            f"{shards} shards and padding is disabled — enable padding "
            f"(masked tail rows) or resize the vocabulary")
    return vocab + (shards - rem)


@dataclass(frozen=True)
class TableSpec:
    """Declarative spec of one sharded table — everything a host needs to
    re-derive the initial shard contents (snapshot replay) and validate a
    snapshot against the live config."""

    name: str
    vocab: int
    dim: int
    init: str = "normal"            # 'normal' | 'uniform' | 'zeros'
    initial_std: float = 0.01
    initial_mean: float = 0.0
    seed: int = 0
    dtype: str = "float32"          # master dtype (f32 keeps exact updates)
    compute_dtype: Optional[str] = None   # lookup output cast (e.g. bfloat16)
    #: per-DEVICE byte budget for this table's shard (0 = unchecked); the
    #: "too large for one device" contract: the FULL table may exceed it as
    #: long as every shard fits
    device_budget_bytes: int = 0

    def padded_vocab(self, shards: int, *, pad: bool = True) -> int:
        return pad_vocab(self.vocab, shards, pad=pad, name=self.name)

    def table_bytes(self) -> int:
        return self.vocab * self.dim * jnp.dtype(self.dtype).itemsize

    def shard_bytes(self, shards: int) -> int:
        vs = self.padded_vocab(shards) // shards
        return vs * self.dim * jnp.dtype(self.dtype).itemsize

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "TableSpec":
        return cls(**d)


def init_shard_rows(spec: TableSpec, shard_index, shard_rows: int):
    """Rows ``[shard_index * shard_rows, ...)`` of the table, computed from
    the per-shard folded key.  Traceable (``shard_index`` may be a tracer
    inside shard_map) AND host-replayable with a concrete index — both
    produce identical bits.  Tail rows past the true vocab are masked to
    zero."""
    key = jax.random.fold_in(jax.random.PRNGKey(spec.seed), shard_index)
    shape = (shard_rows, spec.dim)
    dtype = jnp.dtype(spec.dtype)
    if spec.init == "zeros":
        rows = jnp.zeros(shape, dtype)
    elif spec.init == "uniform":
        a = spec.initial_std
        rows = jax.random.uniform(key, shape, dtype, -a, a)
    else:  # normal — the reference's embedding default
        rows = (spec.initial_mean
                + spec.initial_std * jax.random.normal(key, shape, dtype))
    row_id = shard_index * shard_rows + jnp.arange(shard_rows)
    return rows * (row_id < spec.vocab)[:, None].astype(dtype)


class ShardedTable:
    """One live sharded table: the master array, its dirty-row mask, and the
    placement metadata.  ``data``/``dirty`` are plain jax arrays (swapped
    wholesale by the jitted step via the tier), everything else is static."""

    def __init__(self, spec: TableSpec, mesh, *, axis: str = "model",
                 pad: bool = True, data=None, dirty=None,
                 dcn_axis: Optional[str] = None) -> None:
        from paddle_tpu.parallel.mesh import as_mesh

        self.spec = spec
        self.mesh = mesh = as_mesh(mesh)
        self.axis = axis
        # multi-pod: rows shard over (dcn, axis) jointly — global shard
        # p*k + c lives on device (pod p, col c), which is what makes the
        # two-hop a2a routing (lookup._a2a2_body) land each id at its
        # owner after one ICI + one DCN exchange
        self.dcn_axis = dcn_axis if dcn_axis and dcn_axis != axis else None
        self.pods = int(mesh.shape[self.dcn_axis]) if self.dcn_axis else 1
        self.shards = int(mesh.shape[axis]) * self.pods
        self.vocab_padded = spec.padded_vocab(self.shards, pad=pad)
        self.shard_rows = self.vocab_padded // self.shards
        if spec.device_budget_bytes:
            per = self.shard_rows * spec.dim * jnp.dtype(spec.dtype).itemsize
            if per > spec.device_budget_bytes:
                raise ConfigError(
                    f"table {spec.name!r}: one shard needs {per} bytes "
                    f"({self.shard_rows} x {spec.dim} {spec.dtype}) but the "
                    f"device budget is {spec.device_budget_bytes} — add "
                    f"shards or shrink the table")
        row_axes = (self.dcn_axis, axis) if self.dcn_axis else axis
        self.row_axes = row_axes
        self.sharding = NamedSharding(mesh, P(row_axes, None))
        self.mask_sharding = NamedSharding(mesh, P(row_axes))
        self.data = self._init_sharded() if data is None else data
        self.dirty = (jnp.zeros((self.vocab_padded,), jnp.bool_)
                      if dirty is None else dirty)
        if getattr(self.dirty, "sharding", None) != self.mask_sharding:
            self.dirty = jax.device_put(self.dirty, self.mask_sharding)

    # ------------------------------------------------------------------

    def _init_sharded(self):
        """Per-shard init under shard_map: shard s computes ONLY its rows
        from the folded key — the [V_pad, D] array is born sharded."""
        spec, vs = self.spec, self.shard_rows

        def body(idx):
            return init_shard_rows(spec, idx[0], vs)

        mapped = compat.shard_map(
            body, mesh=self.mesh, in_specs=(P(self.row_axes),),
            out_specs=P(self.row_axes, None), check_vma=False)
        idx = jax.device_put(jnp.arange(self.shards, dtype=jnp.int32),
                             self.mask_sharding)
        return mapped(idx)

    # ------------------------------------------------------------------

    def place(self) -> None:
        """(Re-)pin data/dirty to their shardings — after a checkpoint load
        hands back host arrays."""
        self.data = jax.device_put(jnp.asarray(self.data), self.sharding)
        self.dirty = jax.device_put(
            jnp.asarray(self.dirty, jnp.bool_), self.mask_sharding)

    def rows_host(self, ids) -> np.ndarray:
        """Host pull of selected rows (debug/serving oracle) — gathers on
        device, transfers only the [k, D] result."""
        ids = jnp.asarray(ids, jnp.int32)
        return np.asarray(jnp.take(self.data, ids, axis=0))

    def __repr__(self) -> str:
        at = (f"({self.dcn_axis},{self.axis})" if self.dcn_axis
              else self.axis)
        return (f"<ShardedTable {self.spec.name} {self.spec.vocab}"
                f"(+{self.vocab_padded - self.spec.vocab} pad)x{self.spec.dim} "
                f"{self.shards} shards @{at}>")
