"""All-to-all sharded-embedding lookup — the pserver prefetch on ICI.

The previous sharded lookup (parallel/embedding.py) had every shard gather
the FULL id set (zeros for foreign rows) and ``psum`` the [N, D] results:
O(shards) redundant gather work and an [N, D] reduction that replicates the
output on every device.  Here the exchange is balanced, the way the
reference's trainers prefetch from pservers:

1. each shard takes its 1/n slice of the request ids (the "trainer" role),
2. buckets them by owning shard on-device (stable sort by owner — stability
   is what lets the backward scatter-add reproduce the single-host
   accumulation order bit-for-bit),
3. exchanges fixed-capacity id buckets with ``lax.all_to_all`` (capacity =
   slice length: the worst case — every local id owned by one shard — still
   fits, so no overflow path exists),
4. gathers ONLY its owned rows locally (the "pserver" role), and
5. returns the row payloads through the reverse all-to-all and unpermutes
   them to the requesting positions.

Total bytes moved: one [N] id exchange + one [N, D] row exchange, balanced
across the ring, vs the psum's [N, D] all-reduce with n redundant local
gathers.  The whole program is differentiable — all_to_all transposes to
all_to_all, the local gather to a scatter-add — so the compat shim
(parallel/embedding.sharded_embedding_lookup) keeps its autodiff contract;
the trainer tier instead routes gradients through ``TableProxy`` so the
table cotangent is never densified (tier.py).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import compat

__all__ = ["all_to_all_lookup", "bucket_by_owner", "TableProxy"]


def _bucket_by_key(vals, key, n_buckets: int, fill):
    """Stable-bucket ``vals`` by ``key`` (ints in ``[0, n_buckets)``).

    Returns ``(buckets [n_buckets, cap], order, key_sorted, bucket_pos)``
    where ``cap`` = len(vals) (worst case: one bucket takes everything),
    ``order`` is the stable key-sort permutation and ``(key_sorted,
    bucket_pos)`` addresses each sorted value's cell — the coordinates
    the caller reuses to route payloads back to requesting positions.
    Unused cells hold ``fill``.  Stability is the bit-exactness lever:
    it is what lets the backward scatter-add reproduce the single-host
    accumulation order."""
    per = vals.shape[0]
    order = jnp.argsort(key, stable=True)
    svals = vals[order]
    skey = key[order]
    starts = jnp.searchsorted(skey, jnp.arange(n_buckets))
    bucket_pos = jnp.arange(per) - starts[skey]
    buckets = jnp.full((n_buckets, per), fill, vals.dtype)
    buckets = buckets.at[skey, bucket_pos].set(svals)
    return buckets, order, skey, bucket_pos


def bucket_by_owner(ids, n_shards: int, shard_rows: int, fill_id: int):
    """Stable-bucket a flat id slice by owning shard (the
    :func:`_bucket_by_key` special case keyed on ``id // shard_rows``)."""
    owner = jnp.clip(ids // shard_rows, 0, n_shards - 1)
    return _bucket_by_key(ids, owner, n_shards, fill_id)


def _a2a_body(shard, ids, *, axis: str, n: int):
    """shard_map body: ids [N] replicated, shard [vs, D] local."""
    r = lax.axis_index(axis)
    vs, d = shard.shape
    per = ids.shape[0] // n
    mine = lax.dynamic_slice(ids, (r * per,), (per,))
    buckets, order, sowner, bucket_pos = bucket_by_owner(mine, n, vs, n * vs)
    # exchange requests: row k of recv = the ids device k wants from ME
    recv = lax.all_to_all(buckets, axis, 0, 0)
    local = recv - r * vs
    inb = (local >= 0) & (local < vs)
    rows = jnp.take(shard, jnp.clip(local, 0, vs - 1), axis=0)
    rows = rows * inb[..., None].astype(shard.dtype)
    # return payloads: back[k] = rows shard k fetched for MY requests
    back = lax.all_to_all(rows, axis, 0, 0)
    got = back[sowner, bucket_pos]
    return jnp.zeros((per, d), shard.dtype).at[order].set(got)


def _a2a2_body(shard, ids, *, dcn: str, axis: str, m: int, k: int):
    """Two-level (locality-aware) shard_map body for a multi-pod mesh:
    shard ``[vs, D]`` local on device ``(pod p, col c)`` = global shard
    ``p*k + c``.  An id owned by shard ``og`` first hops over ICI to the
    owner's COLUMN (``og % k`` — pod-local, cheap), then over DCN to the
    owner's POD (``og // k``) — so the expensive tier carries each id
    exactly once, in the column-aggregated second exchange, instead of
    every (src, dst) device pair holding its own DCN bucket."""
    p = lax.axis_index(dcn)
    c = lax.axis_index(axis)
    n = m * k
    g = p * k + c
    vs, d = shard.shape
    per = ids.shape[0] // n
    mine = lax.dynamic_slice(ids, (g * per,), (per,))
    sentinel = n * vs
    og1 = jnp.clip(mine // vs, 0, n - 1)
    # hop 1 (ICI): route to the owner's column inside my pod
    b1, order1, col1, pos1 = _bucket_by_key(mine, og1 % k, k, sentinel)
    r1 = lax.all_to_all(b1, axis, 0, 0).reshape(-1)          # [k*per]
    # hop 2 (DCN): everything here is column-c traffic — route by pod
    og2 = jnp.clip(r1 // vs, 0, n - 1)
    b2, order2, pod2, pos2 = _bucket_by_key(r1, og2 // k, m, sentinel)
    req = lax.all_to_all(b2, dcn, 0, 0).reshape(-1)          # [m*k*per]
    local = req - g * vs
    inb = (local >= 0) & (local < vs)
    rows = jnp.take(shard, jnp.clip(local, 0, vs - 1), axis=0)
    rows = rows * inb[..., None].astype(shard.dtype)
    # reverse DCN hop, unpermute to hop-1 arrival order
    back2 = lax.all_to_all(rows.reshape(m, k * per, d), dcn, 0, 0)
    got2 = back2[pod2, pos2]
    flat1 = jnp.zeros((k * per, d), shard.dtype).at[order2].set(got2)
    # reverse ICI hop, unpermute to requesting positions
    back1 = lax.all_to_all(flat1.reshape(k, per, d), axis, 0, 0)
    got1 = back1[col1, pos1]
    return jnp.zeros((per, d), shard.dtype).at[order1].set(got1)


def all_to_all_lookup(mesh, table, ids, *, axis: str = "model",
                      out_dtype=None, dcn_axis: Optional[str] = None):
    """table: [V_pad, D] sharded ``P(axis, None)`` (``P((dcn_axis, axis),
    None)`` on a multi-pod mesh); ids: int array of any shape, replicated.
    Returns ``[*ids.shape, D]`` embeddings (sharded over the shard axes
    along the flattened request dim; consumers that need them replicated
    get one all-gather from GSPMD instead of the old psum's full
    reduction).  ``out_dtype`` casts the gathered rows (bf16 compute over
    the f32 master, ROADMAP item 3).  ``dcn_axis`` routes the exchange in
    two hops — pod-local column first, cross-pod second — so each id
    crosses DCN at most once (``_a2a2_body``)."""
    m = int(mesh.shape[dcn_axis]) if dcn_axis else 1
    k = int(mesh.shape[axis])
    n = m * k
    v_pad, d = table.shape
    flat = ids.reshape(-1).astype(jnp.int32)
    nreq = flat.shape[0]
    if n == 1:
        out = jnp.take(table, flat, axis=0)
    else:
        npad = (-nreq) % n
        if npad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((npad,), jnp.int32)])
        if m == 1:
            mapped = compat.shard_map(
                functools.partial(_a2a_body, axis=axis, n=n),
                mesh=mesh, in_specs=(P(axis, None), P()),
                out_specs=P(axis), check_vma=False)
        else:
            mapped = compat.shard_map(
                functools.partial(_a2a2_body, dcn=dcn_axis, axis=axis,
                                  m=m, k=k),
                mesh=mesh, in_specs=(P((dcn_axis, axis), None), P()),
                out_specs=P((dcn_axis, axis)), check_vma=False)
        out = mapped(table, flat)[:nreq]
    if out_dtype is not None:
        out = out.astype(out_dtype)
    return out.reshape(*ids.shape, d)


class TableProxy:
    """The table stand-in the trainer slips into ``Topology.apply`` for a
    pserver-routed embedding parameter (``param_overrides``).

    Gradient contract: the master table rides through the step as a
    NON-differentiated input; each lookup adds a zeros "proxy" array of the
    request shape, and the step differentiates w.r.t. the proxies — the
    cotangent that comes back is exactly the (per-position) row gradients,
    i.e. the (ids, row-grads) segments the sparse apply pushes, and no
    [V, D] table cotangent is ever materialized (the "never densify"
    contract gated by ``lint --pserver``).

    Duck-typed: ``nn.embedding``'s forward routes to ``pserver_lookup`` when
    its parameter value carries one.
    """

    def __init__(self, name: str, mesh, axis: str, data,
                 proxies: Dict[Tuple[str, str], Any],
                 compute_dtype=None, dcn_axis: Optional[str] = None) -> None:
        self.name = name
        self.mesh = mesh
        self.axis = axis
        self.dcn_axis = dcn_axis          # two-hop routing on multi-pod
        self.data = data                  # [V_pad, D], non-differentiated
        self.proxies = proxies            # {(table, layer): zeros[ids.., D]}
        self.compute_dtype = compute_dtype
        self.dtype = data.dtype           # duck-typing for dtype probes
        self.shape = data.shape

    def pserver_lookup(self, ids, *, layer: str, pad_to_zero_id=None):
        rows = all_to_all_lookup(self.mesh, self.data, ids, axis=self.axis,
                                 dcn_axis=self.dcn_axis)
        proxy = self.proxies.get((self.name, layer))
        if proxy is not None:
            rows = rows + proxy           # grads flow ONLY through the proxy
        if pad_to_zero_id is not None:
            keep = (ids != pad_to_zero_id)[..., None]
            rows = rows * keep.astype(rows.dtype)
        if self.compute_dtype is not None:
            rows = rows.astype(self.compute_dtype)
        return rows
