"""Sharded row-sparse update — the pserver gradient push over ICI.

Backward leaves each lookup with (ids, row-grads) segments; this module
routes every segment to the shard that owns the row and applies the
optimizer there, without EVER building a [V, D] gradient or optimizer
temp (the contract ``lint --pserver`` gates on the traced jaxpr):

1. each device takes its 1/n slice of the flat (ids, row-grads) stream,
2. buckets both by owning shard (the same stable bucketing as the lookup,
   so duplicate-row accumulation order matches the single-host sorted
   scatter-add bit-for-bit),
3. exchanges id buckets [n, cap] and payload buckets [n, cap, D] with
   ``lax.all_to_all``,
4. the owner dedups its received segments (stable sort + segment sum) and
   gather-update-scatters ONLY the touched rows and their slots through
   ``Optimizer.sparse_apply_rows`` — the same tested kernel the
   single-host ``sparse_rows`` integer-K fast path uses,
5. touched rows also set their bit in the shard's dirty mask, feeding the
   incremental snapshot tier (snapshot.py).

The per-(src, dst) bucket capacity is the slice length — the worst case
(every local segment owned by one shard) still fits, so like the lookup
there is no overflow fallback to densify through.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import compat
from paddle_tpu.pserver.lookup import _bucket_by_key, bucket_by_owner

__all__ = ["sharded_row_update"]


def _push_apply_body(opt, shard, slot_leaves, dirty, ids, rows, lr_eff,
                     step, *, axis: str, n: int, decay: float,
                     slot_treedef):
    """shard_map body: exchange (ids, rows) segments, then row-update the
    local shard.  ``slot_leaves`` are the optimizer slot pytree leaves
    (shard-local for table-shaped leaves)."""
    r = lax.axis_index(axis)
    vs, d = shard.shape
    per = ids.shape[0] // n
    my_ids = lax.dynamic_slice(ids, (r * per,), (per,))
    my_rows = lax.dynamic_slice(rows, (r * per, 0), (per, d))
    sentinel = n * vs
    buckets, order, sowner, bucket_pos = bucket_by_owner(
        my_ids, n, vs, sentinel)
    payload = jnp.zeros((n, per, d), rows.dtype)
    payload = payload.at[sowner, bucket_pos].set(my_rows[order])
    recv_ids = lax.all_to_all(buckets, axis, 0, 0).reshape(-1)
    recv_rows = lax.all_to_all(payload, axis, 0, 0).reshape(-1, d)
    # global -> shard-local row ids; foreign/sentinel entries park OOB and
    # sparse_apply_rows drops them
    local = recv_ids - r * vs
    local = jnp.where((local >= 0) & (local < vs), local, vs)
    slots = jax.tree_util.tree_unflatten(slot_treedef, slot_leaves)
    new_shard, new_slots = opt.sparse_apply_rows(
        shard, local, recv_rows, slots, lr_eff=lr_eff, step=step,
        decay=decay)
    touched = (local < vs) & jnp.any(recv_rows != 0, axis=1)
    safe = jnp.where(touched, local, vs)       # untouched -> OOB, dropped
    new_dirty = dirty.at[safe].set(True, mode="drop")
    return (new_shard, new_dirty,
            *jax.tree_util.tree_leaves(new_slots))


def _push_apply_body2(opt, shard, slot_leaves, dirty, ids, rows, lr_eff,
                      step, *, dcn: str, axis: str, m: int, k: int,
                      decay: float, slot_treedef):
    """Two-level push twin of ``_push_apply_body`` for a multi-pod mesh
    (same routing as ``lookup._a2a2_body``): each (id, row-grad) segment
    hops over ICI to the owner's column, then over DCN to the owner's
    pod — the expensive tier carries each segment once, column-
    aggregated.  A push has no return path, so it is exactly the two
    forward hops."""
    p = lax.axis_index(dcn)
    c = lax.axis_index(axis)
    n = m * k
    g = p * k + c
    vs, d = shard.shape
    per = ids.shape[0] // n
    my_ids = lax.dynamic_slice(ids, (g * per,), (per,))
    my_rows = lax.dynamic_slice(rows, (g * per, 0), (per, d))
    sentinel = n * vs
    og1 = jnp.clip(my_ids // vs, 0, n - 1)
    b1, order1, col1, pos1 = _bucket_by_key(my_ids, og1 % k, k, sentinel)
    p1 = jnp.zeros((k, per, d), rows.dtype)
    p1 = p1.at[col1, pos1].set(my_rows[order1])
    ids1 = lax.all_to_all(b1, axis, 0, 0).reshape(-1)
    rows1 = lax.all_to_all(p1, axis, 0, 0).reshape(-1, d)
    og2 = jnp.clip(ids1 // vs, 0, n - 1)
    b2, order2, pod2, pos2 = _bucket_by_key(ids1, og2 // k, m, sentinel)
    p2 = jnp.zeros((m, k * per, d), rows.dtype)
    p2 = p2.at[pod2, pos2].set(rows1[order2])
    recv_ids = lax.all_to_all(b2, dcn, 0, 0).reshape(-1)
    recv_rows = lax.all_to_all(p2, dcn, 0, 0).reshape(-1, d)
    local = recv_ids - g * vs
    local = jnp.where((local >= 0) & (local < vs), local, vs)
    slots = jax.tree_util.tree_unflatten(slot_treedef, slot_leaves)
    new_shard, new_slots = opt.sparse_apply_rows(
        shard, local, recv_rows, slots, lr_eff=lr_eff, step=step,
        decay=decay)
    touched = (local < vs) & jnp.any(recv_rows != 0, axis=1)
    safe = jnp.where(touched, local, vs)       # untouched -> OOB, dropped
    new_dirty = dirty.at[safe].set(True, mode="drop")
    return (new_shard, new_dirty,
            *jax.tree_util.tree_leaves(new_slots))


def sharded_row_update(mesh, opt, table, slots, dirty, ids, row_grads, *,
                       axis: str = "model", lr_eff, step,
                       decay: float = 0.0,
                       dcn_axis: str = None) -> Tuple[Any, Any, Any]:
    """Apply (ids, row-grads) segments to a sharded table.

    ``table``: [V_pad, D] sharded ``P(axis, None)``; ``slots``: the
    optimizer slot pytree for this table (table-shaped leaves sharded like
    the table); ``dirty``: bool [V_pad] sharded ``P(axis)``; ``ids``
    [N] int (global row ids; sentinels >= V_pad allowed), ``row_grads``
    [N, D].  Returns ``(new_table, new_slots, new_dirty)``.  ``dcn_axis``
    shards the table over ``(dcn_axis, axis)`` jointly and routes each
    segment in two hops — pod-local column, then cross-pod
    (``_push_apply_body2``) — so segments cross DCN at most once.
    """
    m = int(mesh.shape[dcn_axis]) if dcn_axis else 1
    n = int(mesh.shape[axis]) * m
    v_pad, d = table.shape
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    flat_g = row_grads.reshape(-1, d)
    npad = (-flat_ids.shape[0]) % n
    if npad:
        flat_ids = jnp.concatenate(
            [flat_ids, jnp.full((npad,), v_pad, jnp.int32)])
        flat_g = jnp.concatenate(
            [flat_g, jnp.zeros((npad, d), flat_g.dtype)])
    if n == 1:
        new_table, new_slots = opt.sparse_apply_rows(
            table, flat_ids, flat_g, slots, lr_eff=lr_eff, step=step,
            decay=decay)
        touched = (flat_ids < v_pad) & jnp.any(flat_g != 0, axis=1)
        safe = jnp.where(touched, flat_ids, v_pad)
        new_dirty = dirty.at[safe].set(True, mode="drop")
        return new_table, new_slots, new_dirty

    slot_leaves, slot_treedef = jax.tree_util.tree_flatten(slots)
    row_axes = (dcn_axis, axis) if m > 1 else axis
    tbl_spec = P(row_axes, None)
    leaf_specs = tuple(
        tbl_spec if getattr(l, "shape", None) == table.shape else P()
        for l in slot_leaves)
    if m > 1:
        body = functools.partial(
            _push_apply_body2, opt, dcn=dcn_axis, axis=axis, m=m,
            k=n // m, decay=decay, slot_treedef=slot_treedef)
    else:
        body = functools.partial(
            _push_apply_body, opt, axis=axis, n=n, decay=decay,
            slot_treedef=slot_treedef)
    mapped = compat.shard_map(
        body, mesh=mesh,
        in_specs=(tbl_spec, leaf_specs, P(row_axes), P(), P(), P(), P()),
        out_specs=(tbl_spec, P(row_axes)) + leaf_specs,
        check_vma=False)
    out = mapped(table, tuple(slot_leaves), dirty, flat_ids, flat_g,
                 jnp.asarray(lr_eff, table.dtype), jnp.asarray(step))
    new_table, new_dirty = out[0], out[1]
    new_slots = jax.tree_util.tree_unflatten(slot_treedef, out[2:])
    return new_table, new_slots, new_dirty
