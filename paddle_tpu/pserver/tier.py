"""PServerTier — wires sharded tables into the SGDTrainer step.

The reference splits this across SparseRemoteParameterUpdater (prefetch /
push RPCs) and the trainer config (which parameters are remote); here the
split is: ``nn.embedding(..., sparse_grad=True)`` marks a parameter, and a
trainer constructed with a mesh carrying the pserver axis routes every such
parameter through this tier instead of the dense params dict:

- the table is created sharded (never on one host) and REMOVED from the
  trainer's ``params`` pytree — the dense optimizer neither stores nor
  updates it;
- inside the jitted step the topology sees a ``TableProxy`` for that
  parameter (``Topology.apply(param_overrides=...)``): lookups run the
  all-to-all exchange against the live sharded table, and each lookup adds
  a zeros proxy of the request shape;
- the step differentiates w.r.t. the proxies — the cotangents ARE the
  (ids, row-grads) segments — and ``apply_grads`` pushes them through
  ``sharded_row_update``.  Gradients for the table are never
  materialized at [V, D] (gated by ``lint --pserver``);
- optimizer slots for each table live sharded exactly like the table and
  advance only for touched rows (lazy regularization, the
  SparseRowMatrix semantics);
- the whole tier state (tables, slots, dirty masks, step counter) rides
  trainer checkpoints as an ``extra`` pytree, so gang recovery restores a
  lost shard's rows from the manifest like any other state.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.param.optimizers import dedup_rows
from paddle_tpu.pserver.apply import sharded_row_update
from paddle_tpu.pserver.lookup import TableProxy
from paddle_tpu.pserver.table import ShardedTable, TableSpec
from paddle_tpu.utils import FLAGS, logger

__all__ = ["PServerTier", "Route"]


def _repad_rows(arr, vocab: int, v_pad_new: int):
    """Carry a row-dimensioned array across a shard-count change: keep the
    TRUE vocab rows, re-pad the tail with zeros to the new shard multiple.
    Works for [V_pad, D] tables/slots and [V_pad] dirty masks alike; exact
    because pad rows are zeros in every world (ids are always < vocab)."""
    arr = arr[:vocab]
    if v_pad_new > vocab:
        pad = jnp.zeros((v_pad_new - vocab,) + tuple(arr.shape[1:]),
                        arr.dtype)
        arr = jnp.concatenate([arr, pad])
    return arr


class Route(NamedTuple):
    """One embedding layer routed through the tier."""

    layer: str       # embedding layer name
    param: str       # table parameter name
    data: str        # feeding data layer name
    is_seq: bool     # sequence slot (ids [B, T]) vs scalar slot ([B, 1])
    dim: int


def discover_routes(topology) -> List[Route]:
    """Embedding layers whose table parameter is marked sparse_grad."""
    routes = []
    for layer in topology.layers:
        if layer.layer_type != "embedding" or not layer.param_specs:
            continue
        spec = layer.param_specs[0]
        if not spec.attr.sparse_grad:
            continue
        parent = layer.parents[0]
        routes.append(Route(
            layer=layer.name, param=spec.name, data=parent.name,
            is_seq=bool((parent.data_spec or {}).get("is_seq")),
            dim=layer.size))
    return routes


def _feed_ids(feed, route: Route):
    """The EXACT ids the embedding forward will look up for this route —
    mirrors nn/graph._coerce_feed + the embedding forward's [B,1] squeeze,
    so proxy shapes and pushed segments always line up with the lookup."""
    v = feed[route.data]
    value = v[0] if isinstance(v, tuple) else v
    ids = jnp.asarray(value).astype(jnp.int32)
    if not route.is_seq and ids.ndim == 2 and ids.shape[1] == 1:
        ids = ids[:, 0]
    return ids


class PServerTier:
    """Sharded-table store + step-integration hooks for one trainer."""

    def __init__(self, mesh, topology, optimizer, *,
                 axis: Optional[str] = None, pad: Optional[bool] = None,
                 lr_scales: Optional[Dict[str, float]] = None,
                 decays: Optional[Dict[str, float]] = None,
                 seed: Optional[int] = None) -> None:
        from paddle_tpu.parallel.mesh import MeshConfig, as_mesh

        if axis is None and isinstance(mesh, MeshConfig):
            axis = mesh.role_axis("pserver")
        self.mesh = as_mesh(mesh)
        self.axis = axis or FLAGS.pserver_axis
        self.dcn_axis = self._resolve_dcn(mesh, self.axis)
        self.optimizer = optimizer
        self.lr_scales = dict(lr_scales or {})
        self.decays = dict(decays or {})
        # the TRAINER's seed, not the global flag: table init must follow
        # the same reproducibility contract as the dense params
        seed = int(FLAGS.seed) if seed is None else int(seed)
        pad = FLAGS.pserver_pad_vocab if pad is None else pad
        self.routes = discover_routes(topology)
        self.tables: Dict[str, ShardedTable] = {}
        self._slots: Dict[str, Any] = {}
        self._step = jnp.zeros((), jnp.int32)
        by_param: Dict[str, List[Route]] = {}
        for r in self.routes:
            by_param.setdefault(r.param, []).append(r)
        self.routes_by_param = by_param
        for pname, rs in by_param.items():
            spec = topology.param_specs[pname]
            attr = spec.attr
            init = attr.init or "normal"
            if init not in ("normal", "uniform", "zeros"):
                init = "normal"   # xavier etc. have no row-local analog
            tspec = TableSpec(
                name=pname, vocab=spec.shape[0], dim=spec.shape[1],
                init=init,
                initial_std=(attr.initial_std
                             if attr.initial_std is not None else 0.01),
                initial_mean=attr.initial_mean,
                seed=seed,
                # --amp (ROADMAP item 2 follow-up): gathered rows leave the
                # lookup in bf16 — the cast sits AFTER the grad proxy add
                # (lookup.TableProxy), so masters, row gradients, and the
                # row-sparse update path stay f32 and bit-identical
                compute_dtype=("bfloat16" if FLAGS.amp else None))
            table = ShardedTable(tspec, mesh, axis=self.axis, pad=pad,
                                 dcn_axis=self.dcn_axis)
            self.tables[pname] = table
            slots = optimizer.init_leaf(table.data)
            self._slots[pname] = jax.tree_util.tree_map(
                lambda s: jax.device_put(s, table.sharding)
                if getattr(s, "shape", None) == table.data.shape else s,
                slots)
            logger.info("pserver: routed %s (%s) -> %r", pname,
                        ", ".join(r.layer for r in rs), table)

    @staticmethod
    def _resolve_dcn(mesh, axis: str) -> Optional[str]:
        """The dcn axis tables co-shard over, when the world is multi-pod:
        a MeshConfig's binding (or ``--dcn_axis``), present in the mesh,
        larger than 1, and distinct from the pserver axis.  None
        otherwise — a single-pod world keeps the one-hop a2a unchanged."""
        from paddle_tpu.parallel.mesh import MeshConfig

        if isinstance(mesh, MeshConfig):
            name, shape = mesh.dcn_axis, mesh.shape
        else:
            name = FLAGS.dcn_axis or None
            shape = {n: int(mesh.shape[n]) for n in mesh.axis_names}
        if name and name != axis and shape.get(name, 1) > 1:
            return name
        return None

    # ------------------------------------------------------------------
    # step-state plumbing (a plain pytree the jitted step donates)
    # ------------------------------------------------------------------

    @property
    def active(self) -> bool:
        return bool(self.tables)

    def param_names(self):
        return set(self.tables)

    def state(self) -> Dict[str, Any]:
        return {
            "step": self._step,
            "tables": {k: t.data for k, t in self.tables.items()},
            "slots": dict(self._slots),
            "dirty": {k: t.dirty for k, t in self.tables.items()},
        }

    def adopt(self, state: Dict[str, Any]) -> None:
        """Take ownership of a step's output (or a loaded checkpoint's)
        pserver pytree.

        Tolerates a WORLD-SIZE mismatch: a checkpoint taken under a
        different shard count stores tables at a different padded vocab
        ([V_pad_old, D]); the true rows carry over and the tail re-pads to
        this mesh's shard multiple (pad rows are zeros in every world —
        they can never be looked up or updated — so the reshard is
        bit-exact; tests/test_elastic_reshard.py)."""
        self._step = state["step"]
        new_slots: Dict[str, Any] = {}
        for k, t in self.tables.items():
            data = jnp.asarray(state["tables"][k])
            v_in = int(data.shape[0])
            if v_in == t.vocab_padded:
                t.data = data
                t.dirty = state["dirty"][k]
                new_slots[k] = state["slots"][k]
                continue
            logger.info(
                "pserver: resharding table %r from padded vocab %d to %d "
                "(%d shards)", k, v_in, t.vocab_padded, t.shards)
            t.data = _repad_rows(data, t.spec.vocab, t.vocab_padded)
            t.dirty = _repad_rows(
                jnp.asarray(state["dirty"][k], jnp.bool_),
                t.spec.vocab, t.vocab_padded)
            new_slots[k] = jax.tree_util.tree_map(
                lambda s: (_repad_rows(jnp.asarray(s), t.spec.vocab,
                                       t.vocab_padded)
                           if getattr(s, "shape", None) is not None
                           and jnp.ndim(s) >= 1
                           and int(jnp.shape(s)[0]) == v_in else s),
                state["slots"][k])
        self._slots = new_slots

    def resize(self, mesh) -> None:
        """Re-instantiate every table on a NEW mesh (the elastic resize:
        the pserver-axis size — hence shard count and padded vocab — may
        change).  Live rows, dirty bits, and optimizer slots carry over
        via ``_repad_rows``; nothing is re-initialized."""
        from paddle_tpu.parallel.mesh import as_mesh

        state = self.state()
        # re-resolve the pod axis BEFORE as_mesh: a MeshConfig carries the
        # binding; the dcn axis may have shrunk to one pod (or grown back)
        self.dcn_axis = self._resolve_dcn(mesh, self.axis)
        mesh = as_mesh(mesh)
        self.mesh = mesh
        for pname, old in list(self.tables.items()):
            # adopt() below overwrites data/dirty/slots from ``state``
            # (the same repad path a cross-world checkpoint load takes),
            # so hand the constructor the old rows as-is — each table is
            # copied ONCE, not twice, inside the latency-sensitive
            # resize window
            self.tables[pname] = ShardedTable(
                old.spec, mesh, axis=self.axis, data=old.data,
                dirty=None, dcn_axis=self.dcn_axis)
        # adopt() re-pads the carried rows, dirty bits, and slots into the
        # new shard multiple; place() re-pins everything to the new
        # mesh's shardings
        self.adopt(state)
        self.place()

    def place(self) -> None:
        """Re-pin every leaf to its sharding (after checkpoint load)."""
        self._step = jnp.asarray(self._step, jnp.int32)
        for k, t in self.tables.items():
            t.place()
            self._slots[k] = jax.tree_util.tree_map(
                lambda s: jax.device_put(jnp.asarray(s), t.sharding)
                if getattr(s, "shape", None) == tuple(t.data.shape)
                else jnp.asarray(s),
                self._slots[k])

    # ------------------------------------------------------------------
    # inside-the-step hooks (all traced)
    # ------------------------------------------------------------------

    def make_proxies(self, feed) -> Dict[Tuple[str, str], Any]:
        """Zeros of each routed lookup's request shape — the differentiable
        stand-ins whose cotangents are the row gradients."""
        out = {}
        for r in self.routes:
            ids = _feed_ids(feed, r)
            out[(r.param, r.layer)] = jnp.zeros(
                ids.shape + (r.dim,), jnp.float32)
        return out

    def make_overrides(self, tables: Dict[str, Any],
                       proxies: Dict[Tuple[str, str], Any]):
        return {
            name: TableProxy(name, self.mesh, self.axis, tables[name],
                             proxies,
                             compute_dtype=self.tables[name].spec.compute_dtype,
                             dcn_axis=self.dcn_axis)
            for name in self.tables
        }

    @staticmethod
    def _dedup_sq(ids, g):
        """Sum of squares of the PER-ROW (duplicate-summed) gradients —
        the mass the dense scatter-add gradient would contribute to a
        global-norm clip, computed without densifying.  Shares
        ``dedup_rows`` with ``Optimizer.sparse_apply_rows`` so the norm
        and the applied update see bit-identical sums."""
        _, sums = dedup_rows(ids, g, sentinel=jnp.iinfo(jnp.int32).max)
        return jnp.sum(jnp.square(sums.astype(jnp.float32)))

    def grad_norm_sq(self, feed, proxy_grads: Dict[Tuple[str, str], Any]):
        """Global-norm contribution of every routed table's row gradients
        (deduped, matching the dense path's norm) — feeds the trainer's
        joint clip so clipping parity holds with single-host training."""
        total = jnp.zeros((), jnp.float32)
        for pname, routes in self.routes_by_param.items():
            ids = jnp.concatenate(
                [_feed_ids(feed, r).reshape(-1) for r in routes])
            g = jnp.concatenate(
                [proxy_grads[(pname, r.layer)].reshape(-1, r.dim)
                 for r in routes])
            total = total + self._dedup_sq(ids, g)
        return total

    def apply_grads(self, state: Dict[str, Any], feed,
                    proxy_grads: Dict[Tuple[str, str], Any]):
        """Push the proxy cotangents into the sharded tables; returns the
        next pserver state pytree.  Pure/traced — called inside the jitted
        step (and inside the bad-step guard's cond, so a non-finite step
        holds tables, slots, and dirty masks unchanged)."""
        step = state["step"] + 1
        lr = self.optimizer.lr_at(step)
        new_tables, new_slots, new_dirty = {}, {}, {}
        for pname, routes in self.routes_by_param.items():
            segs_ids, segs_g = [], []
            for r in routes:
                ids = _feed_ids(feed, r).reshape(-1)
                g = proxy_grads[(pname, r.layer)].reshape(-1, r.dim)
                segs_ids.append(ids)
                segs_g.append(g)
            ids = jnp.concatenate(segs_ids)
            g = jnp.concatenate(segs_g)
            scale = self.lr_scales.get(pname, 1.0)
            decay = self.decays.get(pname, 0.0) + self.optimizer.l2_rate
            new_tables[pname], new_slots[pname], new_dirty[pname] = (
                sharded_row_update(
                    self.mesh, self.optimizer, state["tables"][pname],
                    state["slots"][pname], state["dirty"][pname], ids, g,
                    axis=self.axis, lr_eff=lr * scale, step=step,
                    decay=decay, dcn_axis=self.dcn_axis))
        return {"step": step, "tables": new_tables, "slots": new_slots,
                "dirty": new_dirty}

    # ------------------------------------------------------------------
    # snapshots (serving read path)
    # ------------------------------------------------------------------

    def snapshot(self, save_dir: str, *, reset_dirty: bool = True
                 ) -> Dict[str, str]:
        """Write one incremental snapshot per table under
        ``save_dir/<table>/snap-xxxxx`` (only rows dirty since the last
        snapshot) and clear the dirty masks.  Returns {table: snap_dir}."""
        import os
        import shutil

        from paddle_tpu.pserver.snapshot import (SnapshotError,
                                                 latest_snapshot,
                                                 save_table_snapshot,
                                                 validate_snapshot)

        out = {}
        for pname, t in self.tables.items():
            d = os.path.join(save_dir, pname.strip("_"))
            snap_id = latest_snapshot(d, validate=False) + 1
            out[pname] = save_table_snapshot(
                d, t.spec, t.data, t.dirty, snap_id, shards=t.shards)
            # clear dirty bits only once the published snapshot verifies:
            # rows whose delta never became durable must stay dirty so the
            # NEXT snapshot rewrites them
            reason = validate_snapshot(out[pname])
            if reason is not None:
                # the invalid dir must not keep its chain position, or the
                # retry would publish PAST it where no valid-prefix reader
                # can ever reach — drop it so the next attempt reuses the id
                shutil.rmtree(out[pname], ignore_errors=True)
                raise SnapshotError(
                    f"table {pname!r}: snapshot {out[pname]} failed "
                    f"post-write validation: {reason}")
            if reset_dirty:
                t.dirty = jax.device_put(
                    jnp.zeros_like(t.dirty), t.mask_sharding)
        return out
