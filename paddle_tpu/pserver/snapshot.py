"""Incremental per-shard table snapshots — the serving read path.

A 100M-row table must export WITHOUT a full dump.  Exploiting the
deterministic per-shard init (table.init_shard_rows): the base of every
snapshot chain is the re-derivable init, and each ``snap-%05d`` directory
stores only the rows DIRTY since the previous snapshot, one npz per shard
(``shard-%03d.npz``: global row ``ids`` + row ``values``).  A reader
reconstructs any point of the chain as

    re-init from the manifest's TableSpec  +  replay snaps 0..k in order

touching only the dirty rows of each delta.  Durability matches the
resilience checkpoints (same discipline as resilience/checkpoint_io): the
write lands in a dot-prefixed temp dir, every file is fsynced, one atomic
rename publishes, and the manifest records a CRC32 per stored array — a
corrupted shard file raises the typed ``SnapshotError`` naming the failing
member, and the chain loader falls back to the newest snapshot that still
validates.

``TableReader`` is the serving-side consumer: it holds the reconstructed
host table and ``hot_reload()`` applies only snapshots newer than what it
already has — the rows a serving replica rewrites per reload are exactly
the rows training touched since, not V.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import time
import uuid
import zlib
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.resilience.checkpoint_io import (_fsync_dir, _fsync_file,
                                                 npz_safe)
from paddle_tpu.resilience.errors import CheckpointError
from paddle_tpu.pserver.table import TableSpec, init_shard_rows
from paddle_tpu.utils import logger

__all__ = ["SnapshotError", "ReloadStopped", "save_table_snapshot",
           "validate_snapshot", "quarantine_snapshot", "latest_snapshot",
           "load_table_host", "TableReader", "snap_dir"]

SNAPSHOT_VERSION = 1

_SNAP_RE = re.compile(r"snap-(\d{5,})")
_TMP_PREFIX = ".tmp-"


class SnapshotError(CheckpointError):
    """A table snapshot failed validation (missing/corrupt member)."""


def snap_dir(save_dir: str, snap_id: int) -> str:
    return os.path.join(save_dir, f"snap-{snap_id:05d}")


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def save_table_snapshot(save_dir: str, spec: TableSpec, data, dirty,
                        snap_id: int, *, shards: int) -> str:
    """Write ``snap-%05d`` atomically: per shard, ONLY the rows whose dirty
    bit is set.  ``data`` [V_pad, D] (sharded or host), ``dirty`` bool
    [V_pad].  Returns the published directory."""
    os.makedirs(save_dir, exist_ok=True)
    v_pad = int(data.shape[0])
    vs = v_pad // shards
    dirty_host = np.asarray(dirty)
    final = snap_dir(save_dir, snap_id)
    tmp = os.path.join(
        save_dir, f"{_TMP_PREFIX}snap-{snap_id:05d}-{uuid.uuid4().hex[:8]}")
    os.makedirs(tmp)
    try:
        files: Dict[str, Dict] = {}
        total = 0
        for s in range(shards):
            ids_local = np.flatnonzero(dirty_host[s * vs:(s + 1) * vs])
            ids_global = (ids_local + s * vs).astype(np.int64)
            # device-side gather: only the [k, D] payload crosses the link
            rows = npz_safe(jnp.take(data, jnp.asarray(ids_global), axis=0)
                            if ids_global.size else
                            np.zeros((0, int(data.shape[1]))))
            rows = np.asarray(rows)
            fname = f"shard-{s:03d}.npz"
            fpath = os.path.join(tmp, fname)
            np.savez_compressed(fpath, ids=ids_global, rows=rows)
            _fsync_file(fpath)
            # fp64: the SDC-grade 64-bit fold (resilience/integrity.py)
            # alongside the CRCs — an independent second detector, so an
            # at-rest scrub's miss probability is ~2^-96, and the same
            # digest family the trainer's cross-replica agreement check
            # uses covers shard snapshots too
            from paddle_tpu.resilience.integrity import (fingerprint_int,
                                                         np_tree_fingerprint)

            files[fname] = {
                "rows": int(ids_global.size),
                "crc_ids": _crc(ids_global),
                "crc_rows": _crc(rows),
                "fp64": fingerprint_int(np_tree_fingerprint(
                    {"ids": ids_global, "rows": rows})),
            }
            total += int(ids_global.size)
        manifest = {
            "version": SNAPSHOT_VERSION,
            "snap_id": snap_id,
            "spec": spec.to_json(),
            "shards": shards,
            "vocab_padded": v_pad,
            "dirty_rows": total,
            "files": files,
            "time": time.time(),
        }
        mpath = os.path.join(tmp, "manifest.json")
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        os.replace(tmp, final)
        _fsync_dir(save_dir)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    logger.info("pserver snapshot %s: %d dirty row(s) over %d shard(s)",
                final, total, shards)
    # a published snapshot is a durability anchor like a checkpoint
    # commit: fsync'd into the causal timeline (no-op without
    # --obs_journal; docs/observability.md)
    from paddle_tpu.obs import journal_event

    journal_event("pserver_snapshot", fsync=True, snap_id=snap_id,
                  table=spec.name, dirty_rows=total, shards=shards)
    return final


def read_snapshot_manifest(d: str) -> Dict:
    with open(os.path.join(d, "manifest.json")) as f:
        return json.load(f)


def validate_snapshot(d: str) -> Optional[str]:
    """None when the snapshot is loadable, else the human-readable reason
    (the string a raised SnapshotError carries)."""
    if not os.path.isdir(d):
        return "not a directory"
    from paddle_tpu.resilience.checkpoint_io import quarantine_reason

    q = quarantine_reason(d)
    if q is not None:
        return q
    try:
        manifest = read_snapshot_manifest(d)
    except FileNotFoundError:
        return "missing manifest.json"
    except (json.JSONDecodeError, OSError) as e:
        return f"unreadable manifest.json: {e}"
    for fname, info in manifest.get("files", {}).items():
        fpath = os.path.join(d, fname)
        if not os.path.exists(fpath):
            return f"missing {fname}"
        try:
            data = np.load(fpath, allow_pickle=False)
            ids, rows = data["ids"], data["rows"]
        except Exception as e:
            return f"{fname} unreadable: {type(e).__name__}: {e}"
        if _crc(ids) != info.get("crc_ids"):
            return f"{fname}:ids CRC mismatch"
        if _crc(rows) != info.get("crc_rows"):
            return f"{fname}:rows CRC mismatch"
        if "fp64" in info:
            from paddle_tpu.resilience.integrity import (
                fingerprint_int, np_tree_fingerprint)

            got = fingerprint_int(np_tree_fingerprint(
                {"ids": np.asarray(ids), "rows": np.asarray(rows)}))
            if got != info["fp64"]:
                return (f"{fname}:rows fp64 mismatch "
                        f"({got:#018x} != {info['fp64']:#018x})")
    return None


def quarantine_snapshot(d: str, reason: str) -> None:
    """Scrubber hook (resilience/integrity.py): refuse this snapshot from
    now on — ``validate_snapshot`` fails it, so ``latest_snapshot`` /
    ``valid_chain_tip`` demote the chain to its predecessor — while the
    payload stays on disk for forensics.  Shares the checkpoint tier's
    marker protocol (one write path, one read path)."""
    from paddle_tpu.resilience.checkpoint_io import quarantine_checkpoint

    quarantine_checkpoint(d, reason)


def valid_chain_tip(save_dir: str) -> int:
    """Highest snap id reachable through an UNBROKEN valid chain from
    snap 0 (reconstruction replays every delta in order, so a corrupt or
    missing middle snapshot caps the usable tip at its predecessor), or
    -1.  This is the fallback contract: one damaged snapshot costs you
    the deltas from it onward, never the whole table."""
    sid = -1
    k = 0
    while True:
        d = snap_dir(save_dir, k)
        if not os.path.isdir(d):
            break
        reason = validate_snapshot(d)
        if reason is not None:
            logger.warning("table snapshot chain stops at %s: %s", d, reason)
            break
        sid = k
        k += 1
    return sid


def latest_snapshot(save_dir: str, *, validate: bool = True) -> int:
    """Highest snap id under ``save_dir`` (validated unless told not to),
    or -1.  Corrupt snapshots are logged and skipped — the fallback the
    acceptance contract requires."""
    if not os.path.isdir(save_dir):
        return -1
    ids = [int(m.group(1)) for m in
           (_SNAP_RE.fullmatch(n) for n in os.listdir(save_dir)) if m]
    for sid in sorted(ids, reverse=True):
        if not validate:
            return sid
        reason = validate_snapshot(snap_dir(save_dir, sid))
        if reason is None:
            return sid
        logger.warning("skipping corrupt table snapshot %s: %s",
                       snap_dir(save_dir, sid), reason)
    return -1


def _apply_snap(table: np.ndarray, d: str) -> int:
    """Replay one snapshot's dirty rows into ``table``; validates CRCs and
    raises the typed error on damage.  Returns rows replayed."""
    reason = validate_snapshot(d)
    if reason is not None:
        raise SnapshotError(f"table snapshot {d} failed validation: {reason}")
    manifest = read_snapshot_manifest(d)
    n = 0
    for fname in sorted(manifest.get("files", {})):
        data = np.load(os.path.join(d, fname), allow_pickle=False)
        ids, rows = data["ids"], data["rows"]
        if ids.size:
            # a chain may span WORLD SIZES (elastic resize changes the
            # shard count, hence the padded vocab): true-row ids are
            # world-independent, but an id past this table's rows means
            # the chain and the spec genuinely disagree — typed error,
            # never a silent wrap/scatter
            if int(ids.max()) >= table.shape[0]:
                raise SnapshotError(
                    f"table snapshot {d}: {fname} carries row id "
                    f"{int(ids.max())} beyond the table's {table.shape[0]} "
                    "rows (spec/chain mismatch)")
            table[ids] = rows.astype(table.dtype)
            n += int(ids.size)
    return n


def _reinit_host(spec: TableSpec, shards: int, v_pad: int) -> np.ndarray:
    """Re-derive the initial table on the host, shard by shard — the same
    bits the device-side per-shard init produced."""
    vs = v_pad // shards
    return np.concatenate(
        [np.asarray(init_shard_rows(spec, s, vs)) for s in range(shards)],
        axis=0)


def load_table_host(save_dir: str, *, upto: Optional[int] = None
                    ) -> Tuple[TableSpec, np.ndarray, int]:
    """Reconstruct the host table: re-init from the manifest's spec, then
    replay every snapshot in chain order.  Returns
    ``(spec, table [V_pad, D], snap_id)``.

    Without ``upto``, the tip is the end of the longest VALID chain
    prefix (``valid_chain_tip``): a damaged snapshot — tip or middle —
    falls back to its predecessor instead of making the table
    unreconstructable.  With ``upto`` given explicitly, a corrupt member
    anywhere in the requested chain raises the typed ``SnapshotError``."""
    sid = valid_chain_tip(save_dir) if upto is None else int(upto)
    if sid < 0:
        raise SnapshotError(f"no valid table snapshot under {save_dir!r}")
    newest = read_snapshot_manifest(snap_dir(save_dir, sid))
    spec = TableSpec.from_json(newest["spec"])
    v_pad = int(newest["vocab_padded"])
    shards = int(newest["shards"])
    table = _reinit_host(spec, shards, v_pad)
    for k in range(sid + 1):
        d = snap_dir(save_dir, k)
        if not os.path.isdir(d):
            raise SnapshotError(
                f"table snapshot chain broken: missing {d} (needed to "
                f"reconstruct snap {sid})")
        _apply_snap(table, d)
    return spec, table, sid


@dataclasses.dataclass(frozen=True)
class ReloadStopped:
    """Typed record of a hot-reload that could not reach the newest
    snapshot: ``snap`` it stopped at, the failing ``member`` inside it
    (best-effort, same extraction as checkpoint fsck), and the full
    validation ``reason``.  Held on ``TableReader.last_stop`` so reload
    probation logic (serving/reload.py) can see a stalled table without
    parsing log lines."""

    snap: int
    member: str
    reason: str

    def __str__(self) -> str:
        member = f" ({self.member})" if self.member else ""
        return f"snap {self.snap}{member}: {self.reason}"


class TableReader:
    """Serving-side hot-reloadable view of one snapshotted table."""

    def __init__(self, save_dir: str) -> None:
        self.save_dir = save_dir
        self.spec, self.table, self.version = load_table_host(save_dir)
        self.rows_replayed = 0
        #: typed record of the last stopped reload, or None after a clean
        #: one — the accessor hot-swap probation keys off
        self.last_stop: Optional[ReloadStopped] = None

    def hot_reload(self) -> int:
        """Apply snapshots newer than the loaded version; returns rows
        replayed.  A corrupt NEW snapshot leaves the reader on its current
        (previous-snapshot) view and records the typed stop — serving
        keeps answering from the last good table, and ``last_stop`` tells
        the probation logic WHICH snap and member stalled it (journaled as
        ``snapshot_reload_stopped``, counted as
        ``pserver_reload_stopped_total``)."""
        from paddle_tpu.obs import get_registry, journal_event
        from paddle_tpu.resilience.checkpoint_io import failing_member

        newest = latest_snapshot(self.save_dir, validate=False)
        replayed = 0
        self.last_stop = None
        for k in range(self.version + 1, newest + 1):
            try:
                replayed += _apply_snap(self.table, snap_dir(self.save_dir, k))
            except SnapshotError as e:
                reason = str(e)
                member = failing_member(
                    reason.split("failed validation: ", 1)[-1])
                self.last_stop = ReloadStopped(snap=k, member=member,
                                               reason=reason)
                journal_event("snapshot_reload_stopped",
                              table=self.spec.name, snap=k, member=member,
                              reason=reason)
                get_registry().counter(
                    "pserver_reload_stopped_total",
                    "table hot-reloads stopped by a corrupt snapshot",
                    labels=("table",), table=self.spec.name).inc()
                logger.warning("hot_reload stopped at %s", self.last_stop)
                break
            self.version = k
        self.rows_replayed += replayed
        return replayed

    def lookup(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.spec.vocab):
            raise SnapshotError(
                f"table {self.spec.name!r}: lookup id out of range "
                f"[0, {self.spec.vocab})")
        return self.table[ids]

    def healthz(self) -> dict:
        return {"table": self.spec.name, "version": self.version,
                "vocab": self.spec.vocab, "dim": self.spec.dim,
                "rows_replayed": self.rows_replayed,
                "last_stop": str(self.last_stop) if self.last_stop else None}
