"""``python -m paddle_tpu lint --pserver`` — the tier's CI gate.

Traces the compiled all-to-all lookup and the sharded sparse-apply
closures at a compact flagship-shaped config and audits them with the
jaxpr auditor's serving check set (host transfers, constant bloat, Pallas
tiles), PLUS the tier-specific "never densify" assertion
(``analysis.audit_no_dense_rows``): no ``[V, D]``-shaped gradient or
optimizer temp may appear in the sparse-apply jaxpr, and no broadcast may
conjure a per-shard dense buffer.  The shapes are chosen so every legal
buffer size (requests N, per-shard rows Vs, bucket capacity) differs from
the vocab dims the gate scans for.
"""

from __future__ import annotations

from typing import List, Optional

from paddle_tpu.analysis.findings import Finding

__all__ = ["audit_pserver"]

_DEFAULTS = (4096, 32, 256, 4)   # V, D, N, shards


def _mesh(shards: int):
    """A shards-wide 1D mesh on real devices when available, else an
    abstract mesh (tracing needs axis sizes, not silicon)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) >= shards:
        return Mesh(np.asarray(devs[:shards]).reshape(shards), ("model",))
    from jax.sharding import AbstractMesh

    try:
        return AbstractMesh((("model", shards),))
    except TypeError:  # newer signature: (shape_tuple, axis_names)
        return AbstractMesh((shards,), ("model",))


def audit_pserver(spec: str = "") -> List[Finding]:
    """``spec``: 'V,D,N,S' comma ints (defaults 4096,32,256,4)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.jaxpr_audit import (DECODE_CHECKS, audit_jaxpr,
                                                 audit_no_dense_rows)
    from paddle_tpu.param.optimizers import Adam
    from paddle_tpu.pserver.apply import sharded_row_update
    from paddle_tpu.pserver.lookup import all_to_all_lookup
    from paddle_tpu.pserver.table import pad_vocab

    try:
        dims = [int(x) for x in spec.split(",")] if spec else []
    except ValueError:
        return [Finding(
            check="pserver-build", severity="ERROR", file="--pserver",
            message=f"malformed --pserver spec {spec!r}: expected up to "
                    f"four comma-separated ints 'V,D,N,S'")]
    v, d, n_req, shards = (dims + list(_DEFAULTS)[len(dims):])[:4]
    v_pad = pad_vocab(v, shards)
    vs = v_pad // shards
    # every leading dim of a buffer the closures legitimately materialize:
    # the full/padded id list, the [S, per(, D)] exchange buckets, and the
    # pad-tail concat — none may collide with a vocab dim or the
    # dense-temp scan is ambiguous (a clean build would be flagged)
    npad = (-n_req) % shards
    n_tot = n_req + npad
    per = n_tot // shards
    fixed_dims = {n_req, n_tot, shards, per} | ({npad} if npad else set())
    if fixed_dims & {v, v_pad, vs}:
        return [Finding(
            check="pserver-build", severity="ERROR", file="--pserver",
            message=f"--pserver spec N={n_req},S={shards} collides with a "
                    f"vocab dim (V={v}, V_pad={v_pad}, Vs={vs}): buffer "
                    f"dims {sorted(fixed_dims)} must avoid vocab dims — "
                    f"the dense-temp scan would be ambiguous; pick a "
                    f"different N or S")]
    try:
        mesh = _mesh(shards)
    except Exception as e:
        return [Finding(
            check="pserver-build", severity="ERROR", file="--pserver",
            message=f"cannot build a {shards}-shard mesh: "
                    f"{type(e).__name__}: {e}")]

    opt = Adam(learning_rate=1e-3)
    table = jax.ShapeDtypeStruct((v_pad, d), jnp.float32)
    slots = (jax.ShapeDtypeStruct((v_pad, d), jnp.float32),
             jax.ShapeDtypeStruct((v_pad, d), jnp.float32))
    dirty = jax.ShapeDtypeStruct((v_pad,), jnp.bool_)
    ids = jax.ShapeDtypeStruct((n_req,), jnp.int32)
    grads = jax.ShapeDtypeStruct((n_req, d), jnp.float32)
    step = jax.ShapeDtypeStruct((), jnp.int32)

    findings: List[Finding] = []

    def lookup_fn(t, i):
        return all_to_all_lookup(mesh, t, i, axis="model")

    def apply_fn(t, s, dt, i, g, st):
        return sharded_row_update(
            mesh, opt, t, s, dt, i, g, axis="model",
            lr_eff=opt.lr_at(st + 1), step=st + 1, decay=1e-4)

    try:
        closed = jax.make_jaxpr(lookup_fn)(table, ids)
        findings.extend(audit_jaxpr(closed, label="pserver:lookup",
                                    checks=DECODE_CHECKS))
    except Exception as e:
        findings.append(Finding(
            check="pserver-build", severity="ERROR", file="pserver[lookup]",
            message=f"lookup closure failed to trace: "
                    f"{type(e).__name__}: {e}"))
    try:
        closed = jax.make_jaxpr(apply_fn)(table, slots, dirty, ids, grads,
                                          step)
        findings.extend(audit_jaxpr(closed, label="pserver:apply",
                                    checks=DECODE_CHECKS))
        # the "never densify" gate proper: Vs-leading temps may only be
        # transforms of the donated table/slot buffers, and NOTHING may
        # carry the global vocab dim
        findings.extend(audit_no_dense_rows(
            closed, full_rows=v_pad, shard_rows=vs, label="pserver:apply"))
        if v != v_pad:
            findings.extend(audit_no_dense_rows(
                closed, full_rows=v, label="pserver:apply"))
    except Exception as e:
        findings.append(Finding(
            check="pserver-build", severity="ERROR", file="pserver[apply]",
            message=f"sparse-apply closure failed to trace: "
                    f"{type(e).__name__}: {e}"))
    return findings
