"""paddle_tpu.pserver — the parameter-server tier redone as TPU-native SPMD.

Reference lineage: ``paddle/pserver`` holds huge embedding tables row-sharded
across nodes; trainers prefetch only the rows a batch touches and push sparse
row gradients back over sockets (``SparseRowMatrix``,
``SparseRemoteParameterUpdater``, ``MultiGradientMachine``).  Here the same
contract rides the mesh instead of a TCP fabric:

- tables live **row-sharded across a mesh axis** and never materialize on one
  host (``table.ShardedTable``; vocab padded to a shard multiple with masked
  tail rows, per-shard deterministic RNG init);
- the prefetch is an **all-to-all lookup** under shard_map (``lookup``): ids
  are bucketed by owning shard on-device, exchanged with a fixed-capacity
  all-to-all, gathered locally, and returned to the requesting rows — one
  balanced exchange instead of the psum-of-zeros broadcast that did
  O(shards) redundant work;
- the gradient push is a **row-sparse optimizer update that never
  densifies** (``apply.sharded_row_update`` over
  ``Optimizer.sparse_apply_rows``): backward keeps (ids, row-grads)
  segments, each shard receives only the segments it owns and
  scatter-updates only the touched rows and their optimizer slots;
- the serving read path is **incremental per-shard snapshots**
  (``snapshot``): only rows dirty since the last snapshot are written,
  CRC-manifested like resilience checkpoints, and a ``TableReader``
  hot-reloads deltas into a serving process without a full dump;
- a lost shard is just a rank failure: tables checkpoint with the trainer
  state, so the PR-4 gang supervisor restores them from the manifest and
  training replays the dirty rows (tests/test_pserver_gang.py).

Trainer entry point: ``nn.embedding(..., sparse_grad=True)`` routes through
this tier automatically when the trainer has a mesh with the pserver axis
(``--pserver_axis``, default 'model').  docs/pserver.md has the full map.
"""

from paddle_tpu.pserver.table import (TableSpec, ShardedTable, pad_vocab,
                                      init_shard_rows)
from paddle_tpu.pserver.lookup import all_to_all_lookup, TableProxy
from paddle_tpu.pserver.apply import sharded_row_update
from paddle_tpu.pserver.tier import PServerTier
from paddle_tpu.pserver.snapshot import (SnapshotError, TableReader,
                                         latest_snapshot, load_table_host,
                                         save_table_snapshot,
                                         validate_snapshot)
from paddle_tpu.pserver.audit import audit_pserver

__all__ = [
    "TableSpec", "ShardedTable", "pad_vocab", "init_shard_rows",
    "all_to_all_lookup", "TableProxy", "sharded_row_update", "PServerTier",
    "SnapshotError", "TableReader", "latest_snapshot", "load_table_host",
    "save_table_snapshot", "validate_snapshot", "audit_pserver",
]
