"""``python -m paddle_tpu lint`` — the CLI front of the analysis subsystem.

Usage:

    python -m paddle_tpu lint --path paddle_tpu --format json
    python -m paddle_tpu lint --config demo/mnist/conf.py --fail-on WARN
    python -m paddle_tpu lint --config conf.py --allowlist .tpu-lint-allow
    python -m paddle_tpu lint --decode B,S,K,L
    python -m paddle_tpu lint --serve model.ptz
    python -m paddle_tpu lint --deploy model.ptz
    python -m paddle_tpu lint --pserver V,D,N,S
    python -m paddle_tpu lint --obs
    python -m paddle_tpu lint --race --protocol --hbm
    python -m paddle_tpu lint --all --format sarif

``--path DIR`` runs the AST trace-safety linter over the tree;
``--config CONF.py`` additionally builds the config's trainer and audits
the closed jaxpr of its train step (the jaxpr auditor).  Both may repeat.
With neither, the installed ``paddle_tpu`` package itself is linted.

``--serve BUNDLE.ptz`` is the serving preflight: the bundle's inference
closure is audited with the serving check set (host transfers on the
request path, >1 MiB folded constants — weights must ride as arguments,
not baked into the executable), the same gate
``InferenceServer.start(preflight=True)`` applies before reporting ready.
A ``--serve`` run ALSO audits the continuous-batching ``decode_step``
closure (the slot-table fused step, serving/slots.py) with the decode
check set — a host transfer there fires once per token per resident
request, the same contract as ``audit_decode``; both readout variants
are traced (the kernel in interpret mode off-TPU).

``--deploy BUNDLE.ptz`` extends the offline preflight to QUANTIZED
bundles (docs/deploy.md): the dequantized forward — and, for int8
bundles, the in-trace-dequantize closure — is audited for
dtype-promotion and constant-bloat; an int8 table accidentally
materialized as f32 constants is exactly the constant-bloat check's job.

``--pserver [V,D,N,S]`` audits the sharded-embedding tier's compiled
all-to-all lookup and row-sparse apply closures (paddle_tpu/pserver) with
the serving check set, and additionally asserts the "never densify"
contract: no ``[V, D]``-shaped gradient or optimizer temp may appear in
the sparse-apply jaxpr, and no broadcast may conjure a per-shard dense
buffer (``analysis.audit_no_dense_rows``).

``--obs`` gates the telemetry contract (docs/observability.md): the
trainer's jitted step is traced with the step timeline / MFU plumbing
enabled, audited for host transfers and constant bloat (the
``audit_decode`` contract), and diffed equation-for-equation against the
telemetry-disabled trace — instrumentation must live in host-side Python
around the existing per-batch sync, never inside the compiled program.
The same gate covers request tracing (obs/trace.py): the train step AND
the continuous-batching ``decode_step`` are re-traced with tracing armed
(``--obs_journal`` + ``--trace_sample``) and must be equation-identical
to tracing-off — spans add ZERO compiled equations.

``--decode [B,S,K,L]`` audits the compiled decode closure of the flagship
generation path (Seq2SeqAttention.beam_search over the fused decode
engine, ops/decode.py) with the decode check set — host transfers inside
the token loop, >1 MiB folded constants, and the tile alignment of the
vocab-tiled top-k readout kernel's BlockSpecs.  Both the kernel and the
XLA-fallback variants are traced (the kernel in interpret mode off-TPU),
so a serving regression fails lint on any backend.

``--race [FILE]`` runs the host-concurrency lock-discipline checker over
the known concurrent classes (serving, feeder prefetch, obs registries,
the gang cluster): the guard lock of each mutable attribute is inferred
from ``with self._lock:`` usage, and any read/write reachable from a
cross-thread entry point outside the guard is flagged — intentionally
lock-free fields carry ``# tpu-lint: guarded-by=none - <invariant>``
annotations.  Lock-order inversions across classes are ERRORs.

``--protocol [FILE]`` runs the gang collective/barrier protocol checker
over trainer + cluster + checkpoint_io + integrity: on a rank-conditional
branch both sides must reach the SAME collectives in the SAME order (the
read-first-grow deadlock shape), and an except handler may not swallow or
exit past a collective its peers still block on.

``--hbm`` runs the static HBM audit over the real compiled train and
decode steps: peak-live-bytes (liveness walk, donation credited) vs the
chip HBM table, donated-buffer-use-after-donation, and f64/weak-type
constants that defeat the compile-cache key.

``--all`` runs every registered pass (tree lint + decode + pserver + obs
+ amp + sdc + race + protocol + hbm + the slot-step audit).

Exit status (uniform across every pass — docs/lint.md has the matrix):
0 = ran clean, 1 = findings at/above ``--fail-on`` (default ERROR)
survive suppression, 2 = usage error (unknown flag, unreadable
allowlist).  ``--fail-on NEVER`` always exits 0 after a successful run.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from paddle_tpu.analysis.findings import (Finding, apply_allowlist,
                                          format_findings, load_allowlist,
                                          severity_at_least)

__all__ = ["run"]


def _audit_config(conf_path: str) -> List[Finding]:
    """Build the config's trainer and audit its step jaxpr; AST-lint the
    config source as well (configs are user code running under trace)."""
    from paddle_tpu.__main__ import _build_trainer, _first_feed, _load_config
    from paddle_tpu.analysis.ast_lint import lint_file

    findings = lint_file(conf_path)
    try:
        conf = _load_config(conf_path)
        trainer = _build_trainer(conf)
        feed = _first_feed(conf)
    except Exception as e:
        findings.append(Finding(
            check="config-build", severity="ERROR", file=conf_path,
            message=f"config failed to build a trainer: "
                    f"{type(e).__name__}: {e}"))
        return findings
    label = os.path.basename(conf_path)
    try:
        findings.extend(trainer.audit(feed, label=f"{label}:train_step"))
    except Exception as e:  # a step that fails to TRACE is itself a finding
        findings.append(Finding(
            check="config-build", severity="ERROR", file=conf_path,
            message=f"train step failed to trace for auditing: "
                    f"{type(e).__name__}: {e}"))
    return findings


def _audit_decode_closure(spec: str) -> List[Finding]:
    """Trace the flagship decode at a compact flagship-shaped model
    (lane-aligned dims, tiled vocab — structure, not perf) and audit both
    readout variants.  ``spec``: 'B,S,K,L' (defaults 8,8,4,8 — B*K=32
    keeps the kernel variant inside its sublane-aligned row gate)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.jaxpr_audit import audit_decode
    from paddle_tpu.models import Seq2SeqAttention

    from paddle_tpu.ops.decode import _forced_kernel_config

    try:
        dims = [int(x) for x in spec.split(",")] if spec else []
    except ValueError:
        return [Finding(
            check="decode-build", severity="ERROR", file="--decode",
            message=f"malformed --decode spec {spec!r}: expected up to four "
                    f"comma-separated ints 'B,S,K,L'")]
    B, S, K, L = (dims + [8, 8, 4, 8][len(dims):])[:4]
    m = Seq2SeqAttention(src_vocab=1024, trg_vocab=1024, emb_dim=128,
                         enc_dim=128, dec_dim=128, att_dim=128)
    params = m.init(jax.random.PRNGKey(0))
    src = jnp.zeros((B, S), jnp.int32)
    src_len = jnp.full((B,), S, jnp.int32)
    findings: List[Finding] = []
    variants = [(False, "xla_topk")]
    if _forced_kernel_config(B * K, m.dec_dim, m.trg_vocab, K) is not None:
        variants.insert(0, (True, "kernel"))
    else:
        findings.append(Finding(
            check="decode-build", severity="INFO", file="decode[kernel]",
            message=f"kernel variant gated at B*K={B * K}, k={K} (needs a "
                    f"sublane-aligned row block and k<=16) — audited the "
                    f"XLA fallback only"))
    for use_kernel, tag in variants:
        try:
            findings.extend(audit_decode(
                lambda p, s, l, uk=use_kernel: m.beam_search(
                    p, s, l, beam_size=K, max_len=L, use_kernel=uk),
                params, src, src_len, label=f"decode[{tag}]:beam{K}"))
        except Exception as e:  # a decode that fails to TRACE is a finding
            findings.append(Finding(
                check="decode-build", severity="ERROR",
                file=f"decode[{tag}]",
                message=f"decode closure failed to trace: "
                        f"{type(e).__name__}: {e}"))
    return findings


def _audit_serving_bundle(bundle: str) -> List[Finding]:
    """``lint --serve BUNDLE.ptz``: load the deploy bundle and trace its
    serving closure through the auditor's host-transfer/constant-bloat
    checks — the same preflight ``InferenceServer.start(preflight=True)``
    runs before reporting ready (fail-fast, like ``v2.infer(audit=True)``).
    Bundle-integrity failures (BundleCorruptError) are findings too: a
    corrupt artifact must fail lint, not crash it."""
    try:
        from paddle_tpu.config.deploy import load_inference_model

        model = load_inference_model(bundle)
    except Exception as e:
        return [Finding(
            check="serve-build", severity="ERROR", file=bundle,
            message=f"bundle failed to load: {type(e).__name__}: {e}")]
    try:
        from paddle_tpu.serving.preflight import audit_serving

        return audit_serving(model, label=f"serve:{os.path.basename(bundle)}")
    except Exception as e:  # a closure that fails to TRACE is a finding
        return [Finding(
            check="serve-build", severity="ERROR", file=bundle,
            message=f"serving closure failed to trace: "
                    f"{type(e).__name__}: {e}")]


def _audit_serving_fleet(bundles: List[str]) -> List[Finding]:
    """``lint --serve A.ptz --serve B.ptz ...`` with SEVERAL bundles:
    the fleet preflight.  The bundles are loaded into a model table
    exactly as ``ModelFleet`` would serve them (one entry per bundle,
    servers never started) and ``ModelFleet.audit()`` traces the
    compiled serving closure of EVERY entry — each finding labeled
    ``fleet:<name>@v<version>``, so one bad entry in a fleet rollout
    is named, not averaged away.  A bundle that fails to load is an
    ERROR finding, and the remaining entries are still audited."""
    from paddle_tpu.serving.fleet import ModelFleet

    fleet = ModelFleet()
    findings: List[Finding] = []
    try:
        for bundle in bundles:
            name = os.path.splitext(os.path.basename(bundle))[0] or bundle
            try:
                from paddle_tpu.config.deploy import load_inference_model

                model = load_inference_model(bundle)
                fleet.add_model(name, model, start=False)
            except Exception as e:  # noqa: BLE001 — audit the rest
                findings.append(Finding(
                    check="serve-build", severity="ERROR", file=bundle,
                    message=f"bundle failed to load: "
                            f"{type(e).__name__}: {e}"))
        findings.extend(fleet.audit())
    finally:
        fleet.close()
    return findings


def _audit_deploy_bundle(bundle: str) -> List[Finding]:
    """``lint --deploy BUNDLE.ptz`` — the offline preflight extended to
    QUANTIZED bundles (docs/deploy.md): the dequantized forward is traced
    through the dtype-promotion and constant-bloat checks (params ride as
    arguments, so an int8 table accidentally materialized as f32
    *constants* is exactly what constant-bloat catches), and for int8
    bundles the in-trace-dequantize closure is audited too — the same
    gate ``load_inference_model(int8_in_trace=True)`` applies before it
    keeps weights quantized in HBM.  Bundle-integrity failures are ERROR
    findings, never crashes."""
    try:
        from paddle_tpu.config.deploy import load_inference_model
        from paddle_tpu.nn.feeds import example_feed

        model = load_inference_model(bundle)
    except Exception as e:
        return [Finding(
            check="deploy-build", severity="ERROR", file=bundle,
            message=f"bundle failed to load: {type(e).__name__}: {e}")]
    base = os.path.basename(bundle)
    qmode = (model.manifest.get("quantize") or {}).get("mode") or "f32"
    variants = [(model, f"deploy[{qmode}]:{base}")]
    if any(m.get("mode") == "int8" for m in
           (model.manifest.get("quantize") or {}).get("arrays", {}).values()):
        try:
            m8 = load_inference_model(bundle, int8_in_trace=True)
            if m8._int8:  # the gate admitted the in-trace closure
                variants.append((m8, f"deploy[int8_in_trace]:{base}"))
        except Exception as e:  # noqa: BLE001 — audited best-effort
            return [Finding(
                check="deploy-build", severity="ERROR", file=bundle,
                message=f"int8 in-trace load failed: "
                        f"{type(e).__name__}: {e}")]
    findings: List[Finding] = []
    for m, label in variants:
        try:
            from paddle_tpu.analysis.jaxpr_audit import audit_fn

            names = tuple(m.output_names)
            findings.extend(audit_fn(
                m._make_run(names), m.params, m.state,
                example_feed(m.topology), label=label,
                checks=["dtype-promotion", "constant-bloat"]))
        except Exception as e:  # a closure that fails to TRACE is a finding
            findings.append(Finding(
                check="deploy-build", severity="ERROR", file=bundle,
                message=f"{label} failed to trace: "
                        f"{type(e).__name__}: {e}"))
    return findings


def _audit_slot_step_closure() -> List[Finding]:
    """The continuous-batching half of ``--serve``: audit the compiled
    ``decode_step`` closure over a slot table at a compact flagship shape
    (serving.slots.audit_slot_backend — same check set and contract as
    ``--decode``), plus the speculative wide-verify closure over a greedy
    (``beam_size == 1``) table (docs/decode.md "Speculative decoding").
    One audit per lint run, independent of how many bundles were given:
    the step programs are the serving tier's, not a bundle's."""
    try:
        from paddle_tpu.serving.slots import (audit_slot_backend,
                                              example_slot_backend)

        findings = list(audit_slot_backend())
        findings.extend(audit_slot_backend(
            example_slot_backend(slots=4, beam_size=1),
            slots=4, label="serve_slots[greedy]", spec_k=4))
        return findings
    except Exception as e:  # a step that fails to BUILD is a finding
        return [Finding(
            check="serve-build", severity="ERROR", file="serve_slots",
            message=f"slot decode_step closure failed to build: "
                    f"{type(e).__name__}: {e}")]


def run(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu lint",
        description="Static trace-safety linter + jaxpr auditor "
                    "(docs/lint.md has the check catalog)")
    p.add_argument("--config", action="append", default=[], metavar="CONF",
                   help="audit the train step of this config (repeatable)")
    p.add_argument("--path", action="append", default=[], metavar="DIR",
                   help="AST-lint this file/tree (repeatable)")
    p.add_argument("--decode", nargs="?", const="", default=None,
                   metavar="B,S,K,L",
                   help="audit the flagship fused-decode closure "
                        "(kernel + XLA-fallback variants) at these shapes")
    p.add_argument("--pserver", nargs="?", const="", default=None,
                   metavar="V,D,N,S",
                   help="audit the pserver lookup/sparse-apply closures "
                        "and gate the never-densify contract")
    p.add_argument("--obs", action="store_true",
                   help="audit the telemetry contract: the compiled train "
                        "step with the timeline/MFU plumbing enabled must "
                        "be host-transfer-free AND identical to the "
                        "telemetry-off trace; also pins the train step "
                        "and decode_step identical with request tracing "
                        "armed (spans add zero compiled equations)")
    p.add_argument("--sdc", action="store_true",
                   help="audit the SDC-firewall contract: the compiled "
                        "step with --sdc_check_every=0 must be "
                        "equation-identical to a never-enabled build, "
                        "and the in-jit state fingerprint (check on) "
                        "must audit host-transfer-free "
                        "(docs/resilience.md 'Silent corruption')")
    p.add_argument("--amp", action="store_true",
                   help="audit the mixed-precision contract: the compiled "
                        "--amp train step (forward + backward + loss "
                        "scaling + fused apply) must contain ZERO "
                        "non-allowlisted all-f32 dot_general/conv eqns "
                        "(docs/mixed_precision.md)")
    p.add_argument("--serve", action="append", default=[],
                   metavar="BUNDLE.ptz",
                   help="serving preflight: audit a deploy bundle's "
                        "serving closure (host-transfer/constant-bloat; "
                        "repeatable — several bundles audit as a FLEET "
                        "model table, every entry traced and labeled "
                        "fleet:<name>@v<version>)")
    p.add_argument("--deploy", action="append", default=[],
                   metavar="BUNDLE.ptz",
                   help="deploy preflight incl. QUANTIZED bundles: audit "
                        "the dequantized forward (and the int8 in-trace "
                        "closure) for dtype-promotion and constant-bloat "
                        "(repeatable; docs/deploy.md)")
    p.add_argument("--race", nargs="?", const="", default=None,
                   metavar="FILE",
                   help="host-concurrency race lint: infer each mutable "
                        "attribute's guard lock and flag cross-thread "
                        "access outside it (default: the known concurrent "
                        "classes; FILE restricts to one module)")
    p.add_argument("--protocol", nargs="?", const="", default=None,
                   metavar="FILE",
                   help="gang collective/barrier protocol checker: both "
                        "sides of a rank-conditional branch must reach "
                        "the same collectives in the same order (default: "
                        "trainer + resilience tier; FILE restricts)")
    p.add_argument("--hbm", action="store_true",
                   help="static HBM audit of the real compiled train and "
                        "decode steps: peak-live-bytes vs the chip table, "
                        "donation honored, no f64/weak-type cache-key "
                        "poison")
    p.add_argument("--all", action="store_true",
                   help="run every registered pass (tree lint + decode + "
                        "pserver + obs + amp + sdc + race + protocol + "
                        "hbm + slot-step audit)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--fail-on", default="ERROR", type=str.upper,
                   choices=("ERROR", "WARN", "INFO", "NEVER"),
                   help="exit 1 when findings at/above this severity remain")
    p.add_argument("--allowlist", metavar="FILE",
                   help="suppression file: '<check-id> [message substring]' "
                        "per line")
    try:
        ns = p.parse_args(argv)
    except SystemExit as e:
        if e.code in (0, None):  # --help: the documented SystemExit(0)
            raise
        return 2  # unknown flag / bad choice: usage error, uniformly 2

    allow_entries = None
    if ns.allowlist:
        try:  # validate BEFORE the passes run: a typo'd path is a usage
            # error, not a full lint run followed by a crash
            allow_entries = load_allowlist(ns.allowlist)
        except OSError as e:
            print(f"lint: cannot read allowlist {ns.allowlist!r}: {e}",
                  file=sys.stderr)
            return 2

    if ns.all:
        # every registered pass; explicit flags keep their given specs
        ns.decode = ns.decode if ns.decode is not None else ""
        ns.pserver = ns.pserver if ns.pserver is not None else ""
        ns.obs = ns.amp = ns.sdc = ns.hbm = True
        ns.race = ns.race if ns.race is not None else ""
        ns.protocol = ns.protocol if ns.protocol is not None else ""

    targets = list(ns.path)
    configs = list(ns.config)
    if (not targets and not configs and ns.decode is None
            and ns.pserver is None and not ns.serve and not ns.obs
            and not ns.amp and not ns.deploy and not ns.sdc
            and ns.race is None and ns.protocol is None and not ns.hbm):
        targets = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    if ns.all and not ns.path:
        targets = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]

    findings: List[Finding] = []
    from paddle_tpu.analysis.ast_lint import lint_path

    for path in targets:
        if not os.path.exists(path):
            findings.append(Finding(check="bad-target", severity="ERROR",
                                    file=path, message="no such file or "
                                    "directory"))
            continue
        findings.extend(lint_path(path))
    for conf in configs:
        findings.extend(_audit_config(conf))
    if ns.decode is not None:
        findings.extend(_audit_decode_closure(ns.decode))
    if ns.pserver is not None:
        from paddle_tpu.pserver import audit_pserver

        findings.extend(audit_pserver(ns.pserver))
    if ns.obs:
        from paddle_tpu.obs.audit import audit_telemetry_step

        findings.extend(audit_telemetry_step())
    if ns.amp:
        from paddle_tpu.analysis.amp_audit import audit_amp_step

        findings.extend(audit_amp_step())
    if ns.sdc:
        from paddle_tpu.resilience.integrity import audit_sdc_step

        findings.extend(audit_sdc_step())
    if ns.race is not None:
        from paddle_tpu.analysis.static import run_race

        findings.extend(run_race((ns.race,) if ns.race else ()))
    if ns.protocol is not None:
        from paddle_tpu.analysis.static import run_protocol

        findings.extend(run_protocol((ns.protocol,) if ns.protocol else ()))
    if ns.hbm:
        from paddle_tpu.analysis.static import run_hbm

        findings.extend(run_hbm())
    if len(ns.serve) > 1:
        # several bundles = a fleet: every model-table entry's closure
        # is audited, findings labeled fleet:<name>@v<version>
        findings.extend(_audit_serving_fleet(ns.serve))
    else:
        for bundle in ns.serve:
            findings.extend(_audit_serving_bundle(bundle))
    if ns.serve or ns.all:
        # --serve also gates the continuous path's fused step (once);
        # --all runs the bundle-independent half even with no bundle
        findings.extend(_audit_slot_step_closure())
    for bundle in ns.deploy:
        findings.extend(_audit_deploy_bundle(bundle))

    if allow_entries is not None:
        findings = apply_allowlist(findings, allow_entries)

    print(format_findings(findings, ns.format))
    if ns.fail_on == "NEVER":
        return 0
    return 1 if severity_at_least(findings, ns.fail_on) else 0


if __name__ == "__main__":
    sys.exit(run())
