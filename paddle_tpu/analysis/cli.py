"""``python -m paddle_tpu lint`` — the CLI front of the analysis subsystem.

Usage:

    python -m paddle_tpu lint --path paddle_tpu --format json
    python -m paddle_tpu lint --config demo/mnist/conf.py --fail-on WARN
    python -m paddle_tpu lint --config conf.py --allowlist .tpu-lint-allow

``--path DIR`` runs the AST trace-safety linter over the tree;
``--config CONF.py`` additionally builds the config's trainer and audits
the closed jaxpr of its train step (the jaxpr auditor).  Both may repeat.
With neither, the installed ``paddle_tpu`` package itself is linted.

Exit status: 1 when any finding at/above ``--fail-on`` (default ERROR)
survives suppression, else 0.  ``--fail-on NEVER`` always exits 0.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from paddle_tpu.analysis.findings import (Finding, apply_allowlist,
                                          format_findings, load_allowlist,
                                          severity_at_least)

__all__ = ["run"]


def _audit_config(conf_path: str) -> List[Finding]:
    """Build the config's trainer and audit its step jaxpr; AST-lint the
    config source as well (configs are user code running under trace)."""
    from paddle_tpu.__main__ import _build_trainer, _first_feed, _load_config
    from paddle_tpu.analysis.ast_lint import lint_file

    findings = lint_file(conf_path)
    try:
        conf = _load_config(conf_path)
        trainer = _build_trainer(conf)
        feed = _first_feed(conf)
    except Exception as e:
        findings.append(Finding(
            check="config-build", severity="ERROR", file=conf_path,
            message=f"config failed to build a trainer: "
                    f"{type(e).__name__}: {e}"))
        return findings
    label = os.path.basename(conf_path)
    try:
        findings.extend(trainer.audit(feed, label=f"{label}:train_step"))
    except Exception as e:  # a step that fails to TRACE is itself a finding
        findings.append(Finding(
            check="config-build", severity="ERROR", file=conf_path,
            message=f"train step failed to trace for auditing: "
                    f"{type(e).__name__}: {e}"))
    return findings


def run(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu lint",
        description="Static trace-safety linter + jaxpr auditor "
                    "(docs/lint.md has the check catalog)")
    p.add_argument("--config", action="append", default=[], metavar="CONF",
                   help="audit the train step of this config (repeatable)")
    p.add_argument("--path", action="append", default=[], metavar="DIR",
                   help="AST-lint this file/tree (repeatable)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--fail-on", default="ERROR", type=str.upper,
                   choices=("ERROR", "WARN", "INFO", "NEVER"),
                   help="exit 1 when findings at/above this severity remain")
    p.add_argument("--allowlist", metavar="FILE",
                   help="suppression file: '<check-id> [message substring]' "
                        "per line")
    ns = p.parse_args(argv)

    targets = list(ns.path)
    configs = list(ns.config)
    if not targets and not configs:
        targets = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]

    findings: List[Finding] = []
    from paddle_tpu.analysis.ast_lint import lint_path

    for path in targets:
        if not os.path.exists(path):
            findings.append(Finding(check="bad-target", severity="ERROR",
                                    file=path, message="no such file or "
                                    "directory"))
            continue
        findings.extend(lint_path(path))
    for conf in configs:
        findings.extend(_audit_config(conf))

    if ns.allowlist:
        findings = apply_allowlist(findings, load_allowlist(ns.allowlist))

    print(format_findings(findings, ns.format))
    if ns.fail_on == "NEVER":
        return 0
    return 1 if severity_at_least(findings, ns.fail_on) else 0


if __name__ == "__main__":
    sys.exit(run())
