"""``lint --race`` — static lock-discipline checker for the host tier.

The reference Paddle hand-audited its threading (MultiGradientMachine
worker threads, pserver RPC); our rewrite replaced that with lock-guarded
host classes (SlotScheduler, BatchQueue, WorkerSupervisor, the metrics
registry, the journal, the tracer).  The discipline is a *convention* —
"``self._lock`` guards the slot table" — that nothing checks.  This pass
checks it:

1. **Guard inference.**  For every class that owns a lock attribute
   (``self._lock = threading.Lock()`` / ``RLock`` / ``Condition``), every
   mutable ``self.<field>`` that is written at least once inside a
   ``with self.<lock>:`` block is inferred *guarded by* that lock.
2. **Unguarded access.**  Any read (WARN) or write (ERROR) of a guarded
   field outside the guard — in any method a foreign thread can enter
   (conservatively: every method except ``__init__``; a private helper
   whose every intraclass call site holds the lock inherits it as
   *held-on-entry*) — is a finding.
3. **Lock-order inversion.**  ``with B:`` nested (lexically or through a
   held-on-entry helper) inside ``with A:`` adds the edge A→B to a global
   lock graph across all scanned files; any cycle is an ERROR naming the
   participating locks.

Intentional lock-free fields declare themselves with an annotation that
MUST name its invariant::

    self.closed = False  # tpu-lint: guarded-by=none - monotonic flag,
                         # single writer, stale read only delays shutdown

``guarded-by=<lockattr>`` instead *overrides* the inferred guard; on an
access line (rather than the ``__init__`` assignment) it exempts just that
line.  An annotation without invariant text is itself an ERROR
(``race-annotation``) — the whole point is that the invariant is written
down.  ``# tpu-lint: disable=race-*`` line/def directives work as for
every other AST check.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.findings import (Finding, line_suppressions,
                                          suppressed)

__all__ = ["run_race", "scan_file", "DEFAULT_RACE_TARGETS"]

#: the known concurrent modules (ISSUE: serving tier, data prefetch,
#: observability, gang cluster) — the default ``--race`` target set
DEFAULT_RACE_TARGETS = (
    "serving/server.py",
    "serving/slots.py",
    "serving/batching.py",
    "serving/worker.py",
    "data/feeder.py",
    "obs/registry.py",
    "obs/journal.py",
    "obs/trace.py",
    "resilience/cluster.py",
    "resilience/dcn.py",
)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}

_GUARDED_BY = re.compile(
    r"#\s*tpu-lint:\s*guarded-by=(\w+)\s*(?:[-—–:]\s*(\S.*))?")

_SKIP_METHODS = {"__init__", "__new__", "__post_init__", "__init_subclass__"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func) or ""
    leaf = name.rsplit(".", 1)[-1]
    return leaf in _LOCK_FACTORIES


@dataclass
class _Access:
    attr: str
    write: bool
    line: int
    held: Tuple[str, ...]
    method: str


@dataclass
class _ClassScan:
    name: str
    locks: Set[str] = field(default_factory=set)
    accesses: List[_Access] = field(default_factory=list)
    #: (method, acquired-lock, locally-held-at-acquire, line)
    acquires: List[Tuple[str, str, Tuple[str, ...], int]] = \
        field(default_factory=list)
    #: intraclass call sites: callee -> [(caller, held-at-site)]
    calls: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = \
        field(default_factory=dict)
    methods: Set[str] = field(default_factory=set)
    #: guarded-by policy from annotated __init__ assignments:
    #: field -> (lockname-or-'none', line)
    policy: Dict[str, Tuple[str, int]] = field(default_factory=dict)


class _MethodVisitor:
    """Walks one method body tracking the lexically-held lock set."""

    def __init__(self, scan: _ClassScan, method: str,
                 module_locks: Set[str], annotations: Dict[int, tuple]):
        self.scan = scan
        self.method = method
        self.module_locks = module_locks
        self.annotations = annotations

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in self.scan.locks):
            return expr.attr
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"<module>.{expr.id}"
        return None

    def visit(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                self.visit(item.context_expr, held)
                lk = self._lock_of(item.context_expr)
                if lk is not None:
                    acquired.append(lk)
                    self.scan.acquires.append(
                        (self.method, lk, held, node.lineno))
            inner = held + tuple(a for a in acquired if a not in held)
            for stmt in node.body:
                self.visit(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def/lambda may run on another thread (Thread
            # target, callback): its body holds NOTHING
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                self.visit(stmt, ())
            return
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            if node.attr not in self.scan.locks:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                self.scan.accesses.append(_Access(
                    node.attr, write, node.lineno, held, self.method))
            return  # self.<attr> has no deeper self references
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "self"):
                self.scan.calls.setdefault(fn.attr, []).append(
                    (self.method, held))
        for child in ast.iter_child_nodes(node):
            self.visit(child, held)


def _scan_class(cls: ast.ClassDef, module_locks: Set[str],
                annotations: Dict[int, tuple]) -> _ClassScan:
    scan = _ClassScan(cls.name)
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    scan.methods = {m.name for m in methods}
    # pass 1: lock attributes + annotated field policies
    for m in methods:
        for node in ast.walk(m):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, value = node.target, node.value
            else:
                continue
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                if _is_lock_ctor(value):
                    scan.locks.add(tgt.attr)
                ann = annotations.get(node.lineno)
                if ann is not None:
                    scan.policy[tgt.attr] = (ann[0], node.lineno)
    # pass 2: accesses / acquisitions / intraclass calls
    for m in methods:
        v = _MethodVisitor(scan, m.name, module_locks, annotations)
        for stmt in m.body:
            v.visit(stmt, ())
    return scan


def _held_on_entry(scan: _ClassScan) -> Dict[str, frozenset]:
    """Locks a method provably holds on EVERY entry: the intersection over
    its intraclass call sites of (locks held at the site + the caller's
    own held-on-entry).  Public methods and uncalled helpers get the empty
    set — anyone may call them bare."""
    he: Dict[str, frozenset] = {m: frozenset() for m in scan.methods}
    for _ in range(4):  # tiny graphs; fixpoint in a few rounds
        changed = False
        for m in scan.methods:
            sites = scan.calls.get(m, ())
            if not m.startswith("_") or not sites:
                continue
            acc: Optional[frozenset] = None
            for caller, held in sites:
                eff = frozenset(held) | he.get(caller, frozenset())
                acc = eff if acc is None else (acc & eff)
            acc = acc or frozenset()
            if acc != he[m]:
                he[m] = acc
                changed = True
        if not changed:
            break
    return he


def _module_scan(tree: ast.Module, module_locks: Set[str],
                 acquires: List[Tuple[str, str, Tuple[str, ...], int]]):
    """Module-level functions contribute lock-ORDER edges only (module
    locks guard module globals, which this pass does not model)."""

    def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                name = None
                expr = item.context_expr
                if isinstance(expr, ast.Name) and expr.id in module_locks:
                    name = f"<module>.{expr.id}"
                if name is not None:
                    acquired.append(name)
                    acquires.append(("<module>", name, held, node.lineno))
            inner = held + tuple(acquired)
            for stmt in node.body:
                walk(stmt, inner)
            return
        if isinstance(node, ast.ClassDef):
            return  # classes handled separately
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for s in stmt.body:
                walk(s, ())


def scan_file(path: str,
              edges: Optional[List[Tuple[str, str, str, int]]] = None
              ) -> List[Finding]:
    """Race-lint one file.  ``edges`` (if given) collects qualified
    lock-order edges ``(held, acquired, file, line)`` for the caller's
    global cycle detection instead of per-file."""
    with open(path) as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(check="race-parse", severity="ERROR", file=path,
                        line=e.lineno, message=f"unparsable: {e.msg}")]

    sup = line_suppressions(source)
    func_ranges = [(n.lineno, max(n.lineno, getattr(n, "end_lineno", n.lineno)))
                   for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    annotations: Dict[int, tuple] = {}
    findings: List[Finding] = []
    for i, line in enumerate(source.splitlines(), start=1):
        m = _GUARDED_BY.search(line)
        if m:
            annotations[i] = (m.group(1), (m.group(2) or "").strip())
            if not (m.group(2) or "").strip():
                findings.append(Finding(
                    check="race-annotation", severity="ERROR", file=path,
                    line=i, message="guarded-by annotation must name its "
                    "invariant: '# tpu-lint: guarded-by=<lock|none> - "
                    "<why this is safe>'"))

    module_locks = {
        t.targets[0].id if isinstance(t, ast.Assign) else t.target.id
        for t in tree.body
        if (isinstance(t, ast.Assign) and len(t.targets) == 1
            and isinstance(t.targets[0], ast.Name)
            and _is_lock_ctor(t.value))
        or (isinstance(t, ast.AnnAssign) and isinstance(t.target, ast.Name)
            and t.value is not None and _is_lock_ctor(t.value))}

    local_edges: List[Tuple[str, str, str, int]] = []
    sink = edges if edges is not None else local_edges
    mod_acquires: List[Tuple[str, str, Tuple[str, ...], int]] = []
    _module_scan(tree, module_locks, mod_acquires)
    for _fn, lk, held, line in mod_acquires:
        for h in held:
            sink.append((h, lk, path, line))

    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        scan = _scan_class(cls, module_locks, annotations)
        if not scan.locks:
            continue
        he = _held_on_entry(scan)

        def qual(lock: str) -> str:
            return lock if lock.startswith("<module>") else \
                f"{scan.name}.{lock}"

        for method, lk, held, line in scan.acquires:
            for h in tuple(held) + tuple(he.get(method, ())):
                if h != lk:
                    sink.append((qual(h), qual(lk), path, line))

        # guard inference: a field WRITTEN under a lock (outside __init__)
        # is guarded by the lock most of its guarded writes hold.  Reads
        # never vote: a field only ever written at construction cannot
        # race, however many locked reads it has
        votes: Dict[str, Dict[str, int]] = {}
        for a in scan.accesses:
            if a.method in _SKIP_METHODS or not a.write:
                continue
            eff = frozenset(a.held) | he.get(a.method, frozenset())
            for lk in eff:
                votes.setdefault(a.attr, {})[lk] = \
                    votes.setdefault(a.attr, {}).get(lk, 0) + 1
        guards: Dict[str, str] = {}
        for attr, tally in votes.items():
            if tally:
                guards[attr] = max(tally, key=lambda k: tally[k])
        exempt: Set[str] = set()
        for attr, (lockname, line) in scan.policy.items():
            if lockname == "none":
                exempt.add(attr)
            elif lockname in scan.locks:
                guards[attr] = lockname
            else:
                findings.append(Finding(
                    check="race-annotation", severity="ERROR", file=path,
                    line=line,
                    message=f"guarded-by={lockname} names no lock "
                            f"attribute of {scan.name} (locks: "
                            f"{sorted(scan.locks)}; use 'none' for "
                            f"intentionally lock-free fields)"))

        for a in scan.accesses:
            if a.method in _SKIP_METHODS or a.attr in exempt:
                continue
            guard = guards.get(a.attr)
            if guard is None:
                continue  # no lock discipline exists for this field
            eff = frozenset(a.held) | he.get(a.method, frozenset())
            if guard in eff:
                continue
            if a.line in annotations:  # line-level guarded-by exemption
                continue
            check = "race-unguarded-write" if a.write else \
                "race-unguarded-read"
            if suppressed(check, a.line, sup, func_ranges):
                continue
            kind = "lock attribute" if not guard.startswith("<module>") \
                else "module lock"
            findings.append(Finding(
                check=check,
                severity="ERROR" if a.write else "WARN",
                file=path, line=a.line,
                message=f"{scan.name}.{a.attr} is guarded by {kind} "
                        f"{guard.split('.')[-1]!r} elsewhere but "
                        f"{'written' if a.write else 'read'} here in "
                        f"{a.method}() without it (annotate "
                        f"'# tpu-lint: guarded-by=none - <invariant>' if "
                        f"intentionally lock-free)"))

    if edges is None:
        findings.extend(_order_findings(local_edges))
    return findings


def _order_findings(edges: Sequence[Tuple[str, str, str, int]]
                    ) -> List[Finding]:
    """Cycle detection over the global lock-order graph: an edge A→B means
    B was acquired while A was held; any cycle is a potential deadlock."""
    graph: Dict[str, Set[str]] = {}
    where: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for a, b, f, line in edges:
        graph.setdefault(a, set()).add(b)
        where.setdefault((a, b), (f, line))
    findings: List[Finding] = []
    seen_cycles: Set[frozenset] = set()

    def dfs(node: str, stack: List[str], on_stack: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cycle = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cycle)
                if key in seen_cycles:
                    continue
                seen_cycles.add(key)
                f, line = where[(cycle[0], cycle[1])]
                findings.append(Finding(
                    check="race-lock-order", severity="ERROR",
                    file=f, line=line,
                    message="lock-order inversion: "
                            + " -> ".join(cycle)
                            + " (two threads taking these in opposite "
                              "order deadlock)"))
            elif stack.count(nxt) == 0:
                stack.append(nxt)
                on_stack.add(nxt)
                dfs(nxt, stack, on_stack)
                on_stack.discard(nxt)
                stack.pop()

    for start in sorted(graph):
        dfs(start, [start], {start})
    return findings


def run_race(paths: Sequence[str] = ()) -> List[Finding]:
    """Race-lint ``paths`` (files or trees); with none given, the known
    concurrent modules of the installed package
    (:data:`DEFAULT_RACE_TARGETS`)."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    files: List[str] = []
    if not paths:
        files = [os.path.join(pkg, rel) for rel in DEFAULT_RACE_TARGETS]
    else:
        for p in paths:
            if os.path.isdir(p):
                for root, dirs, names in os.walk(p):
                    dirs[:] = [d for d in dirs
                               if d not in ("__pycache__", ".git")]
                    files.extend(os.path.join(root, n)
                                 for n in sorted(names)
                                 if n.endswith(".py"))
            else:
                files.append(p)
    findings: List[Finding] = []
    edges: List[Tuple[str, str, str, int]] = []
    for f in files:
        if not os.path.exists(f):
            findings.append(Finding(
                check="race-target", severity="ERROR", file=f,
                message="no such file"))
            continue
        findings.extend(scan_file(f, edges))
    findings.extend(_order_findings(edges))
    return findings
