"""Whole-stack static safety passes (``lint --race/--protocol/--hbm``).

PR 1's trace-time lint sees the *compiled program*; these passes see the
*host program around it* — the lock-guarded serving/observability classes,
the rank-conditional gang protocol, and the static HBM footprint of the
compiled steps.  Every recent incident class fixed by hand (abandoned
worker commits, the read-first grow deadlock, flushed-buffer span
mutations) was a statically detectable lock-discipline or barrier-ordering
bug; these passes turn those conventions into checked gates.

- ``race``     — lock-discipline checker over the known concurrent classes
- ``protocol`` — barrier/collective matching over the gang protocol
- ``hbm``      — static peak-live-bytes + donation audit of compiled steps

All three emit :class:`paddle_tpu.analysis.findings.Finding` and honor the
existing suppression planes (``# tpu-lint: disable=`` directives and
``--allowlist``); the race pass adds ``# tpu-lint: guarded-by=`` (see
docs/lint.md).
"""

from paddle_tpu.analysis.static.hbm import audit_hbm_jaxpr, run_hbm
from paddle_tpu.analysis.static.protocol import run_protocol
from paddle_tpu.analysis.static.race import run_race

__all__ = ["run_race", "run_protocol", "run_hbm", "audit_hbm_jaxpr"]
