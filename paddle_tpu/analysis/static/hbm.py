"""``lint --hbm`` — static HBM footprint + donation audit.

An OOM or a silently-unhonored donation shows up as a pod falling over
(or a 2x HBM bill) minutes into a run; both are visible in the *closed
jaxpr* before anything compiles.  This pass runs the
``analysis.jaxpr_walk.peak_live_bytes`` liveness walk (buffers born at
their producing eqn, dead after last read, donated args credited at
their donation point) over the real compiled steps and reports:

- ``hbm-peak`` — static peak live bytes vs the chip HBM table
  (``analysis.flops.CHIP_HBM_BYTES``): INFO with the utilization when it
  fits, ERROR when the step cannot fit the chip (off-TPU there is no
  capacity and the estimate reports as INFO);
- ``hbm-donation-reuse`` (ERROR) — a donated argument still read AFTER
  the eqn producing its shape/dtype-matched output: XLA cannot honor the
  aliasing and silently materializes a copy, exactly the 2x-params bill
  donation exists to avoid;
- ``hbm-donation-unmatched`` (WARN) — a donated argument with no
  shape/dtype-matched output at all (the donation is silently dropped);
- ``hbm-f64-const`` (ERROR) — a float64 constant/literal in the trace:
  besides the 2x bytes, an x64 constant makes the jaxpr — and therefore
  the compile-cache key — differ from the f32 trace every other process
  builds;
- ``hbm-weak-arg`` (WARN) — a weak-type argument aval (a Python scalar
  passed positionally): weak/strong flips retrace and defeat the
  persistent compile cache key (docs/deploy.md).

``run_hbm()`` audits the representative trainer step (the exact
``_step_fn`` closure ``train_batch`` compiles, with its real
``donate_argnums=(0, 2, 3)``) and the flagship fused decode step;
``audit_hbm_jaxpr`` is the direct entry for any closed jaxpr.
"""

from __future__ import annotations

from typing import List, Sequence

from paddle_tpu.analysis.findings import Finding

__all__ = ["audit_hbm_jaxpr", "run_hbm"]


def _fmt_bytes(n: float) -> str:
    if n >= 1 << 30:
        return f"{n / (1 << 30):.2f} GiB"
    return f"{n / (1 << 20):.1f} MiB"


def _donation_findings(jaxpr, donate_argnums: Sequence[int],
                       label: str) -> List[Finding]:
    from paddle_tpu.analysis.jaxpr_walk import _is_var

    findings: List[Finding] = []
    producer = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            producer[v] = i
    claimed = set()
    for argnum in donate_argnums:
        if not 0 <= argnum < len(jaxpr.invars):
            continue
        inv = jaxpr.invars[argnum]
        sig = (tuple(getattr(inv.aval, "shape", ())),
               str(getattr(inv.aval, "dtype", "")))
        if inv in jaxpr.outvars:
            continue  # identity passthrough: trivially aliasable
        match = None
        for out in jaxpr.outvars:
            if not _is_var(out) or out in claimed or out not in producer:
                continue
            osig = (tuple(getattr(out.aval, "shape", ())),
                    str(getattr(out.aval, "dtype", "")))
            if osig == sig:
                match = out
                break
        if match is None:
            findings.append(Finding(
                check="hbm-donation-unmatched", severity="WARN",
                where=f"{label}/invar[{argnum}]",
                message=f"donated arg {argnum} {sig[0]}:{sig[1]} has no "
                        f"shape/dtype-matched output — the donation is "
                        f"silently dropped and the buffer stays live"))
            continue
        claimed.add(match)
        # the donated buffer is reused the moment the matched output is
        # produced; any read of the input AFTER that eqn needs the old
        # bytes, so XLA copies and the donation saves nothing
        last_read = max((i for i, eqn in enumerate(jaxpr.eqns)
                         if inv in eqn.invars), default=-1)
        if last_read > producer[match]:
            findings.append(Finding(
                check="hbm-donation-reuse", severity="ERROR",
                where=f"{label}/invar[{argnum}]",
                message=f"donated arg {argnum} {sig[0]}:{sig[1]} is still "
                        f"read at eqn[{last_read}] after its aliased "
                        f"output is produced at eqn[{producer[match]}] — "
                        f"donation cannot be honored (silent copy; "
                        f"use-after-donation)"))
    return findings


def _const_findings(closed, label: str) -> List[Finding]:
    import numpy as np

    from paddle_tpu.analysis.jaxpr_walk import walk_eqns

    findings: List[Finding] = []
    jaxpr = getattr(closed, "jaxpr", closed)
    for i, v in enumerate(getattr(closed, "consts", ()) or ()):
        dt = np.asarray(v).dtype if not hasattr(v, "dtype") else v.dtype
        if str(dt) in ("float64", "complex128", "int64") and \
                str(dt) == "float64":
            findings.append(Finding(
                check="hbm-f64-const", severity="ERROR",
                where=f"{label}/const[{i}]",
                message=f"float64 constant {tuple(np.shape(v))} in the "
                        f"trace: 2x HBM and a compile-cache key no f32 "
                        f"process reproduces (jnp.asarray(..., "
                        f"jnp.float32) it)"))
    for eqn, path in walk_eqns(jaxpr):
        for v in eqn.invars:
            if hasattr(v, "val"):  # Literal
                dt = getattr(getattr(v, "aval", None), "dtype", None)
                if dt is not None and str(dt) == "float64":
                    findings.append(Finding(
                        check="hbm-f64-const", severity="ERROR",
                        where=f"{label}/{path}",
                        message="float64 literal in the trace defeats "
                                "the f32 compile-cache key (and doubles "
                                "the constant's HBM)"))
    for i, v in enumerate(jaxpr.invars):
        if getattr(getattr(v, "aval", None), "weak_type", False):
            findings.append(Finding(
                check="hbm-weak-arg", severity="WARN",
                where=f"{label}/invar[{i}]",
                message=f"argument {i} traces weak-typed (a bare Python "
                        f"scalar): weak/strong flips retrace the step "
                        f"and defeat the persistent compile cache key"))
    return findings


def audit_hbm_jaxpr(closed, *, donate_argnums: Sequence[int] = (),
                    label: str = "step") -> List[Finding]:
    """Full ``--hbm`` check set over one closed jaxpr: peak-live-bytes vs
    the chip table, donation audit, f64/weak-type constants."""
    from paddle_tpu.analysis.flops import chip_hbm_bytes
    from paddle_tpu.analysis.jaxpr_walk import peak_live_bytes

    findings: List[Finding] = []
    stats = peak_live_bytes(closed, donate_argnums)
    peak = stats["peak_bytes"]
    cap = None
    try:
        import jax

        cap = chip_hbm_bytes(jax.devices()[0].device_kind)
    except Exception:  # no backend at all: report the estimate bare
        cap = None
    msg = (f"static peak live {_fmt_bytes(peak)} (args "
           f"{_fmt_bytes(stats['args_bytes'])}, consts "
           f"{_fmt_bytes(stats['consts_bytes'])}, outputs "
           f"{_fmt_bytes(stats['out_bytes'])}, donated "
           f"{_fmt_bytes(stats['donated_bytes'])})")
    if cap:
        pct = 100.0 * peak / cap
        fits = peak <= cap
        findings.append(Finding(
            check="hbm-peak", severity="INFO" if fits else "ERROR",
            where=label,
            message=msg + f" = {pct:.1f}% of chip HBM "
                          f"({_fmt_bytes(cap)})"
                    + ("" if fits else " — the step cannot fit")))
    else:
        findings.append(Finding(
            check="hbm-peak", severity="INFO", where=label,
            message=msg + " (no TPU backend: chip capacity unknown)"))
    jaxpr = getattr(closed, "jaxpr", closed)
    findings.extend(_donation_findings(jaxpr, donate_argnums, label))
    findings.extend(_const_findings(closed, label))
    return findings


def _train_step_closed():
    """Trace the representative trainer's REAL ``_step_fn`` (embedding +
    stacked LSTM + BN head + CE, the amp-audit shape) and return
    ``(closed_jaxpr, donate_argnums)`` — the same (0, 2, 3) donation the
    trainer's jit applies (params, opt_state, accumulators in place)."""
    import jax

    from paddle_tpu.analysis.amp_audit import _amp_trainer

    tr, feed = _amp_trainer()
    rng = jax.random.PRNGKey(0)
    args = (tr.params, tr.state, tr.opt_state, {}, rng, feed)
    closed = jax.make_jaxpr(tr._step_fn)(*args)
    # jit's donate_argnums are PYTREE positions; the jaxpr's invars are
    # the flattened leaves — map (0, 2, 3) to flat leaf index ranges
    donate = []
    off = 0
    for argnum, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        if argnum in (0, 2, 3):
            donate.extend(range(off, off + n))
        off += n
    return closed, tuple(donate)


def _decode_step_closed():
    """Trace the flagship fused decode closure at a compact
    flagship-shaped model (the ``--decode`` audit shape)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import Seq2SeqAttention

    B, S, K, L = 8, 8, 4, 8
    m = Seq2SeqAttention(src_vocab=1024, trg_vocab=1024, emb_dim=128,
                         enc_dim=128, dec_dim=128, att_dim=128)
    params = m.init(jax.random.PRNGKey(0))
    src = jnp.zeros((B, S), jnp.int32)
    src_len = jnp.full((B,), S, jnp.int32)
    closed = jax.make_jaxpr(
        lambda p, s, l: m.beam_search(p, s, l, beam_size=K, max_len=L))(
        params, src, src_len)
    return closed, ()


def run_hbm() -> List[Finding]:
    """The ``--hbm`` pass: audit the real compiled train step and decode
    step (build failures are findings, never crashes)."""
    findings: List[Finding] = []
    for name, build in (("hbm:train_step", _train_step_closed),
                        ("hbm:decode_step", _decode_step_closed)):
        try:
            closed, donate = build()
        except Exception as e:
            findings.append(Finding(
                check="hbm-build", severity="ERROR", where=name,
                message=f"step failed to trace for the HBM audit: "
                        f"{type(e).__name__}: {e}"))
            continue
        try:
            findings.extend(audit_hbm_jaxpr(
                closed, donate_argnums=donate, label=name))
        except Exception as e:  # auditor bug: a finding, not a crash
            findings.append(Finding(
                check="hbm-build", severity="INFO", where=name,
                message=f"HBM auditor internal error: "
                        f"{type(e).__name__}: {e}"))
    return findings
