"""``lint --protocol`` — barrier/collective protocol checker.

The gang protocol (elastic resize, checkpoint commit, SDC voting) is a
set of *matched* blocking ops: every rank must reach the same barriers /
exchanges in the same order, or the gang deadlocks.  Two incident classes
were fixed by hand and are exactly the shapes this pass detects:

- **unmatched collective** (``protocol-unmatched``, ERROR): a
  rank-conditional branch (``if rank == 0:`` / ``gang.is_coordinator``)
  after which one side can reach a collective the other side cannot;
- **order inversion** (``protocol-order``, ERROR): both sides reach the
  same collectives but in different order — the read-first grow deadlock
  (PR 8): the joiner read the resume broadcast *before* the barrier while
  the coordinator barriered before publishing, so neither advanced;
- **exception edge** (``protocol-exception``): an ``except`` handler that
  swallows (never re-raises) around — or returning past — collectives its
  peers still block on: the abandoned-worker commit shape (PR 6), one
  rank silently leaving the protocol mid-step;
- **two-level inversion** (``protocol-pod-order``, ERROR): a function
  that introduces a pod-LOCAL rendezvous (``pod_barrier``) reaches a
  GLOBAL collective before it.  The two-tier protocol (multi-pod elastic
  resize, ``_gang_resize``) must settle the cheap tier first — drain
  pod-local traffic, then commit globally — or a pod whose members are
  split across the two tiers deadlocks against the other pods' global
  barrier.

The checker parses the protocol modules (trainer, cluster, checkpoint_io,
integrity by default), builds a call graph (``self.m()`` within a class,
bare names within a module, then globally-unique bare names across the
scanned set), linearizes each function into its ordered collective
sequence, and compares the two sides of every rank-conditional branch —
including the shared fall-through continuation, which a side that
``return``s early never reaches.  A ``barrier=gang.barrier`` keyword
*reference* counts as reaching a barrier (the t5x-style commit protocol
passes the collective down as a callback).

Findings carry the ``if``/handler line, so the standard
``# tpu-lint: disable=protocol-*`` line/def directives apply; genuinely
one-sided ops matched cross-function (the coordinator-only resume
broadcast consumed by ``_gang_join``) are annotated in place, each naming
its invariant.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.findings import (Finding, line_suppressions,
                                          suppressed)

__all__ = ["run_protocol", "scan_modules", "DEFAULT_PROTOCOL_TARGETS",
           "COLLECTIVES", "POD_LOCAL"]

DEFAULT_PROTOCOL_TARGETS = (
    "trainer/trainer.py",
    "resilience/cluster.py",
    "resilience/checkpoint_io.py",
    "resilience/integrity.py",
    "resilience/dcn.py",
)

#: blocking collective ops every rank must reach together.  One-sided ops
#: (ack_resize, poll_world, epoch publishes) are deliberately absent:
#: they have a single blocked peer by design and matching them would
#: flag the protocol's own implementation.
COLLECTIVES = frozenset({
    "barrier", "exchange_json", "broadcast_json", "allgather",
    "all_gather", "process_allgather", "broadcast_one_to_all",
    "pod_barrier",
})

#: the pod-LOCAL tier of the two-level protocol; every other collective
#: is global.  ``protocol-pod-order`` pins local-before-global.
POD_LOCAL = frozenset({"pod_barrier"})

# no \b guards: 'is_coordinator' / 'local_rank' must match, and an
# underscore is a word character, so word boundaries would miss them
_RANK_RE = re.compile(r"(rank|coordinator|chief|leader)", re.IGNORECASE)

#: an op occurrence: (collective name, source line, note)
_Op = Tuple[str, int, str]


class _Module:
    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.sup = line_suppressions(source)
        self.func_ranges = [
            (n.lineno, max(n.lineno, getattr(n, "end_lineno", n.lineno)))
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        #: top-level functions by name
        self.functions: Dict[str, ast.AST] = {}
        #: class -> method -> node
        self.classes: Dict[str, Dict[str, ast.AST]] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                meths = {}
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        meths[sub.name] = sub
                self.classes[node.name] = meths


class _Checker:
    def __init__(self, modules: List[_Module]):
        self.modules = modules
        self.findings: List[Finding] = []
        #: (module-path, class-or-None, func) -> summary op list
        self._summaries: Dict[Tuple[str, Optional[str], str], List[_Op]] = {}
        self._stack: Set[Tuple[str, Optional[str], str]] = set()
        #: bare name -> (module, class, name) when globally unique
        self._global: Dict[str, Tuple[_Module, Optional[str], str]] = {}
        counts: Dict[str, int] = {}
        for mod in modules:
            for fn in mod.functions:
                counts[fn] = counts.get(fn, 0) + 1
                self._global[fn] = (mod, None, fn)
        for name, n in counts.items():
            if n > 1:
                del self._global[name]

    # -- resolution --------------------------------------------------------

    def _resolve(self, call: ast.Call, mod: _Module,
                 cls: Optional[str]) -> Optional[Tuple[_Module,
                                                       Optional[str], str]]:
        fn = call.func
        if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
                and fn.value.id == "self" and cls is not None
                and fn.attr in mod.classes.get(cls, {})):
            return (mod, cls, fn.attr)
        if isinstance(fn, ast.Name):
            if fn.id in mod.functions:
                return (mod, None, fn.id)
            return self._global.get(fn.id)
        return None

    def summary(self, mod: _Module, cls: Optional[str],
                name: str) -> List[_Op]:
        key = (mod.path, cls, name)
        if key in self._summaries:
            return self._summaries[key]
        if key in self._stack:
            return []  # recursion: bounded, contributes nothing extra
        node = (mod.classes.get(cls, {}) if cls else mod.functions).get(name)
        if node is None:
            return []
        self._stack.add(key)
        try:
            ops, _exits = self._seq(node.body, mod, cls, emit=True)
        finally:
            self._stack.discard(key)
        self._summaries[key] = ops
        return ops

    # -- expression ops ----------------------------------------------------

    def _expr_ops(self, expr: ast.AST, mod: _Module,
                  cls: Optional[str]) -> List[_Op]:
        ops: List[_Op] = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                fn = node.func
                name = None
                if isinstance(fn, ast.Attribute):
                    name = fn.attr
                elif isinstance(fn, ast.Name):
                    name = fn.id
                if name in COLLECTIVES:
                    ops.append((name, node.lineno, ""))
                    continue
                target = self._resolve(node, mod, cls)
                if target is not None:
                    callee = self.summary(*target)
                    ops.extend((op, node.lineno, f"via {target[2]}()")
                               for op, _ln, _note in callee)
                # a collective passed down as a callback reference
                # (save_checkpoint(barrier=gang.barrier)) reaches it
                for kw in node.keywords:
                    for sub in ast.walk(kw.value):
                        if (isinstance(sub, ast.Attribute)
                                and sub.attr in COLLECTIVES
                                and not isinstance(
                                    getattr(sub, "ctx", None), ast.Store)):
                            ops.append((sub.attr, node.lineno,
                                        f"passed as {kw.arg}="))
        return ops

    # -- statement linearization -------------------------------------------

    def _always_exits(self, stmts: Sequence[ast.AST]) -> bool:
        for s in stmts:
            if isinstance(s, (ast.Return, ast.Raise, ast.Continue,
                              ast.Break)):
                return True
            if isinstance(s, ast.If) and s.orelse and \
                    self._always_exits(s.body) and \
                    self._always_exits(s.orelse):
                return True
        return False

    def _is_rank_test(self, test: ast.AST, mod: _Module) -> bool:
        seg = None
        try:
            seg = ast.get_source_segment(mod.source, test)
        except Exception:  # pragma: no cover - malformed locations
            seg = None
        if seg is None:
            seg = ast.dump(test)
        return bool(_RANK_RE.search(seg))

    def _seq(self, stmts: Sequence[ast.AST], mod: _Module,
             cls: Optional[str], *, emit: bool) -> Tuple[List[_Op], bool]:
        """Linearize ``stmts`` into ordered collective ops; ``emit``
        controls whether divergence findings fire (a function body is
        checked once — inlined callers reuse the summary silently)."""
        ops: List[_Op] = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.If):
                then_ops, then_exit = self._seq(s.body, mod, cls, emit=emit)
                else_ops, else_exit = self._seq(s.orelse, mod, cls,
                                                emit=emit)
                if self._is_rank_test(s.test, mod):
                    rest_ops, rest_exit = self._seq(
                        stmts[i + 1:], mod, cls, emit=emit)
                    side_a = then_ops + ([] if then_exit else rest_ops)
                    side_b = else_ops + ([] if else_exit else rest_ops)
                    if emit:
                        self._compare(s, side_a, side_b, mod)
                    merged = ops + _first_order(side_a + side_b)
                    return merged, (then_exit and else_exit) or rest_exit
                ops.extend(then_ops)
                ops.extend(else_ops)
                if then_exit and else_exit:
                    return ops, True
                continue
            if isinstance(s, ast.Try):
                body_ops, body_exit = self._seq(s.body, mod, cls, emit=emit)
                rest_ops, _ = self._seq(stmts[i + 1:], mod, cls, emit=False)
                for h in s.handlers:
                    h_ops, h_exit = self._seq(h.body, mod, cls, emit=emit)
                    swallows = not any(isinstance(n, ast.Raise)
                                       for n in ast.walk(h))
                    if not swallows or not emit:
                        continue
                    skipped = [op for op in body_ops
                               if op[0] not in {o[0] for o in h_ops}]
                    after = [op for op in rest_ops
                             if op[0] not in {o[0] for o in h_ops}]
                    if skipped and not suppressed(
                            "protocol-exception", h.lineno, mod.sup,
                            mod.func_ranges):
                        self.findings.append(Finding(
                            check="protocol-exception", severity="WARN",
                            file=mod.path, line=h.lineno,
                            message=f"except handler swallows mid-protocol"
                            f": a raise before "
                            f"{_names(skipped)} (line "
                            f"{skipped[0][1]}) leaves peers blocked there "
                            f"while this rank continues"))
                    elif h_exit and after and not suppressed(
                            "protocol-exception", h.lineno, mod.sup,
                            mod.func_ranges):
                        self.findings.append(Finding(
                            check="protocol-exception", severity="ERROR",
                            file=mod.path, line=h.lineno,
                            message=f"except handler exits past "
                            f"{_names(after)} that the success path still "
                            f"reaches — an abandoned rank skips a "
                            f"collective its peers block on (the "
                            f"abandoned-commit shape)"))
                ops.extend(body_ops)
                # handler ops are modeled via findings, not the sequence
                fin_ops, _ = self._seq(s.finalbody, mod, cls, emit=emit)
                ops.extend(fin_ops)
                if body_exit:
                    return ops, True
                continue
            if isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
                # a loop body runs 0..N times: its ops are *conditional*;
                # model one iteration for reachability
                t_ops, _ = self._seq(s.body, mod, cls, emit=emit)
                if isinstance(s, ast.While):
                    ops.extend(self._expr_ops(s.test, mod, cls))
                ops.extend(t_ops)
                e_ops, _ = self._seq(s.orelse, mod, cls, emit=emit)
                ops.extend(e_ops)
                continue
            if isinstance(s, ast.With):
                for item in s.items:
                    ops.extend(self._expr_ops(item.context_expr, mod, cls))
                t_ops, t_exit = self._seq(s.body, mod, cls, emit=emit)
                ops.extend(t_ops)
                if t_exit:
                    return ops, True
                continue
            if isinstance(s, (ast.Return, ast.Raise)):
                if getattr(s, "value", None) is not None:
                    ops.extend(self._expr_ops(s.value, mod, cls))
                if isinstance(s, ast.Raise) and s.exc is not None:
                    ops.extend(self._expr_ops(s.exc, mod, cls))
                return ops, True
            if isinstance(s, (ast.Continue, ast.Break)):
                return ops, True
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue  # nested defs run later, not on this path
            ops.extend(self._expr_ops(s, mod, cls))
        return ops, False

    def check_pod_order(self) -> None:
        """``protocol-pod-order``: in any function that DIRECTLY calls a
        pod-local collective (note == "" — inlined callee ops do not make
        a caller part of the two-level sequence), no GLOBAL collective
        may precede it.  The two-tier resize protocol settles the pod
        tier first; a global barrier reached earlier on the same path
        deadlocks pods whose members are split across the tiers."""
        by_path = {mod.path: mod for mod in self.modules}
        for (path, _cls, name), ops in sorted(
                self._summaries.items(),
                key=lambda kv: (kv[0][0], kv[0][1] or "", kv[0][2])):
            mod = by_path.get(path)
            if mod is None:
                continue
            first_local = next(
                (i for i, (op, _ln, note) in enumerate(ops)
                 if op in POD_LOCAL and not note), None)
            if first_local is None:
                continue
            before = [op for op in ops[:first_local]
                      if op[0] not in POD_LOCAL]
            lcl = ops[first_local]
            if before and not suppressed(
                    "protocol-pod-order", lcl[1], mod.sup, mod.func_ranges):
                self.findings.append(Finding(
                    check="protocol-pod-order", severity="ERROR",
                    file=path, line=lcl[1],
                    message=f"{name}() reaches the GLOBAL collective "
                    f"{before[0][0]} (line {before[0][1]}) before the "
                    f"pod-LOCAL {lcl[0]} — the two-level protocol must "
                    f"drain the pod tier first, then commit globally, or "
                    f"a pod split across the tiers deadlocks the gang"))

    def _compare(self, node: ast.If, side_a: List[_Op], side_b: List[_Op],
                 mod: _Module) -> None:
        a, b = _first_order(side_a), _first_order(side_b)
        names_a = [op[0] for op in a]
        names_b = [op[0] for op in b]
        if set(names_a) == set(names_b):
            if names_a != names_b:
                if not suppressed("protocol-order", node.lineno, mod.sup,
                                  mod.func_ranges):
                    self.findings.append(Finding(
                        check="protocol-order", severity="ERROR",
                        file=mod.path, line=node.lineno,
                        message=f"rank-conditional branches reach the same "
                        f"collectives in DIFFERENT order (one side "
                        f"{' -> '.join(names_a)}, the other "
                        f"{' -> '.join(names_b)}) — the read-first grow "
                        f"deadlock shape: each side blocks where the "
                        f"other has not arrived"))
            return
        if suppressed("protocol-unmatched", node.lineno, mod.sup,
                      mod.func_ranges):
            return
        only_a = [op for op in a if op[0] not in set(names_b)]
        only_b = [op for op in b if op[0] not in set(names_a)]
        for side, ops in (("taken", only_a), ("not-taken", only_b)):
            if not ops:
                continue
            cites = ", ".join(
                f"{op}@line {ln}" + (f" ({note})" if note else "")
                for op, ln, note in ops)
            self.findings.append(Finding(
                check="protocol-unmatched", severity="ERROR",
                file=mod.path, line=node.lineno,
                message=f"only the {side} branch of this rank-conditional "
                f"can reach {cites}; ranks on the other side never "
                f"arrive, so the collective blocks forever"))


def _first_order(ops: List[_Op]) -> List[_Op]:
    """Dedup to first occurrence per collective, preserving order — the
    comparison unit (repeat counts are implementation detail; ORDER and
    MEMBERSHIP are the protocol)."""
    seen: Set[str] = set()
    out: List[_Op] = []
    for op in ops:
        if op[0] not in seen:
            seen.add(op[0])
            out.append(op)
    return out


def _names(ops: List[_Op]) -> str:
    return "/".join(sorted({op[0] for op in ops}))


def scan_modules(paths: Sequence[str]) -> List[Finding]:
    modules: List[_Module] = []
    findings: List[Finding] = []
    for path in paths:
        if not os.path.exists(path):
            findings.append(Finding(
                check="protocol-target", severity="ERROR", file=path,
                message="no such file"))
            continue
        with open(path) as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                check="protocol-parse", severity="ERROR", file=path,
                line=e.lineno, message=f"unparsable: {e.msg}"))
            continue
        modules.append(_Module(path, source, tree))
    checker = _Checker(modules)
    for mod in modules:
        for fn in mod.functions:
            checker.summary(mod, None, fn)
        for cls, meths in mod.classes.items():
            for m in meths:
                checker.summary(mod, cls, m)
    checker.check_pod_order()
    findings.extend(checker.findings)
    return findings


def run_protocol(paths: Sequence[str] = ()) -> List[Finding]:
    """Protocol-check ``paths`` (files or trees); with none given, the
    gang-protocol modules of the installed package."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    files: List[str] = []
    if not paths:
        files = [os.path.join(pkg, rel) for rel in DEFAULT_PROTOCOL_TARGETS]
    else:
        for p in paths:
            if os.path.isdir(p):
                for root, dirs, names in os.walk(p):
                    dirs[:] = [d for d in dirs
                               if d not in ("__pycache__", ".git")]
                    files.extend(os.path.join(root, n)
                                 for n in sorted(names)
                                 if n.endswith(".py"))
            else:
                files.append(p)
    return scan_modules(files)
