"""Trace-time jaxpr auditor — static checks over a compiled topology/step.

Legacy Paddle's ``config_parser.py`` validated model configs before any
kernel ran; the failure modes that actually bite a JAX/XLA port are only
visible in the traced program.  This auditor walks the closed jaxpr of a
train step / inference forward (the same traversal ``bench.py``'s FLOPs
walker uses — ``jaxpr_walk``) and emits typed findings:

================ ======== ====================================================
check id         severity what it catches
================ ======== ====================================================
dtype-promotion  WARN     a dot/conv running wholly in f32 inside a net that
                          otherwise computes in bf16/f16 (silent promotion —
                          2x the MXU cycles and HBM traffic)
host-transfer    ERROR    ``device_put`` of live (non-constant) values or any
                          ``*_callback`` inside the jitted step — a host
                          round-trip per step
constant-bloat   WARN     captured constants > 1 MiB folded into the
                          executable (a closed-over batch once overflowed the
                          remote-compile request limit; see bench.py)
unsharded-op     WARN     a mesh with >1 device but no sharded inputs and no
                          ``sharding_constraint`` anywhere — the step is
                          silently replicated
unaligned-pallas WARN     Pallas ``BlockSpec`` tiles violating the (8, 128)
-tile                     sublane/lane alignment (partial-dim blocks only —
                          a block spanning the full array dim is exempt)
================ ======== ====================================================

Provenance is the jaxpr-eqn path (``label/eqn[4]:scan/eqn[1]:dot_general``).
Suppression happens at the CLI layer via the allowlist file
(``findings.apply_allowlist``) — jaxpr findings have no source line for
``# tpu-lint: disable`` comments to attach to.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.analysis.findings import Finding
from paddle_tpu.analysis.jaxpr_walk import walk_eqns

__all__ = ["audit_jaxpr", "audit_fn", "audit_decode", "audit_no_dense_rows",
           "audit_amp_matmuls", "DECODE_CHECKS", "JAXPR_CHECKS",
           "CONSTANT_BLOAT_BYTES"]

#: constants folded into the executable above this size are flagged
CONSTANT_BLOAT_BYTES = 1 << 20

#: reduced-precision dtypes that mark a net as "low-precision by intent"
_LOW_PRECISION = ("bfloat16", "float16")

#: matmul-class primitives the MXU executes (dtype-promotion targets)
_MXU_PRIMS = ("dot_general", "conv_general_dilated")

#: primitives that imply a host round-trip inside the step
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                   "outside_call")


_FLOAT_NAMES = frozenset(
    ("bfloat16", "float16", "float32", "float64", "float8_e4m3fn",
     "float8_e5m2"))


def _float_dtypes(eqn) -> List[str]:
    # by NAME, not np.issubdtype: ml_dtypes' bfloat16/float8 are not
    # subdtypes of np.floating
    out = []
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        dt = getattr(aval, "dtype", None)
        if dt is not None and str(dt) in _FLOAT_NAMES:
            out.append(str(dt))
    return out


def _shapes(eqn) -> str:
    dims = []
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if hasattr(aval, "shape"):
            dims.append("x".join(map(str, aval.shape)) or "scalar")
    return ", ".join(dims)


# ---------------------------------------------------------------------------
# individual checks — each (closed_jaxpr, label, ctx) -> [Finding]
# ---------------------------------------------------------------------------


def _check_dtype_promotion(closed, label, ctx) -> List[Finding]:
    mxu = [(eqn, path) for eqn, path in walk_eqns(closed.jaxpr, label)
           if eqn.primitive.name in _MXU_PRIMS]
    low = any(any(d in _LOW_PRECISION for d in _float_dtypes(eqn))
              for eqn, _ in mxu)
    if not low:
        return []  # an all-f32 net promotes nothing
    out = []
    for eqn, path in mxu:
        fdts = _float_dtypes(eqn)
        if fdts and all(d == "float32" for d in fdts):
            out.append(Finding(
                check="dtype-promotion", severity="WARN", where=path,
                message=f"{eqn.primitive.name} ({_shapes(eqn)}) runs in f32 "
                        f"inside a {'/'.join(sorted({d for e, _ in mxu for d in _float_dtypes(e) if d in _LOW_PRECISION}))} "
                        f"net — likely silent promotion (2x MXU cycles)"))
    return out


def _check_host_transfer(closed, label, ctx) -> List[Finding]:
    constvars = set(map(id, closed.jaxpr.constvars))
    out = []
    for eqn, path in walk_eqns(closed.jaxpr, label):
        name = eqn.primitive.name
        if name == "device_put":
            # device_put of a captured constant is XLA placing weights —
            # constant-bloat's domain, not a per-step transfer
            live = [v for v in eqn.invars
                    if hasattr(v, "aval") and id(v) not in constvars
                    and type(v).__name__ != "Literal"]
            if not live:
                continue
            out.append(Finding(
                check="host-transfer", severity="ERROR", where=path,
                message=f"device_put of a live value ({_shapes(eqn)}) inside "
                        f"the jitted step — host<->device round-trip per step"))
        elif name in _CALLBACK_PRIMS:
            cb = eqn.params.get("callback")
            out.append(Finding(
                check="host-transfer", severity="ERROR", where=path,
                message=f"{name} ({getattr(cb, '__name__', cb)}) inside the "
                        f"jitted step — host callback per step"))
    return out


def _check_constant_bloat(closed, label, ctx) -> List[Finding]:
    out = []
    for i, const in enumerate(getattr(closed, "consts", ())):
        nbytes = getattr(const, "nbytes", None)
        if nbytes is None:
            try:
                nbytes = np.asarray(const).nbytes
            except Exception:
                continue
        if nbytes > CONSTANT_BLOAT_BYTES:
            shape = "x".join(map(str, np.shape(const))) or "scalar"
            dt = getattr(const, "dtype", "?")
            out.append(Finding(
                check="constant-bloat", severity="WARN",
                where=f"{label}/const[{i}]",
                message=f"captured constant {shape} {dt} "
                        f"({nbytes / 2**20:.1f} MiB) folded into the "
                        f"executable — pass it as an argument instead"))
    return out


def _check_unsharded(closed, label, ctx) -> List[Finding]:
    mesh = ctx.get("mesh")
    if mesh is None or int(np.prod(list(mesh.shape.values()))) <= 1:
        return []
    if ctx.get("inputs_sharded"):
        return []  # GSPMD propagates from sharded args; constraints optional
    sharded_prims = {"sharding_constraint", "psum", "all_gather",
                     "all_to_all", "ppermute", "reduce_scatter", "pmin",
                     "pmax", "shard_map"}
    biggest = None
    for eqn, path in walk_eqns(closed.jaxpr, label):
        if eqn.primitive.name in sharded_prims:
            return []
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            shape = getattr(aval, "shape", ())
            if len(shape) >= 2:
                size = int(np.prod(shape))
                if biggest is None or size > biggest[0]:
                    biggest = (size, eqn.primitive.name, shape, path)
    if biggest is None:
        return []
    size, prim, shape, path = biggest
    ndev = int(np.prod(list(mesh.shape.values())))
    return [Finding(
        check="unsharded-op", severity="WARN", where=path,
        message=f"mesh has {ndev} devices but the step carries no sharding "
                f"constraints, collectives, or sharded inputs — largest op "
                f"{prim} {'x'.join(map(str, shape))} runs replicated")]


def _block_dims(block_shape) -> List[Optional[int]]:
    dims: List[Optional[int]] = []
    for d in block_shape:
        dims.append(int(d) if isinstance(d, (int, np.integer)) else None)
    return dims


def _check_pallas_tiles(closed, label, ctx) -> List[Finding]:
    out = []
    seen = set()  # identical in/out block mappings -> one finding
    for eqn, path in walk_eqns(closed.jaxpr, label):
        if eqn.primitive.name != "pallas_call":
            continue
        gm = eqn.params.get("grid_mapping")
        mappings = getattr(gm, "block_mappings", None)
        if not mappings:
            continue
        for bm in mappings:
            dims = _block_dims(getattr(bm, "block_shape", ()))
            arr = getattr(getattr(bm, "array_shape_dtype", None), "shape", None)
            if len(dims) < 2:
                continue
            bad = []
            # (sublane, lane) = last two block dims; a block spanning the
            # full array dim is exempt (Mosaic pads it), as are unit dims
            # (broadcast rows / scalar lanes)
            for off, align, kind in ((1, 128, "lane"), (2, 8, "sublane")):
                if off > len(dims):
                    break
                b = dims[-off]
                if b is None or b <= 1 or b % align == 0:
                    continue
                full = arr is not None and len(arr) >= off and b == arr[-off]
                if not full:
                    bad.append(f"{kind} dim {b} % {align} != 0")
            if bad:
                shape = "x".join("?" if d is None else str(d) for d in dims)
                key = (path, shape, tuple(bad))
                if key in seen:
                    continue
                seen.add(key)
                out.append(Finding(
                    check="unaligned-pallas-tile", severity="WARN", where=path,
                    message=f"Pallas BlockSpec tile {shape} violates (8, 128) "
                            f"alignment: {'; '.join(bad)} — the kernel will "
                            f"retile per sublane (slow) or fail to lower"))
    return out


JAXPR_CHECKS: Dict[str, Callable] = {
    "dtype-promotion": _check_dtype_promotion,
    "host-transfer": _check_host_transfer,
    "constant-bloat": _check_constant_bloat,
    "unsharded-op": _check_unsharded,
    "unaligned-pallas-tile": _check_pallas_tiles,
}


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def audit_jaxpr(closed, *, label: str = "step", mesh=None,
                inputs_sharded: bool = False,
                checks: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the registered checks over a ClosedJaxpr; returns findings.

    ``mesh``/``inputs_sharded`` feed the unsharded-op check: pass the mesh
    the step will run under, and whether any argument already carries a
    non-trivial ``NamedSharding`` (GSPMD then propagates placement without
    explicit constraints)."""
    ctx = {"mesh": mesh, "inputs_sharded": inputs_sharded}
    selected = JAXPR_CHECKS if checks is None else {
        k: JAXPR_CHECKS[k] for k in checks}
    out: List[Finding] = []
    for fn in selected.values():
        try:
            out.extend(fn(closed, label, ctx))
        except Exception as e:  # a broken check must not sink the report
            out.append(Finding(
                check="auditor-internal", severity="INFO", where=label,
                message=f"check {fn.__name__} failed: "
                        f"{type(e).__name__}: {e}"))
    return out


def _leaf_is_sharded(x) -> bool:
    sh = getattr(x, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is None:
        return False
    return any(s is not None for s in spec)


def audit_fn(fn: Callable, *args: Any, label: str = "step", mesh=None,
             checks: Optional[Sequence[str]] = None,
             **kwargs: Any) -> List[Finding]:
    """Trace ``fn(*args, **kwargs)`` to a closed jaxpr and audit it.
    Sharded arguments (NamedSharding leaves) are detected automatically
    for the unsharded-op check."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    sharded = any(_leaf_is_sharded(leaf)
                  for leaf in jax.tree_util.tree_leaves((args, kwargs)))
    return audit_jaxpr(closed, label=label, mesh=mesh,
                       inputs_sharded=sharded, checks=checks)


#: the checks that matter for a serving/generation closure: a host
#: round-trip per emitted token, weights folded into the executable, and
#: the decode engine's kernel tiles.  (dtype-promotion is deliberately
#: excluded — a decode program legitimately runs its statistics in f32,
#: and unsharded-op needs a training mesh to mean anything.)
DECODE_CHECKS: Sequence[str] = ("host-transfer", "constant-bloat",
                                "unaligned-pallas-tile")


#: primitives that MATERIALIZE a fresh array (vs transform an existing one)
#: — the ways a sparse program accidentally densifies a table
_MATERIALIZE_PRIMS = ("broadcast_in_dim", "iota")

#: container/routing primitives whose outvars merely CARRY operands through
#: (the sharded table legitimately rides shard_map, the bad-step guard's
#: cond, scans, jit boundaries).  Their BODIES are still walked — a
#: densifying eqn inside is flagged on its own leaf primitive.
_CARRIER_PRIMS = frozenset({
    "shard_map", "cond", "while", "scan", "pjit", "xla_call", "core_call",
    "closed_call", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "remat", "remat2",
    "checkpoint", "custom_vjp_call_custom_transpose", "device_put",
    "sharding_constraint", "optimization_barrier",
})


def audit_no_dense_rows(closed, *, full_rows: int,
                        shard_rows: Optional[int] = None,
                        label: str = "step") -> List[Finding]:
    """The pserver "never densify" gate: ERROR on any equation that
    produces a ``[V, ...]``-shaped value (``full_rows`` = the GLOBAL padded
    vocab — under shard_map no legal per-shard value carries it), and on
    any broadcast/iota that conjures a fresh ``[Vs, ...]`` per-shard dense
    temp (``shard_rows``) — a zeros-of-table-shape gradient or optimizer
    buffer.  Gathers/scatters ON the table shard itself are legal: they
    transform the existing (donated) buffer rather than materialize a new
    one."""
    out: List[Finding] = []
    for eqn, path in walk_eqns(closed.jaxpr, label):
        prim = eqn.primitive.name
        for v in eqn.outvars:
            shape = getattr(getattr(v, "aval", None), "shape", ())
            if len(shape) >= 2 and shape[0] == full_rows \
                    and prim not in _CARRIER_PRIMS:
                out.append(Finding(
                    check="dense-table-temp", severity="ERROR", where=path,
                    message=f"{eqn.primitive.name} materializes a "
                            f"full-table value "
                            f"{'x'.join(map(str, shape))} (vocab dim "
                            f"{full_rows}) — the sparse path must never "
                            f"densify the table"))
            elif (shard_rows is not None and len(shape) >= 2
                  and shape[0] == shard_rows
                  and prim in _MATERIALIZE_PRIMS):
                out.append(Finding(
                    check="dense-table-temp", severity="ERROR", where=path,
                    message=f"{eqn.primitive.name} conjures a per-shard "
                            f"dense temp {'x'.join(map(str, shape))} "
                            f"(shard rows {shard_rows}) — row-sparse "
                            f"updates must stay O(touched-rows)"))
    return out


def audit_amp_matmuls(closed, *, label: str = "step",
                      allow: Sequence[str] = ()) -> List[Finding]:
    """The ``lint --amp`` gate (docs/mixed_precision.md): under ``--amp``
    the compiled step must contain ZERO all-f32 ``dot_general``/conv
    equations outside the allowlist — a silently-promoted matmul costs 2x
    the MXU cycles exactly where the mode exists to save them.  The f32
    allowlist (BN statistics, softmax/logsumexp reductions, the loss) is
    made of REDUCTIONS, not matmuls, so by default nothing is exempt;
    ``allow`` takes provenance-path substrings for deliberately-f32 dots
    (e.g. a numerically-fragile head a model pins wide).

    Escalates the dtype-promotion auditor's WARN heuristic to a hard
    ERROR with an explicit opt-out, and additionally ERRORs when the trace
    contains NO low-precision MXU op at all — an "amp" step that never
    reached bf16 means the mode silently did not engage."""
    mxu = [(eqn, path) for eqn, path in walk_eqns(closed.jaxpr, label)
           if eqn.primitive.name in _MXU_PRIMS]
    out: List[Finding] = []
    low = 0
    for eqn, path in mxu:
        fdts = _float_dtypes(eqn)
        if any(d in _LOW_PRECISION for d in fdts):
            low += 1
            continue
        if fdts and all(d == "float32" for d in fdts):
            if any(a in path for a in allow):
                continue
            out.append(Finding(
                check="amp-f32-matmul", severity="ERROR", where=path,
                message=f"{eqn.primitive.name} ({_shapes(eqn)}) runs "
                        f"wholly in f32 under --amp — outside the "
                        f"BN/softmax/loss allowlist every matmul/conv "
                        f"must take bf16 operands (2x MXU cycles + HBM "
                        f"otherwise)"))
    if mxu and not low:
        out.append(Finding(
            check="amp-f32-matmul", severity="ERROR", where=label,
            message=f"no bf16 matmul/conv anywhere in the --amp step "
                    f"({len(mxu)} MXU eqns, all f32) — the amp dtype "
                    f"policy never engaged (is FLAGS.amp set at trace "
                    f"time?)"))
    return out


def audit_decode(fn: Callable, *args: Any, label: str = "decode",
                 **kwargs: Any) -> List[Finding]:
    """Audit a decode/generation closure (``ops/decode.py`` engine output,
    a ``SequenceGenerator`` run, a ``v2.infer`` forward) with the decode
    check set.  The traversal sees through the engine's early-exit
    ``while`` (``jaxpr_walk.eqn_subjaxprs`` recurses into cond/body), so
    kernel BlockSpecs and callbacks inside the token loop are covered —
    the acceptance bar is ERROR-free, i.e. host-transfer-free."""
    return audit_fn(fn, *args, label=label, checks=DECODE_CHECKS, **kwargs)
