"""Shared jaxpr traversal — ONE definition of "recurse into sub-jaxprs".

Grown out of ``bench.py``'s FLOPs walker, which recursed into *every*
jaxpr-valued param of every primitive: primitives carrying several
sub-jaxprs (``custom_vjp_call`` holds the primal *and* fwd/bwd rules,
``linear_solve`` holds four) were double-counted.  Here recursion is
per-primitive into the known key — ``scan``/``while``/``cond`` get their
trip-count/branch semantics, everything else takes the FIRST of
``call_jaxpr``/``jaxpr``/``fun_jaxpr`` (the primal computation the
primitive will actually execute once).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence, Set, Tuple

__all__ = ["eqn_subjaxprs", "walk_eqns", "find_primitives",
           "aval_bytes", "peak_live_bytes"]

#: primal-computation param keys, most specific first; exactly ONE is taken
_PRIMAL_KEYS = ("call_jaxpr", "jaxpr", "fun_jaxpr")


def _as_jaxpr(v):
    """Unwrap ClosedJaxpr -> Jaxpr; None for non-jaxpr values."""
    inner = getattr(v, "jaxpr", v)
    return inner if hasattr(inner, "eqns") else None


def eqn_subjaxprs(eqn) -> Iterator[Tuple[object, float]]:
    """Yield ``(jaxpr, multiplier)`` for the sub-jaxprs the primitive
    executes.  ``scan`` bodies carry their trip count as the multiplier
    (the case XLA's own FLOPs counter gets wrong); ``cond`` yields every
    branch with multiplier 1 — callers wanting max-over-branches (FLOPs)
    must special-case ``cond`` themselves."""
    name = eqn.primitive.name
    params = eqn.params
    if name == "scan":
        inner = _as_jaxpr(params.get("jaxpr"))
        if inner is not None:
            yield inner, float(params.get("length", 1))
        return
    if name == "while":
        for key in ("cond_jaxpr", "body_jaxpr"):
            inner = _as_jaxpr(params.get(key))
            if inner is not None:
                yield inner, 1.0
        return
    if name == "cond":
        for branch in params.get("branches", ()):
            inner = _as_jaxpr(branch)
            if inner is not None:
                yield inner, 1.0
        return
    for key in _PRIMAL_KEYS:
        inner = _as_jaxpr(params.get(key))
        if inner is not None:
            yield inner, 1.0
            return
    # unknown primitive without a known key: take the FIRST jaxpr-valued
    # param only — never sum over all of them (that is the double-count)
    for v in params.values():
        inner = _as_jaxpr(v)
        if inner is not None:
            yield inner, 1.0
            return


def walk_eqns(jaxpr, path: str = "", *,
              max_depth: int = 32) -> Iterator[Tuple[object, str]]:
    """Depth-first (eqn, provenance-path) pairs over ``jaxpr`` and every
    sub-jaxpr.  Paths look like ``eqn[4]:scan/eqn[1]:dot_general``."""
    jaxpr = _as_jaxpr(jaxpr) or jaxpr
    if max_depth <= 0:
        return
    for i, eqn in enumerate(getattr(jaxpr, "eqns", ())):
        here = f"{path}/eqn[{i}]:{eqn.primitive.name}" if path else \
            f"eqn[{i}]:{eqn.primitive.name}"
        yield eqn, here
        for inner, _mult in eqn_subjaxprs(eqn):
            yield from walk_eqns(inner, here, max_depth=max_depth - 1)


def find_primitives(jaxpr, names: Set[str],
                    path: str = "") -> List[Tuple[str, str]]:
    """All (primitive-name, path) occurrences of ``names`` anywhere in the
    (possibly nested) jaxpr — e.g. residual scan/while after an unrolling
    export (config/deploy._unrolled_scans verification)."""
    return [(eqn.primitive.name, p) for eqn, p in walk_eqns(jaxpr, path)
            if eqn.primitive.name in names]


def aval_bytes(aval) -> int:
    """HBM bytes of one abstract value (0 for tokens/abstract avals)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except (TypeError, ValueError):  # symbolic dim: count as 1
            pass
    return n * getattr(dtype, "itemsize", 4)


def _is_var(v) -> bool:
    return hasattr(v, "aval") and not hasattr(v, "val")  # Var, not Literal


def _last_uses(jaxpr) -> Dict[object, int]:
    """var -> index of the LAST eqn reading it (len(eqns) for jaxpr
    outputs, which stay live to the end; absent = never read)."""
    last: Dict[object, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if _is_var(v):
                last[v] = i
    for v in jaxpr.outvars:
        if _is_var(v):
            last[v] = len(jaxpr.eqns)
    return last


def _open_peak(jaxpr) -> Tuple[int, int]:
    """(peak live bytes, boundary bytes) of an OPEN jaxpr, all inputs
    treated non-donated.  ``boundary`` = invars + constvars + outvars —
    the bytes that alias the enclosing scope's buffers, which a caller
    subtracts to get the sub-jaxpr's *transient* contribution."""
    stats = _liveness(jaxpr, donated=frozenset())
    boundary = (stats["args_bytes"] + stats["consts_bytes"]
                + stats["out_bytes"])
    return stats["peak_bytes"], boundary


def _inner_extra(eqn) -> int:
    """Transient HBM a primitive's sub-jaxpr needs beyond its own
    boundary buffers (worst branch for ``cond``; one iteration's
    transients for ``scan``/``while`` — buffers are reused per step)."""
    extra = 0
    for inner, _mult in eqn_subjaxprs(eqn):
        peak, boundary = _open_peak(inner)
        extra = max(extra, max(0, peak - boundary))
    return extra


def _liveness(jaxpr, donated: frozenset) -> Dict[str, int]:
    last = _last_uses(jaxpr)
    alive: Set[object] = set()
    cur = 0
    args_bytes = consts_bytes = 0
    for v in jaxpr.constvars:
        consts_bytes += aval_bytes(v.aval)
    for v in jaxpr.invars:
        args_bytes += aval_bytes(v.aval)
    for v in list(jaxpr.constvars) + list(jaxpr.invars):
        if v in alive:
            continue
        alive.add(v)
        cur += aval_bytes(v.aval)
        if v not in donated:
            # the caller owns a non-donated input: its buffer exists for
            # the whole program whether or not we still read it
            last[v] = max(last.get(v, 0), len(jaxpr.eqns))
    peak = cur
    # free never-read donated inputs/consts immediately
    for v in list(alive):
        if last.get(v, -1) < 0:
            cur -= aval_bytes(v.aval)
            alive.discard(v)
    for i, eqn in enumerate(jaxpr.eqns):
        born = sum(aval_bytes(v.aval) for v in eqn.outvars)
        peak = max(peak, cur + born + _inner_extra(eqn))
        for v in eqn.outvars:
            if v not in alive:
                alive.add(v)
                cur += aval_bytes(v.aval)
        for v in list(eqn.invars) + list(eqn.outvars):
            if _is_var(v) and v in alive and last.get(v, -1) <= i:
                cur -= aval_bytes(v.aval)
                alive.discard(v)
    out_bytes = sum(aval_bytes(v.aval) for v in jaxpr.outvars
                    if hasattr(v, "aval"))
    return {"peak_bytes": peak, "args_bytes": args_bytes,
            "consts_bytes": consts_bytes, "out_bytes": out_bytes,
            "end_bytes": cur}


def peak_live_bytes(closed, donate_argnums: Sequence[int] = ()
                    ) -> Dict[str, int]:
    """Static peak-live-bytes estimate of a (closed) jaxpr.

    A liveness walk over eqn outputs: every buffer is born at its
    producing eqn, dies after its last read, non-donated arguments and
    jaxpr outputs stay live for the whole program, and donated arguments
    are credited back at their donation point (last read — XLA reuses the
    buffer for a shape/dtype-matched output from there).  Sub-jaxprs
    (scan/while/cond bodies) contribute their transient peak on top of
    the live set at their eqn.  Returns ``{"peak_bytes", "args_bytes",
    "consts_bytes", "out_bytes", "donated_bytes"}``."""
    jaxpr = getattr(closed, "jaxpr", closed)
    donated = frozenset(jaxpr.invars[i] for i in donate_argnums
                        if 0 <= i < len(jaxpr.invars))
    stats = _liveness(jaxpr, donated)
    stats["donated_bytes"] = sum(aval_bytes(v.aval) for v in donated)
    del stats["end_bytes"]
    return stats


def hlo_control_flow(hlo_text: str) -> List[str]:
    """Control-flow op mnemonics present in an HLO/StableHLO text dump —
    the post-lowering half of the scan-unrolling verification: after
    ``export_aot_hlo(unroll_scans=True)`` the module should contain no
    ``while``/``conditional`` ops (the trace-time patch is best-effort;
    anything that bound ``lax.scan`` early, or used ``while_loop``
    directly, still lowers a loop)."""
    found = []
    for op in ("while", "conditional"):
        # HLO text: `%x = ... while(...)`; StableHLO: `"stablehlo.while"` /
        # `stablehlo.while(` — match the op mnemonic at a call position
        if f" {op}(" in hlo_text or f".{op}\"" in hlo_text or \
                f"stablehlo.{op}" in hlo_text:
            found.append(op)
    return found
