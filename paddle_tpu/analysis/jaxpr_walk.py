"""Shared jaxpr traversal — ONE definition of "recurse into sub-jaxprs".

Grown out of ``bench.py``'s FLOPs walker, which recursed into *every*
jaxpr-valued param of every primitive: primitives carrying several
sub-jaxprs (``custom_vjp_call`` holds the primal *and* fwd/bwd rules,
``linear_solve`` holds four) were double-counted.  Here recursion is
per-primitive into the known key — ``scan``/``while``/``cond`` get their
trip-count/branch semantics, everything else takes the FIRST of
``call_jaxpr``/``jaxpr``/``fun_jaxpr`` (the primal computation the
primitive will actually execute once).
"""

from __future__ import annotations

from typing import Iterator, List, Set, Tuple

__all__ = ["eqn_subjaxprs", "walk_eqns", "find_primitives"]

#: primal-computation param keys, most specific first; exactly ONE is taken
_PRIMAL_KEYS = ("call_jaxpr", "jaxpr", "fun_jaxpr")


def _as_jaxpr(v):
    """Unwrap ClosedJaxpr -> Jaxpr; None for non-jaxpr values."""
    inner = getattr(v, "jaxpr", v)
    return inner if hasattr(inner, "eqns") else None


def eqn_subjaxprs(eqn) -> Iterator[Tuple[object, float]]:
    """Yield ``(jaxpr, multiplier)`` for the sub-jaxprs the primitive
    executes.  ``scan`` bodies carry their trip count as the multiplier
    (the case XLA's own FLOPs counter gets wrong); ``cond`` yields every
    branch with multiplier 1 — callers wanting max-over-branches (FLOPs)
    must special-case ``cond`` themselves."""
    name = eqn.primitive.name
    params = eqn.params
    if name == "scan":
        inner = _as_jaxpr(params.get("jaxpr"))
        if inner is not None:
            yield inner, float(params.get("length", 1))
        return
    if name == "while":
        for key in ("cond_jaxpr", "body_jaxpr"):
            inner = _as_jaxpr(params.get(key))
            if inner is not None:
                yield inner, 1.0
        return
    if name == "cond":
        for branch in params.get("branches", ()):
            inner = _as_jaxpr(branch)
            if inner is not None:
                yield inner, 1.0
        return
    for key in _PRIMAL_KEYS:
        inner = _as_jaxpr(params.get(key))
        if inner is not None:
            yield inner, 1.0
            return
    # unknown primitive without a known key: take the FIRST jaxpr-valued
    # param only — never sum over all of them (that is the double-count)
    for v in params.values():
        inner = _as_jaxpr(v)
        if inner is not None:
            yield inner, 1.0
            return


def walk_eqns(jaxpr, path: str = "", *,
              max_depth: int = 32) -> Iterator[Tuple[object, str]]:
    """Depth-first (eqn, provenance-path) pairs over ``jaxpr`` and every
    sub-jaxpr.  Paths look like ``eqn[4]:scan/eqn[1]:dot_general``."""
    jaxpr = _as_jaxpr(jaxpr) or jaxpr
    if max_depth <= 0:
        return
    for i, eqn in enumerate(getattr(jaxpr, "eqns", ())):
        here = f"{path}/eqn[{i}]:{eqn.primitive.name}" if path else \
            f"eqn[{i}]:{eqn.primitive.name}"
        yield eqn, here
        for inner, _mult in eqn_subjaxprs(eqn):
            yield from walk_eqns(inner, here, max_depth=max_depth - 1)


def find_primitives(jaxpr, names: Set[str],
                    path: str = "") -> List[Tuple[str, str]]:
    """All (primitive-name, path) occurrences of ``names`` anywhere in the
    (possibly nested) jaxpr — e.g. residual scan/while after an unrolling
    export (config/deploy._unrolled_scans verification)."""
    return [(eqn.primitive.name, p) for eqn, p in walk_eqns(jaxpr, path)
            if eqn.primitive.name in names]


def hlo_control_flow(hlo_text: str) -> List[str]:
    """Control-flow op mnemonics present in an HLO/StableHLO text dump —
    the post-lowering half of the scan-unrolling verification: after
    ``export_aot_hlo(unroll_scans=True)`` the module should contain no
    ``while``/``conditional`` ops (the trace-time patch is best-effort;
    anything that bound ``lax.scan`` early, or used ``while_loop``
    directly, still lowers a loop)."""
    found = []
    for op in ("while", "conditional"):
        # HLO text: `%x = ... while(...)`; StableHLO: `"stablehlo.while"` /
        # `stablehlo.while(` — match the op mnemonic at a call position
        if f" {op}(" in hlo_text or f".{op}\"" in hlo_text or \
                f"stablehlo.{op}" in hlo_text:
            found.append(op)
    return found
