"""Typed lint findings + the suppression plane.

The analog surface: legacy Paddle's ``config_parser.py`` raised eagerly on
bad model configs before any kernel ran.  On a JAX/XLA stack the failure
modes worth catching early are different (silent f32 promotion, host
transfers inside the step, constant bloat, unaligned Pallas tiles, tracer
leaks) and they are *findings*, not exceptions: a report the CLI/CI can
gate on, with provenance back to a source line (AST checks) or a jaxpr
equation path (auditor checks).

Suppression:
- ``# tpu-lint: disable=<check>[,<check>...]`` (or ``disable=all``) on the
  flagged line, or on the ``def`` line of the enclosing function to cover
  its whole body (AST findings only — jaxpr findings have no source line).
- an allowlist file (one entry per line, ``<check-id> [message substring]``;
  ``#`` comments) applied to every finding, including auditor ones.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "SEVERITIES",
    "severity_at_least",
    "errors_summary",
    "line_suppressions",
    "load_allowlist",
    "apply_allowlist",
    "format_findings",
]

#: ordered weakest -> strongest
SEVERITIES = ("INFO", "WARN", "ERROR")


def severity_at_least(findings: Iterable["Finding"], level: str) -> List["Finding"]:
    floor = SEVERITIES.index(level)
    return [f for f in findings if SEVERITIES.index(f.severity) >= floor]


def errors_summary(findings) -> Optional[str]:
    """One ``check@location: message`` line per ERROR finding, joined
    with '; ' — THE formatting of every fail-fast audit gate
    (``v2.infer(audit=True)``, ``serving.check_serving``), so the two
    surfaces can never drift.  None when no ERROR survives."""
    bad = [f for f in findings if f.severity == "ERROR"]
    if not bad:
        return None
    return "; ".join(f"{f.check}@{f.where or f.location()}: {f.message}"
                     for f in bad)


@dataclass(frozen=True)
class Finding:
    """One lint finding.

    ``file``/``line`` carry AST provenance; ``where`` carries jaxpr-eqn
    provenance (e.g. ``train_step/eqn[12]:scan/eqn[3]:dot_general``).  A
    finding has exactly one of the two.
    """

    check: str
    severity: str  # one of SEVERITIES
    message: str
    file: Optional[str] = None
    line: Optional[int] = None
    where: Optional[str] = None

    def location(self) -> str:
        if self.file is not None:
            return f"{self.file}:{self.line}" if self.line else self.file
        return self.where or "<unknown>"

    def to_dict(self) -> Dict:
        d = {"check": self.check, "severity": self.severity,
             "message": self.message, "location": self.location()}
        if self.file is not None:
            d["file"] = self.file
            d["line"] = self.line
        if self.where is not None:
            d["where"] = self.where
        return d

    def format(self) -> str:
        return f"{self.location()}: {self.severity} [{self.check}] {self.message}"


# ---------------------------------------------------------------------------
# Suppression
# ---------------------------------------------------------------------------

_DIRECTIVE = re.compile(r"#\s*tpu-lint:\s*disable=([\w\-,*]+|all)")


def line_suppressions(source: str) -> Dict[int, frozenset]:
    """{1-based line -> frozenset of suppressed check ids ('all' wildcard)}."""
    out: Dict[int, frozenset] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _DIRECTIVE.search(line)
        if m:
            names = frozenset(n.strip() for n in m.group(1).split(",") if n.strip())
            out[i] = names
    return out


def suppressed(check: str, line: Optional[int],
               suppressions: Dict[int, frozenset],
               func_ranges: Sequence[Tuple[int, int]] = ()) -> bool:
    """True when ``check`` at ``line`` is silenced by a same-line directive
    or by a directive on the ``def`` line of an enclosing function (the
    (def_line, end_line) pairs in ``func_ranges``)."""

    def hit(names: frozenset) -> bool:
        return "all" in names or "*" in names or check in names

    if line is None:
        return False
    names = suppressions.get(line)
    if names and hit(names):
        return True
    for def_line, end_line in func_ranges:
        if def_line <= line <= end_line:
            names = suppressions.get(def_line)
            if names and hit(names):
                return True
    return False


def load_allowlist(path: str) -> List[Tuple[str, str]]:
    """Parse an allowlist file into (check, message-substring) pairs; an
    empty substring matches any message for that check."""
    entries: List[Tuple[str, str]] = []
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(None, 1)
            entries.append((parts[0], parts[1] if len(parts) > 1 else ""))
    return entries


def apply_allowlist(findings: Iterable[Finding],
                    entries: Sequence[Tuple[str, str]]) -> List[Finding]:
    def allowed(f: Finding) -> bool:
        for check, sub in entries:
            # substring matches the MESSAGE only — matching the formatted
            # line would let 'tests' or 'ERROR' accidentally suppress by
            # path/severity
            if check in ("all", "*", f.check) and (not sub or sub in f.message):
                return True
        return False

    return [f for f in findings if not allowed(f)]


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------


def _counts(findings: Sequence[Finding]) -> Dict[str, int]:
    c = {s: 0 for s in SEVERITIES}
    for f in findings:
        c[f.severity] += 1
    return c


#: Finding severity -> SARIF result level
_SARIF_LEVEL = {"ERROR": "error", "WARN": "warning", "INFO": "note"}


def _sarif(ranked: Sequence[Finding], counts: Dict[str, int]) -> str:
    """SARIF 2.1.0 — the interchange format CI annotators (GitHub code
    scanning, VS Code SARIF viewer) ingest.  AST findings carry a
    physicalLocation (file + startLine); jaxpr findings carry their
    eqn-path provenance as a logicalLocation fullyQualifiedName."""
    rule_ix: Dict[str, int] = {}
    rules: List[Dict] = []
    results: List[Dict] = []
    for f in ranked:
        if f.check not in rule_ix:
            rule_ix[f.check] = len(rules)
            rules.append({"id": f.check,
                          "defaultConfiguration":
                              {"level": _SARIF_LEVEL[f.severity]}})
        if f.file is not None:
            phys: Dict = {"artifactLocation": {"uri": f.file}}
            if f.line:
                phys["region"] = {"startLine": int(f.line)}
            loc = {"physicalLocation": phys}
        else:
            loc = {"logicalLocations":
                   [{"fullyQualifiedName": f.where or "<unknown>"}]}
        results.append({
            "ruleId": f.check,
            "ruleIndex": rule_ix[f.check],
            "level": _SARIF_LEVEL[f.severity],
            "message": {"text": f.message},
            "locations": [loc],
        })
    return json.dumps({
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "paddle-tpu-lint",
                "informationUri":
                    "https://github.com/dzhwinter/Paddle",
                "rules": rules,
            }},
            "results": results,
            "properties": {"counts": counts},
        }],
    }, indent=1)


def format_findings(findings: Sequence[Finding], fmt: str = "text") -> str:
    """Render findings for the CLI: 'text' (one line per finding +
    summary), 'json' (machine-readable, stable keys), or 'sarif'
    (SARIF 2.1.0 for CI annotation surfaces)."""
    order = {s: i for i, s in enumerate(SEVERITIES)}
    ranked = sorted(findings,
                    key=lambda f: (-order[f.severity], f.file or "",
                                   f.line or 0, f.check))
    if fmt == "sarif":
        return _sarif(ranked, _counts(findings))
    if fmt == "json":
        return json.dumps({
            "findings": [f.to_dict() for f in ranked],
            "counts": _counts(findings),
        }, indent=1)
    lines = [f.format() for f in ranked]
    c = _counts(findings)
    lines.append(f"{len(findings)} finding(s): {c['ERROR']} error(s), "
                 f"{c['WARN']} warning(s), {c['INFO']} info")
    return "\n".join(lines)
