"""``lint --amp`` — prove the mixed-precision step is actually bf16.

The whole point of ``--amp`` (docs/mixed_precision.md) is that every
matmul/conv in the compiled train step takes bf16 operands — the f32
allowlist (BN statistics, softmax/logsumexp reductions, the loss) is made
of reductions, which this gate does not touch.  A single silently-promoted
``dot_general`` costs 2x MXU cycles exactly where the mode exists to save
them, and nothing at runtime would ever tell you.

This audit builds a representative trainer — embedding, stacked LSTM (the
scan-heavy shape the MFU push targets), batch-norm'd fc head, softmax CE —
with ``FLAGS.amp`` forced on, traces the REAL jitted step (forward +
backward + loss scaling + guarded fused optimizer apply, the exact closure
``train_batch`` compiles), and ERRORs on

1. any all-f32 ``dot_general``/``conv_general_dilated`` outside the
   allowlist (``analysis.audit_amp_matmuls``), and
2. an amp trace containing NO bf16 MXU op at all (the policy never
   engaged).

The same check runs over user models via ``SGDTrainer.audit`` +
``audit_amp_matmuls``, and tests assert it over a real model's step
(tests/test_amp.py).
"""

from __future__ import annotations

from typing import List, Sequence

from paddle_tpu.analysis.findings import Finding

__all__ = ["audit_amp_step"]


def _amp_trainer():
    import numpy as np

    import paddle_tpu.nn as nn
    from paddle_tpu.models import lstm_benchmark_net
    from paddle_tpu.param.optimizers import Adam
    from paddle_tpu.trainer import SGDTrainer

    nn.reset_naming()
    cost, _ = lstm_benchmark_net(256, emb_dim=32, hid_dim=32, num_layers=1)
    tr = SGDTrainer(cost, Adam(learning_rate=1e-3), seed=0)
    rs = np.random.RandomState(0)
    B, T = 4, 8
    feed = {
        "words": (rs.randint(3, 256, (B, T)).astype(np.int32),
                  np.full((B,), T, np.int32)),
        "label": rs.randint(0, 2, (B, 1)).astype(np.int32),
    }
    return tr, feed


def audit_amp_step(allow: Sequence[str] = ()) -> List[Finding]:
    """Trace the representative trainer step under ``--amp`` and gate the
    zero-non-allowlisted-f32-matmuls contract; returns findings."""
    import jax

    from paddle_tpu.analysis.jaxpr_audit import audit_amp_matmuls
    from paddle_tpu.utils.flags import FLAGS

    findings: List[Finding] = []
    keep = FLAGS.amp
    try:
        FLAGS.amp = True
        tr, feed = _amp_trainer()
        rng = jax.random.PRNGKey(0)
        closed = jax.make_jaxpr(tr._step_fn)(
            tr.params, tr.state, tr.opt_state, {}, rng, feed)
        findings.extend(audit_amp_matmuls(closed, label="amp:train_step",
                                          allow=allow))
    except Exception as e:  # a step that fails to trace is itself a finding
        findings.append(Finding(
            check="amp-build", severity="ERROR", where="amp:train_step",
            message=f"amp audit failed to build/trace the step: "
                    f"{type(e).__name__}: {e}"))
    finally:
        FLAGS.amp = keep
    return findings
