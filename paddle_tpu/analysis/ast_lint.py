"""AST trace-safety linter — Python-level hazards the tracer can't report well.

Scans sources (``paddle_tpu/``, ``demo/``, user configs) for patterns that
break or silently degrade under ``jax.jit``.  A function is considered a
*jit context* when it is decorated with ``jax.jit``/``jit``/``pmap`` (also
via ``functools.partial``) or passed by name to a ``jax.jit(...)`` call in
the same module; nested ``def``s inside a jit context are traced too and
inherit it.

Checks (ids, severity):

- ``tracer-leak`` (ERROR): ``float``/``int``/``bool``/``np.asarray``/
  ``np.array``/np scalar ctors, or ``.item()``/``.tolist()``, applied to a
  value derived from a jit-context parameter — concretizes a tracer
  (``ConcretizationTypeError`` at best, a silent constant at worst).
- ``tracer-branch`` (WARN): ``if``/``while`` on a parameter-derived value
  inside a jit context (``is None`` tests and ``.shape``/``.ndim``/
  ``.dtype``/``.size``/``len()`` inspection are static and exempt).
- ``impure-call`` (WARN): ``time.time``/``datetime.now``/``np.random.*``/
  ``random.*`` inside a jit context — evaluated ONCE at trace time, frozen
  into the executable (the Date-impurity class).
- ``set-iter`` (WARN): iterating a ``set`` inside a jit context —
  nondeterministic program order across processes (pytree/eqn instability).
- ``jit-in-loop`` (WARN): constructing ``jax.jit(...)``/``pmap(...)``
  inside a ``for``/``while`` body anywhere — a fresh jit cache per
  iteration (the retrace-storm class).

Suppression: ``# tpu-lint: disable=<check>`` on the flagged line, or on the
``def`` line of an enclosing function (see ``findings``).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddle_tpu.analysis.findings import Finding, line_suppressions, suppressed

__all__ = ["lint_source", "lint_file", "lint_path", "AST_CHECKS"]

AST_CHECKS = ("tracer-leak", "tracer-branch", "impure-call", "set-iter",
              "jit-in-loop")

_JIT_NAMES = {"jit", "pmap", "pjit"}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}
_NP_CASTS = {"asarray", "array", "float32", "float64", "int32", "int64",
             "asanyarray", "ascontiguousarray"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval"}
_IMPURE = {
    ("time", "time"), ("time", "perf_counter"), ("time", "monotonic"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("random", "random"), ("random", "randint"), ("random", "uniform"),
    ("random", "choice"), ("random", "shuffle"),
}


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _module_aliases(tree: ast.Module) -> Dict[str, str]:
    """local alias -> module name for numpy/jax/time/datetime/random."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _is_jit_expr(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """True when ``node`` evaluates to a jit-like transform."""
    dotted = _dotted(node)
    if dotted is None:
        if isinstance(node, ast.Call):
            # functools.partial(jax.jit, ...) / partial(jit, ...)
            head = _dotted(node.func) or ""
            if head.split(".")[-1] == "partial" and node.args:
                return _is_jit_expr(node.args[0], aliases)
        return False
    leaf = dotted.split(".")[-1]
    if leaf not in _JIT_NAMES:
        return False
    root = dotted.split(".")[0]
    target = aliases.get(root)
    if target is not None:
        # import provenance is authoritative: `from numba import jit` is
        # NOT a jax transform
        return target == "jax" or target.startswith("jax.")
    # bare un-imported `jit`/`pmap` (shadowed/local): assume jax's
    return root in _JIT_NAMES


def _jit_context_functions(tree: ast.Module,
                           aliases: Dict[str, str]) -> List[ast.AST]:
    """FunctionDefs that are jit contexts: decorated with a jit transform,
    or referenced by name as the first argument of a jit call."""
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    marked: List[ast.AST] = []
    seen: Set[int] = set()

    def mark(fn: ast.AST) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            marked.append(fn)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(d, aliases) for d in node.decorator_list):
                mark(node)
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func, aliases):
            if node.args and isinstance(node.args[0], ast.Name):
                for fn in defs.get(node.args[0].id, []):
                    mark(fn)
    return marked


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


class _TaintedNames(ast.NodeVisitor):
    """Names used in an expression, skipping static-inspection subtrees
    (``x.shape`` / ``len(x)`` / ``isinstance(x, ...)`` reads are trace-safe)."""

    def __init__(self) -> None:
        self.names: Set[str] = set()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in _STATIC_ATTRS:
            return  # x.shape[0] etc. — static under trace
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        head = (_dotted(node.func) or "").split(".")[-1]
        if head in ("len", "isinstance", "getattr", "hasattr", "type"):
            return
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)


def _names_in(node: ast.AST) -> Set[str]:
    v = _TaintedNames()
    v.visit(node)
    return v.names


def _assign_targets(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for t in ast.walk(node):
        if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store):
            out.add(t.id)
    return out


class _JitBodyLinter(ast.NodeVisitor):
    """Walks one jit-context function body with a simple forward taint set
    seeded from the parameters."""

    def __init__(self, fn: ast.AST, aliases: Dict[str, str],
                 filename: str) -> None:
        self.fn = fn
        self.aliases = aliases
        self.filename = filename
        self.tainted: Set[str] = _param_names(fn)
        self.findings: List[Finding] = []

    def _emit(self, check: str, severity: str, node: ast.AST,
              message: str) -> None:
        self.findings.append(Finding(
            check=check, severity=severity, message=message,
            file=self.filename, line=getattr(node, "lineno", None)))

    def _is_tainted(self, node: ast.AST) -> bool:
        return bool(_names_in(node) & self.tainted)

    # -- taint propagation ------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._is_tainted(node.value):
            self.tainted |= _assign_targets(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self._is_tainted(node.value) or self._is_tainted(node.target):
            self.tainted |= _assign_targets(node.target)
        self.generic_visit(node)

    def _taint_for_target(self, node: ast.For) -> None:
        # `for row in xs:` — the loop variable derives from the iterable
        if self._is_tainted(node.iter):
            self.tainted |= _assign_targets(node.target)

    # -- checks -----------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func) or ""
        parts = dotted.split(".")
        leaf = parts[-1]
        root = self.aliases.get(parts[0], parts[0]) if parts[0] else ""
        args_tainted = any(self._is_tainted(a) for a in node.args)

        if leaf in _CAST_BUILTINS and len(parts) == 1 and args_tainted:
            self._emit("tracer-leak", "ERROR", node,
                       f"{leaf}() on a traced value inside a jitted "
                       f"function — concretizes the tracer")
        elif (leaf in _NP_CASTS and root.startswith("numpy")
              and args_tainted):
            self._emit("tracer-leak", "ERROR", node,
                       f"{dotted}() on a traced value inside a jitted "
                       f"function — forces a host transfer / trace break")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in ("item", "tolist")
              and self._is_tainted(node.func.value)):
            self._emit("tracer-leak", "ERROR", node,
                       f".{node.func.attr}() on a traced value inside a "
                       f"jitted function — concretizes the tracer")
        elif len(parts) >= 2:
            mod = self.aliases.get(parts[0], parts[0]).split(".")[-1]
            if (mod, leaf) in _IMPURE or \
                    (root.startswith("numpy") and parts[-2] == "random"):
                self._emit("impure-call", "WARN", node,
                           f"{dotted}() inside a jitted function is "
                           f"evaluated once at trace time and frozen into "
                           f"the executable")
        self.generic_visit(node)

    def _branch(self, node: ast.AST, kind: str) -> None:
        test = node.test
        # `x is None` / `x is not None` — static trace-time dispatch
        if isinstance(test, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return
        if self._is_tainted(test):
            self._emit("tracer-branch", "WARN", node,
                       f"`{kind}` on a traced value inside a jitted function "
                       f"— raises TracerBoolConversionError; use lax.cond/"
                       f"jnp.where")

    def visit_If(self, node: ast.If) -> None:
        self._branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._branch(node, "while")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self._taint_for_target(node)
        it = node.iter
        if isinstance(it, ast.Set) or (
                isinstance(it, ast.Call)
                and (_dotted(it.func) or "") == "set"):
            self._emit("set-iter", "WARN", node,
                       "iterating a set inside a jitted function — "
                       "nondeterministic eqn/pytree order across processes")
        self.generic_visit(node)


class _JitInLoop(ast.NodeVisitor):
    """Module-wide: jit construction inside a loop body (retrace storm)."""

    def __init__(self, aliases: Dict[str, str], filename: str) -> None:
        self.aliases = aliases
        self.filename = filename
        self.findings: List[Finding] = []
        self._loop_depth = 0

    def _loop(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_While = _loop

    def visit_FunctionDef(self, node) -> None:
        # a def inside a loop resets loop context for its body
        saved, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        if self._loop_depth > 0 and _is_jit_expr(node.func, self.aliases):
            self.findings.append(Finding(
                check="jit-in-loop", severity="WARN",
                file=self.filename, line=node.lineno,
                message="jax.jit constructed inside a loop body — a fresh "
                        "compile cache per iteration (retrace storm); hoist "
                        "it out or cache the jitted callable"))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def lint_source(source: str, filename: str = "<string>",
                checks: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one module's source; returns findings after applying
    ``# tpu-lint: disable=`` suppressions."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [Finding(check="syntax-error", severity="ERROR",
                        message=f"cannot parse: {e.msg}", file=filename,
                        line=e.lineno)]
    aliases = _module_aliases(tree)
    findings: List[Finding] = []

    jit_fns = _jit_context_functions(tree, aliases)
    for fn in jit_fns:
        linter = _JitBodyLinter(fn, aliases, filename)
        for stmt in fn.body:
            linter.visit(stmt)
        findings.extend(linter.findings)

    loop = _JitInLoop(aliases, filename)
    loop.visit(tree)
    findings.extend(loop.findings)

    if checks is not None:
        allowed = set(checks)
        findings = [f for f in findings if f.check in allowed]

    sup = line_suppressions(source)
    if sup:
        ranges: List[Tuple[int, int]] = [
            (n.lineno, getattr(n, "end_lineno", n.lineno))
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        findings = [f for f in findings
                    if not suppressed(f.check, f.line, sup, ranges)]
    return findings


def lint_file(path: str,
              checks: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), filename=path, checks=checks)


def lint_path(path: str,
              checks: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint a .py file or every .py file under a directory tree."""
    if os.path.isfile(path):
        return lint_file(path, checks=checks)
    findings: List[Finding] = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git", "_native"))
        for name in sorted(files):
            if name.endswith(".py"):
                findings.extend(lint_file(os.path.join(root, name),
                                          checks=checks))
    return findings
