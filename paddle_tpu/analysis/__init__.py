"""``paddle_tpu.analysis`` — trace-time lint subsystem.

The TPU-stack analog of legacy Paddle's eager ``config_parser.py``
validation: a jaxpr auditor for compiled topologies/steps (dtype
promotion, host transfers, constant bloat, unsharded meshes, unaligned
Pallas tiles), an AST trace-safety linter for Python sources (tracer
leaks/branches, trace-time impurity, retrace storms), the whole-stack
static safety passes (``analysis.static``: host-concurrency race lint,
gang collective protocol checker, static HBM/donation audit), a
suppression plane, and the ``python -m paddle_tpu lint`` CLI.  See
docs/lint.md for the check catalog.
"""

from paddle_tpu.analysis.findings import (Finding, SEVERITIES,
                                          apply_allowlist, errors_summary,
                                          format_findings, load_allowlist,
                                          severity_at_least)
from paddle_tpu.analysis.jaxpr_walk import (aval_bytes, eqn_subjaxprs,
                                            find_primitives,
                                            hlo_control_flow,
                                            peak_live_bytes, walk_eqns)
from paddle_tpu.analysis.jaxpr_audit import (DECODE_CHECKS, JAXPR_CHECKS,
                                             audit_decode, audit_fn,
                                             audit_jaxpr,
                                             audit_amp_matmuls,
                                             audit_no_dense_rows)
from paddle_tpu.analysis.ast_lint import (AST_CHECKS, lint_file, lint_path,
                                          lint_source)
from paddle_tpu.analysis.flops import (chip_hbm_bytes, chip_peak_bandwidth,
                                       chip_peak_flops, count_jaxpr_flops,
                                       jaxpr_flops)
from paddle_tpu.analysis.static import (audit_hbm_jaxpr, run_hbm,
                                        run_protocol, run_race)

__all__ = [
    "Finding",
    "SEVERITIES",
    "severity_at_least",
    "errors_summary",
    "apply_allowlist",
    "load_allowlist",
    "format_findings",
    "eqn_subjaxprs",
    "walk_eqns",
    "find_primitives",
    "hlo_control_flow",
    "audit_jaxpr",
    "audit_fn",
    "audit_decode",
    "audit_no_dense_rows",
    "audit_amp_matmuls",
    "DECODE_CHECKS",
    "JAXPR_CHECKS",
    "AST_CHECKS",
    "lint_source",
    "lint_file",
    "lint_path",
    "count_jaxpr_flops",
    "jaxpr_flops",
    "chip_peak_flops",
    "chip_peak_bandwidth",
    "chip_hbm_bytes",
    "aval_bytes",
    "peak_live_bytes",
    "run_race",
    "run_protocol",
    "run_hbm",
    "audit_hbm_jaxpr",
]
