"""Analytic FLOPs walker + chip roofline tables — ONE source of truth.

``bench.py`` computes MFU from analytic matmul+conv FLOPs (XLA's
``cost_analysis`` undercounts ``lax.scan`` bodies and lets
rematerialization inflate an implementation's op count), and the live MFU
gauge (``paddle_tpu.obs.timeline``) must report the SAME number for the
same program — a bench row and a live dashboard that disagree about FLOPs
turn every perf investigation into an argument about counters (the
``mfu: null`` drift risk flagged in VERDICT r4 weak #4).  Both import
from here; neither carries a private copy.

Counting convention: 2*M*N*K per ``dot_general`` and
2*out_elems*(filter_spatial*Cin/groups) per ``conv_general_dilated``,
recursing through pjit/scan/cond/custom-vjp sub-jaxprs via the shared
``analysis.jaxpr_walk`` key table (scan bodies multiplied by trip count;
``cond`` counts its WORST branch, since exactly one executes).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["count_jaxpr_flops", "jaxpr_flops", "chip_peak_flops",
           "chip_peak_bandwidth", "chip_hbm_bytes", "CHIP_PEAK_FLOPS",
           "CHIP_PEAK_BW", "CHIP_HBM_BYTES"]

#: chip peak dense FLOP/s (bf16) by device_kind substring, most specific
#: first — the denominator of every MFU number this repo publishes
CHIP_PEAK_FLOPS = (
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 46e12),
)

#: chip peak HBM bandwidth (bytes/s) — the other roofline axis
CHIP_PEAK_BW = (
    ("v6 lite", 1640e9), ("v6e", 1640e9),
    ("v5 lite", 819e9), ("v5e", 819e9), ("v5p", 2765e9), ("v5", 2765e9),
    ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
)


_GiB = float(1 << 30)

#: HBM capacity per chip (bytes) — the denominator of the static
#: peak-live-bytes gate (``lint --hbm``)
CHIP_HBM_BYTES = (
    ("v6 lite", 32 * _GiB), ("v6e", 32 * _GiB),
    ("v5 lite", 16 * _GiB), ("v5e", 16 * _GiB), ("v5p", 95 * _GiB),
    ("v5", 95 * _GiB),
    ("v4", 32 * _GiB), ("v3", 32 * _GiB), ("v2", 16 * _GiB),
)


def _chip_lookup(kind: str, table, default) -> Optional[float]:
    k = (kind or "").lower()
    if "tpu" not in k:
        return None
    for sub, val in table:
        if sub in k:
            return val
    return default


def chip_peak_flops(kind: str) -> Optional[float]:
    """Peak dense FLOP/s for a ``device_kind`` string; None off-TPU
    (an unknown TPU generation assumes v5e rather than dividing by 0)."""
    return _chip_lookup(kind, CHIP_PEAK_FLOPS, 197e12)


def chip_peak_bandwidth(kind: str) -> Optional[float]:
    """Peak HBM bytes/s for a ``device_kind`` string; None off-TPU."""
    return _chip_lookup(kind, CHIP_PEAK_BW, 819e9)


def chip_hbm_bytes(kind: str) -> Optional[float]:
    """HBM bytes per chip for a ``device_kind`` string; None off-TPU
    (an unknown TPU generation assumes v5e)."""
    return _chip_lookup(kind, CHIP_HBM_BYTES, 16 * _GiB)


def count_jaxpr_flops(jaxpr) -> float:
    """Analytic matmul+conv FLOPs of an (open) jaxpr, recursing into
    sub-jaxprs through the shared known-key walker (the old
    recurse-into-every-param loop double-counted primitives carrying
    several sub-jaxprs — custom_vjp holds primal + fwd/bwd rules)."""
    from paddle_tpu.analysis.jaxpr_walk import eqn_subjaxprs

    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            (lc, _), _ = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval
            k = float(np.prod([lhs.shape[d] for d in lc], dtype=np.float64))
            out = float(np.prod(eqn.outvars[0].aval.shape, dtype=np.float64))
            total += 2.0 * out * k
        elif name == "conv_general_dilated":
            dn = eqn.params["dimension_numbers"]
            rhs = eqn.invars[1].aval
            # rhs_spec[0]=out-chan dim, [1]=in-chan(per group), rest spatial
            k = float(np.prod([rhs.shape[d] for d in dn.rhs_spec[1:]],
                              dtype=np.float64))
            out = float(np.prod(eqn.outvars[0].aval.shape, dtype=np.float64))
            total += 2.0 * out * k
        elif name == "cond":
            # a cond executes ONE branch: count the worst case, not the
            # sum (the generic walker yields every branch)
            branches = eqn.params.get("branches", ())
            if branches:
                total += max(count_jaxpr_flops(b.jaxpr) for b in branches)
        else:
            for inner, mult in eqn_subjaxprs(eqn):
                total += mult * count_jaxpr_flops(inner)
    return total


def jaxpr_flops(fn, *args, **kwargs) -> Optional[float]:
    """Trace ``fn(*args, **kwargs)`` and return its analytic FLOPs, or
    None when the trace fails (a bench row degrades to ``mfu: null``
    rather than sinking the capture)."""
    import jax

    try:
        return count_jaxpr_flops(jax.make_jaxpr(fn)(*args, **kwargs).jaxpr)
    except Exception:
        return None
