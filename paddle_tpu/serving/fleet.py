"""Model-fleet serving: a model table with tenancy and versioned rollout.

:class:`ModelFleet` promotes the single-model ``InferenceServer`` into a
model TABLE keyed by ``(name, version)`` (docs/serving.md "Fleet
serving").  Every entry gets the whole PR 5→17 robustness stack
instantiated PER ENTRY — its own admission queue, circuit breaker,
degradation ladder, warmup gate, supervised worker, and (generation
mode) slot scheduler — so a NaN-poisoned or breaker-tripped entry fails
only the requests routed to it, and every other entry keeps serving.

Three planes on top of the table:

- **Tenancy** (serving/tenancy.py): per-tenant token-bucket quotas and
  weighted fair-share admission in front of every entry's typed queue.
  A tenant at quota gets :class:`QuotaExceeded`; fleet contention sheds
  proportionally to weights, never silently.

- **Versioned rollout**: per-model canary percentages over a
  DETERMINISTIC hash-of-request split (same request key -> same arm,
  across retries and processes), shadow traffic (the candidate gets a
  duplicate, the INCUMBENT's reply is the reply, divergence is counted
  and journaled), and automatic rollback generalizing the PR 17
  ``HotSwapManager`` probation to per-entry baselines: a canary whose
  breaker trips or whose error rate regresses past the incumbent's
  baseline is rolled back inside its probation window, journaled as
  ``publish_rollback`` naming the entry.  Session affinity pins a
  session to the version that first admitted it, so in-flight
  generation slots never migrate mid-rollout.

- **Observability**: requests carry ``tenant``/``model``/``version``
  attributes on their trace root spans, registry counters are labeled
  ``fleet_*{tenant=,model=}``, and ``healthz()`` grows a per-entry
  ``models`` table while keeping the single-model ``model`` block
  schema-compatible for old dashboards (pinned in tests/test_serving.py).
"""

from __future__ import annotations

import hashlib
import queue as _queue
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.serving.errors import (InvalidRequestError, QuotaExceeded,
                                       ServingError)
from paddle_tpu.serving.server import InferenceServer
from paddle_tpu.serving.tenancy import TenantAdmission
from paddle_tpu.utils.error import ConfigError
from paddle_tpu.utils.log import logger

__all__ = ["ModelFleet", "canary_arm"]

#: the hash-split grain: percentages resolve to integer permille buckets
_SPLIT_BUCKETS = 10000


def canary_arm(model: str, key: str, percent: float) -> bool:
    """Deterministic hash-of-request canary split: True routes to the
    candidate.  The split is a pure function of ``(model, key)`` — the
    same request id lands on the same arm across retries, processes,
    and rollout restarts (pinned by tests/test_fleet.py)."""
    if percent <= 0.0:
        return False
    if percent >= 100.0:
        return True
    h = hashlib.sha256(f"{model}|{key}".encode()).digest()
    bucket = int.from_bytes(h[:4], "big") % _SPLIT_BUCKETS
    return bucket < percent * (_SPLIT_BUCKETS / 100.0)


def _content_key(feed: Dict[str, Any]) -> str:
    """Stable digest of a feed's bytes — the split key of last resort
    when the client supplies neither request_key nor session_id (an
    identical retry still lands on the same arm)."""
    h = hashlib.sha256()
    for name in sorted(feed):
        v = feed[name]
        parts = v if isinstance(v, (tuple, list)) else (v,)
        h.update(name.encode())
        for p in parts:
            a = np.asarray(p)
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    return h.hexdigest()[:16]


class _Entry:
    """One model-table row: a full per-entry serving stack."""

    def __init__(self, name: str, version: int, server: InferenceServer,
                 info: Optional[dict], added_at: float) -> None:
        self.name = name
        self.version = int(version)
        self.server = server
        self.info = dict(info) if info else None
        self.added_at = added_at
        # serving | canary | shadow | retired | closed — mutated only
        # under the fleet lock; tpu-lint: guarded-by=ModelFleet._lock - routing reads a consistent state
        self.state = "serving"

    @property
    def key(self) -> Tuple[str, int]:
        return (self.name, self.version)

    @property
    def label(self) -> str:
        return f"{self.name}@v{self.version}"


class ModelFleet:
    """The model table plus tenancy, rollout, and fleet health surface.

    ``tenants`` (optional) is an iterable of
    :class:`~paddle_tpu.serving.tenancy.TenantSpec` (or kwarg dicts);
    without it the fleet is untenanted and ``submit(tenant=...)`` is
    carried for attribution only.  Rollout knobs mirror the PR 17
    ``HotSwapManager`` probation contract, applied per entry.
    """

    def __init__(self, *, tenants=None,
                 capacity_rate: Optional[float] = None,
                 capacity_burst: Optional[float] = None,
                 probation_requests: int = 32,
                 min_probation_samples: int = 8,
                 error_rate_margin: float = 0.10,
                 shadow_rtol: float = 1e-5,
                 shadow_atol: float = 1e-6,
                 session_affinity_max: int = 4096,
                 clock=time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.RLock()
        # the model table — tpu-lint: guarded-by=_lock - entries/routes/sessions mutate together on rollout transitions
        self._entries: Dict[Tuple[str, int], _Entry] = {}
        self._routes: Dict[str, dict] = {}
        self._sessions: "OrderedDict[Tuple[str, str], int]" = OrderedDict()
        self._session_max = int(session_affinity_max)
        self.admission = (TenantAdmission(
            tenants, capacity_rate=capacity_rate,
            capacity_burst=capacity_burst, clock=clock)
            if tenants is not None else None)
        self.probation_requests = int(probation_requests)
        self.min_probation_samples = int(min_probation_samples)
        self.error_rate_margin = float(error_rate_margin)
        self.shadow_rtol = float(shadow_rtol)
        self.shadow_atol = float(shadow_atol)
        self._closed = False
        # fleet-labeled registry counters, created on first use —
        # tpu-lint: guarded-by=_metric_lock - label children memoized once
        self._metric_lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[str, ...]], Any] = {}
        # shadow comparison runs OFF the reply path: pairs drain through
        # a bounded queue into one daemon thread; overflow is COUNTED
        # (never blocks a reply), compared pairs feed the divergence
        # counters + journal
        self._shadow_q: "_queue.Queue" = _queue.Queue(maxsize=256)
        self._shadow_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # metrics
    # ------------------------------------------------------------------

    def _count(self, name: str, n: int = 1, **labels) -> None:
        from paddle_tpu.obs import get_registry

        labelnames = tuple(sorted(labels))
        key = (name, labelnames, tuple(labels[k] for k in labelnames))
        with self._metric_lock:
            c = self._counters.get(key)
            if c is None:
                c = get_registry().counter(
                    "fleet_" + name, "fleet counter (docs/serving.md)",
                    labels=labelnames, **labels)
                self._counters[key] = c
        c.inc(n)

    # ------------------------------------------------------------------
    # the model table
    # ------------------------------------------------------------------

    def add_model(self, name: str, model, *, version: int = 1,
                  role: str = "serving", percent: float = 0.0,
                  info: Optional[dict] = None,
                  warmup_feed=None, compile_cache=None,
                  start: bool = True, server_opts: Optional[dict] = None
                  ) -> _Entry:
        """Create one table entry — its own queue/breaker/ladder/worker
        (and slot scheduler in generation mode) — and wire it into the
        model's route.

        ``role``: ``"serving"`` makes the entry the model's incumbent
        (refused typed if one already exists — rollouts go through
        ``"canary"``/``"shadow"``); ``"canary"`` routes ``percent``% of
        the model's traffic to it under probation; ``"shadow"`` mirrors
        traffic to it while every reply still comes from the incumbent.
        """
        if role not in ("serving", "canary", "shadow"):
            raise ConfigError(f"role must be serving|canary|shadow, "
                              f"got {role!r}")
        opts = dict(server_opts or {})
        srv = InferenceServer(model, clock=self._clock, **opts)
        if start:
            srv.start(warmup_feed=warmup_feed,
                      warmup=(warmup_feed is not None
                              or hasattr(model, "topology")),
                      compile_cache=compile_cache)
        if info:
            srv.set_model_info(info)
        entry = _Entry(name, version, srv, info, self._clock())
        with self._lock:
            if self._closed:
                srv.close()
                raise ConfigError("fleet is closed")
            if entry.key in self._entries:
                srv.close()
                raise ConfigError(f"duplicate model entry {entry.label}")
            route = self._routes.get(name)
            if role == "serving":
                if route is not None and route["incumbent"] is not None:
                    srv.close()
                    raise ConfigError(
                        f"model {name!r} already has incumbent "
                        f"v{route['incumbent']} — roll out via "
                        f"role='canary' or role='shadow'")
                self._entries[entry.key] = entry
                self._routes[name] = {
                    "incumbent": version, "candidate": None,
                    "mode": None, "percent": 0.0,
                    "probation": None,
                    "shadow": {"compared": 0, "diverged": 0,
                               "candidate_errors": 0, "dropped": 0},
                }
            else:
                if route is None or route["incumbent"] is None:
                    srv.close()
                    raise ConfigError(
                        f"model {name!r} has no incumbent to roll out "
                        f"against")
                if route["candidate"] is not None:
                    srv.close()
                    raise ConfigError(
                        f"model {name!r} already has candidate "
                        f"v{route['candidate']} in flight — one rollout "
                        f"at a time")
                self._entries[entry.key] = entry
                entry.state = role
                incumbent = self._entries[(name, route["incumbent"])]
                # per-entry probation baselines (the PR 17 HotSwapManager
                # contract generalized): the incumbent's error rate is
                # the bar, the candidate's own counters are the window
                from paddle_tpu.serving.reload import error_baseline

                route["candidate"] = version
                route["mode"] = role
                route["percent"] = float(percent) if role == "canary" else 0.0
                route["probation"] = {
                    "baseline": error_baseline(incumbent.server),
                    "cand_start": error_baseline(srv),
                    "started": self._clock(),
                }
                from paddle_tpu.obs import journal_event

                journal_event("fleet_rollout", model=name, version=version,
                              mode=role, percent=route["percent"],
                              incumbent=route["incumbent"])
        return entry

    def entry(self, name: str, version: int) -> _Entry:
        with self._lock:
            e = self._entries.get((name, int(version)))
        if e is None:
            raise KeyError(f"no model entry {name}@v{version}")
        return e

    def entries(self) -> List[_Entry]:
        with self._lock:
            return list(self._entries.values())

    def route(self, name: str) -> dict:
        with self._lock:
            r = self._routes.get(name)
            if r is None:
                raise KeyError(f"unknown model {name!r}")
            return dict(r)

    def load_published_model(self, publish_root: str, name: str, *,
                             role: str = "serving", percent: float = 0.0,
                             compile_cache=None,
                             server_opts: Optional[dict] = None) -> _Entry:
        """Boot one entry from the model's own publish watch dir
        (``publish_root/<name>/v-NNNNN`` — publish.model_publish_dir):
        newest valid version wins, corrupt versions are skipped typed,
        and the publish dir's shared compile cache warms the entry."""
        from paddle_tpu.publish import model_publish_dir, publish_cache_dir
        from paddle_tpu.serving.reload import load_published

        mdir = model_publish_dir(publish_root, name)
        model, info, version = load_published(mdir)
        cache = compile_cache
        if cache is None:
            try:
                cache = publish_cache_dir(mdir)
            except Exception:  # noqa: BLE001 — cache is an optimization
                cache = None
        return self.add_model(name, model, version=version, role=role,
                              percent=percent, info=info,
                              compile_cache=cache, server_opts=server_opts)

    # ------------------------------------------------------------------
    # routing + submit
    # ------------------------------------------------------------------

    def _pick(self, name: str, request_key: Optional[str],
              session_id: Optional[str], feed: Dict[str, Any]
              ) -> Tuple[_Entry, Optional[_Entry], str]:
        """Resolve (serving entry, shadow candidate or None, split key)
        under the fleet lock."""
        route = self._routes.get(name)
        if route is None:
            known = sorted(self._routes)
            raise InvalidRequestError(
                f"unknown model {name!r} (serving: {known})")
        key = request_key or session_id or _content_key(feed)
        version = route["incumbent"]
        shadow_to = None
        if route["candidate"] is not None:
            cand = route["candidate"]
            if route["mode"] == "canary":
                pinned = (self._sessions.get((name, session_id))
                          if session_id else None)
                if pinned is not None and (
                        (name, pinned) in self._entries
                        and self._entries[(name, pinned)].state
                        not in ("retired", "closed")):
                    version = pinned
                elif canary_arm(name, key, route["percent"]):
                    version = cand
            elif route["mode"] == "shadow":
                shadow_entry = self._entries.get((name, cand))
                if shadow_entry is not None and shadow_entry.state == "shadow":
                    shadow_to = shadow_entry
        if session_id is not None:
            # session affinity: in-flight generation slots (and any
            # follow-up turns) pin to the version that admitted the
            # session — a rollout never migrates a live session
            self._sessions[(name, session_id)] = version
            self._sessions.move_to_end((name, session_id))
            while len(self._sessions) > self._session_max:
                self._sessions.popitem(last=False)
        entry = self._entries[(name, version)]
        return entry, shadow_to, key

    def submit(self, feed: Dict[str, Any], *, model: Optional[str] = None,
               tenant: Optional[str] = None,
               request_key: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               max_len: Optional[int] = None,
               session_id: Optional[str] = None):
        """Admit one request into the fleet, or raise typed.

        Order of the admission planes: tenancy first (quota / fair
        share — :class:`QuotaExceeded` never touches any entry's queue
        or breaker), then rollout routing (canary split / session
        affinity / shadow duplication), then the chosen ENTRY's own
        typed admission (shed / deadline / breaker / warmup).  Returns
        the entry's :class:`ServingFuture` — shadow candidates never
        produce the reply."""
        if self._closed:
            from paddle_tpu.serving.errors import ServerClosed

            raise ServerClosed("fleet is closed")
        if model is None:
            with self._lock:
                if len(self._routes) != 1:
                    raise InvalidRequestError(
                        f"fleet serves {sorted(self._routes)} — "
                        f"submit(..., model=NAME) is required")
                model = next(iter(self._routes))
        if self.admission is not None:
            try:
                self.admission.admit(tenant)
            except QuotaExceeded as e:
                self._count("fair_share_shed_total" if e.fair_share
                            else "quota_rejected_total",
                            tenant=tenant or "-")
                raise
        with self._lock:
            entry, shadow_to, key = self._pick(model, request_key,
                                               session_id, feed)
        attrs = {"tenant": tenant or "-", "model": model,
                 "version": entry.version}
        fut = entry.server.submit(feed, deadline_ms, max_len=max_len,
                                  session_id=session_id, trace_attrs=attrs)
        self._count("requests_total", tenant=tenant or "-", model=model)
        if entry.state == "canary":
            self._count("canary_requests_total", model=model)
        if shadow_to is not None:
            self._shadow_submit(model, shadow_to, feed, deadline_ms,
                                max_len, session_id, fut, key)
        self._tick_locked_route(model)
        return fut

    def infer(self, feed: Dict[str, Any], *, model: Optional[str] = None,
              tenant: Optional[str] = None, timeout: Optional[float] = None,
              **kw) -> Dict[str, np.ndarray]:
        fut = self.submit(feed, model=model, tenant=tenant, **kw)
        return fut.result(timeout if timeout is not None else 30.0)

    # ------------------------------------------------------------------
    # shadow traffic
    # ------------------------------------------------------------------

    def _shadow_submit(self, name: str, entry: _Entry, feed, deadline_ms,
                       max_len, session_id, incumbent_fut, key) -> None:
        route = self._routes[name]
        try:
            cand_fut = entry.server.submit(
                feed, deadline_ms, max_len=max_len, session_id=session_id,
                trace_attrs={"model": name, "version": entry.version,
                             "shadow": True})
        except ServingError:
            # the candidate rejecting mirrored traffic is a candidate
            # problem, never the client's: counted, reply unaffected
            with self._lock:
                route["shadow"]["candidate_errors"] += 1
            return
        try:
            self._shadow_q.put_nowait(
                (name, entry.version, incumbent_fut, cand_fut, key))
        except _queue.Full:
            with self._lock:
                route["shadow"]["dropped"] += 1
            return
        if self._shadow_thread is None or not self._shadow_thread.is_alive():
            self._shadow_thread = threading.Thread(
                target=self._shadow_main, name="fleet-shadow", daemon=True)
            self._shadow_thread.start()

    def _shadow_main(self) -> None:
        from paddle_tpu.obs import journal_event

        while True:
            item = self._shadow_q.get()
            if item is None:
                return
            name, version, inc_fut, cand_fut, key = item
            try:
                inc = inc_fut.result(30.0)
                cand = cand_fut.result(30.0)
            except ServingError:
                with self._lock:
                    route = self._routes.get(name)
                    if route is not None:
                        route["shadow"]["compared"] += 1
                        route["shadow"]["candidate_errors"] += 1
                continue
            except Exception:  # noqa: BLE001 — the comparer must survive
                continue
            diverged = self._outputs_diverge(inc, cand)
            with self._lock:
                route = self._routes.get(name)
                if route is not None:
                    route["shadow"]["compared"] += 1
                    if diverged:
                        route["shadow"]["diverged"] += 1
            if diverged:
                self._count("shadow_diverged_total", model=name)
                journal_event("shadow_divergence", model=name,
                              version=version, request_key=key)

    def _outputs_diverge(self, inc: Dict[str, Any],
                         cand: Dict[str, Any]) -> bool:
        if set(inc) != set(cand):
            return True
        for k in inc:
            a, b = np.asarray(inc[k]), np.asarray(cand[k])
            if a.shape != b.shape or a.dtype != b.dtype:
                return True
            if a.dtype.kind == "f":
                if not np.allclose(a, b, rtol=self.shadow_rtol,
                                   atol=self.shadow_atol, equal_nan=True):
                    return True
            elif not np.array_equal(a, b):
                return True
        return False

    # ------------------------------------------------------------------
    # rollout state machine: probation -> promote | rollback
    # ------------------------------------------------------------------

    def tick(self) -> List[dict]:
        """Advance every model's rollout probation; returns the actions
        taken (``promoted`` / ``rolled_back``).  Also called inline on
        every submit, so a poisoned canary rolls back under live traffic
        without any external driver."""
        with self._lock:
            names = list(self._routes)
        actions = []
        for name in names:
            act = self._tick_locked_route(name)
            if act is not None:
                actions.append(act)
        self._reap_retired()
        return actions

    def _tick_locked_route(self, name: str) -> Optional[dict]:
        with self._lock:
            route = self._routes.get(name)
            if route is None or route["candidate"] is None:
                return None
            p = route["probation"]
            cand = self._entries.get((name, route["candidate"]))
            if cand is None or p is None:
                return None
            if cand.server.breaker.trips > p["cand_start"]["breaker_trips"]:
                return self._rollback_locked(name, "breaker_trip")
            m = cand.server.metrics
            completed = (m.count("completed")
                         - p["cand_start"]["completed"])
            failed = (m.count("inference_failed")
                      - p["cand_start"]["inference_failed"])
            resolved = completed + failed
            if resolved >= self.min_probation_samples:
                rate = failed / resolved
                if rate > p["baseline"]["error_rate"] + self.error_rate_margin:
                    return self._rollback_locked(
                        name, "error_rate_regression",
                        detail=f"candidate error rate {rate:.3f} vs "
                               f"incumbent baseline "
                               f"{p['baseline']['error_rate']:.3f}")
            if route["mode"] == "canary" and \
                    resolved >= self.probation_requests:
                return self._promote_locked(name, resolved)
            return None

    def promote(self, name: str) -> dict:
        """Manually conclude a rollout in the candidate's favor (shadow
        mode never auto-promotes — divergence is a human's call)."""
        with self._lock:
            route = self._routes.get(name)
            if route is None or route["candidate"] is None:
                raise ConfigError(f"model {name!r} has no rollout in flight")
            return self._promote_locked(name, 0)

    def rollback(self, name: str, signal: str = "manual",
                 detail: str = "") -> dict:
        with self._lock:
            route = self._routes.get(name)
            if route is None or route["candidate"] is None:
                raise ConfigError(f"model {name!r} has no rollout in flight")
            return self._rollback_locked(name, signal, detail)

    def _promote_locked(self, name: str, resolved: int) -> dict:
        from paddle_tpu.obs import journal_event

        route = self._routes[name]
        v, prev = route["candidate"], route["incumbent"]
        self._entries[(name, prev)].state = "retired"
        self._entries[(name, v)].state = "serving"
        route.update(incumbent=v, candidate=None, mode=None, percent=0.0,
                     probation=None)
        journal_event("probation_passed", fsync=True, model=name,
                      version=v, requests=resolved)
        journal_event("fleet_promote", model=name, version=v, previous=prev)
        self._count("promotions_total", model=name)
        logger.info("fleet: %s@v%d promoted (replacing v%d)", name, v, prev)
        return {"action": "promoted", "model": name, "version": v,
                "previous": prev}

    def _rollback_locked(self, name: str, signal: str,
                         detail: str = "") -> dict:
        from paddle_tpu.obs import journal_event

        route = self._routes[name]
        v = route["candidate"]
        entry = self._entries[(name, v)]
        entry.state = "retired"
        route.update(candidate=None, mode=None, percent=0.0, probation=None)
        # live sessions pinned to the dead candidate re-route to the
        # incumbent on their next request — never to a retired entry
        for skey in [k for k, sv in self._sessions.items()
                     if k[0] == name and sv == v]:
            del self._sessions[skey]
        journal_event("publish_rollback", fsync=True, model=name,
                      version=v, entry=entry.label, signal=signal,
                      detail=detail, rolled_back_to=route["incumbent"])
        self._count("rollbacks_total", model=name)
        logger.warning("fleet: %s rolled back to v%d (%s)%s",
                       entry.label, route["incumbent"], signal,
                       f": {detail}" if detail else "")
        return {"action": "rolled_back", "model": name, "version": v,
                "signal": signal, "rolled_back_to": route["incumbent"]}

    def _reap_retired(self) -> None:
        """Close retired entries once their queues drain — their queued
        requests resolve typed first (reply-or-typed-error even for a
        rolled-back canary's stragglers), so a rollout→rollback cycle
        drops ZERO requests."""
        with self._lock:
            victims = [e for e in self._entries.values()
                       if e.state == "retired"
                       and e.server.queue.depth() == 0]
            for e in victims:
                e.state = "closed"
        for e in victims:
            try:
                e.server.close()
            except Exception:  # noqa: BLE001 — reaping is best-effort
                logger.warning("fleet: closing retired %s failed", e.label)

    # ------------------------------------------------------------------
    # health + audit + lifecycle
    # ------------------------------------------------------------------

    def healthz(self) -> dict:
        """Fleet health: the per-entry ``models`` table
        (name/version/state/breaker/queue occupancy), per-model
        ``routes``, per-tenant ``tenants`` quota occupancy — plus a
        single-model ``model`` block (the default route's incumbent)
        kept schema-compatible with ``InferenceServer.healthz()`` for
        old dashboards (schema pinned in tests/test_serving.py)."""
        with self._lock:
            entries = list(self._entries.values())
            routes = {n: dict(r) for n, r in self._routes.items()}
        models = {}
        ready = bool(entries)
        for e in entries:
            if e.state == "closed":
                models[e.label] = {"name": e.name, "version": e.version,
                                   "state": "closed"}
                continue
            h = e.server.healthz()
            if e.state == "serving" and not h["ready"]:
                ready = False
            depth = h["queue_depth"]
            cap = e.server.queue.max_queue
            models[e.label] = {
                "name": e.name,
                "version": e.version,
                "state": e.state,
                "ready": h["ready"],
                "mode": h["mode"],
                "breaker": h["breaker"],
                "queue": {"depth": depth, "capacity": cap,
                          "occupancy": round(depth / cap, 4) if cap else 0.0},
                "completed": h["counters"]["completed"],
                "inference_failed": h["counters"]["inference_failed"],
                "shed": h["counters"]["shed"],
            }
        out: Dict[str, Any] = {
            "ready": ready,
            "models": models,
            "routes": {
                n: {"incumbent": r["incumbent"],
                    "candidate": r["candidate"],
                    "mode": r["mode"], "percent": r["percent"],
                    "shadow": dict(r["shadow"])}
                for n, r in routes.items()
            },
        }
        if self.admission is not None:
            out["tenants"] = self.admission.snapshot()
        for n in sorted(routes):
            inc = routes[n]["incumbent"]
            e = next((x for x in entries
                      if x.key == (n, inc) and x.state != "closed"), None)
            if e is not None:
                block = e.server.healthz().get("model")
                if block is not None:
                    out["model"] = block
                    break
        return out

    def audit(self) -> list:
        """``lint --serve`` hook: audit the compiled serving closures of
        EVERY model-table entry — bucket entries through the preflight
        auditor, generation entries through the slot-step auditor — each
        finding labeled with its entry (``fleet:<name>@v<version>``)."""
        findings = []
        for e in sorted(self.entries(), key=lambda x: x.key):
            if e.state == "closed":
                continue
            label = f"fleet:{e.label}"
            try:
                if e.server.mode == "generation":
                    from paddle_tpu.serving.slots import audit_slot_backend

                    findings.extend(audit_slot_backend(
                        e.server.model, slots=e.server._scheduler.slots,
                        label=label,
                        spec_k=e.server._scheduler.spec_k))
                elif hasattr(e.server.model, "topology"):
                    from paddle_tpu.serving.preflight import audit_serving

                    findings.extend(audit_serving(e.server.model,
                                                  label=label))
            except Exception as exc:  # noqa: BLE001 — audited, not crashed
                from paddle_tpu.analysis.findings import Finding

                findings.append(Finding(
                    check="serve-build", severity="ERROR", file=label,
                    message=f"entry audit failed: "
                            f"{type(exc).__name__}: {exc}"))
        return findings

    def close(self, join_timeout: float = 2.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._entries.values())
        if self._shadow_thread is not None and self._shadow_thread.is_alive():
            self._shadow_q.put(None)
            self._shadow_thread.join(join_timeout)
        for e in entries:
            if e.state != "closed":
                try:
                    e.server.close(join_timeout)
                except Exception:  # noqa: BLE001 — close the rest anyway
                    logger.warning("fleet: closing %s failed", e.label)
                e.state = "closed"
        from paddle_tpu.obs import get_registry

        reg = get_registry()
        with self._metric_lock:
            for (name, labelnames, labelvalues) in list(self._counters):
                try:
                    reg.remove_series("fleet_" + name,
                                      **dict(zip(labelnames, labelvalues)))
                except Exception:  # noqa: BLE001 — registry hygiene only
                    pass
            self._counters.clear()

    def __enter__(self) -> "ModelFleet":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
