"""Multi-tenant admission: token-bucket quotas + weighted fair share.

The tenancy tier sits in FRONT of the PR 5 typed admission queue
(docs/serving.md "Fleet serving"): before a request ever reaches a model
entry's ``BatchQueue``, :class:`TenantAdmission` decides whether the
submitting tenant may spend capacity right now.  Two independent layers:

1. **Per-tenant token bucket** — each tenant refills at its own ``rate``
   up to ``burst`` tokens; an empty bucket raises the typed
   :class:`~paddle_tpu.serving.errors.QuotaExceeded` immediately.  One
   tenant's flood burns ONLY its own bucket.

2. **Weighted fair share** — an aggregate bucket models the fleet's
   shared capacity.  While it has tokens, any within-quota tenant
   admits.  When it runs dry (contention), admission falls back to
   start-time fair queuing over the tenants' ``weight``s: every admit
   advances the tenant's virtual time by ``cost / weight``, and a tenant
   whose virtual time has run more than ``credit`` ahead of the global
   virtual clock is shed typed (``QuotaExceeded(fair_share=True)``)
   until the others catch up.  Admitted counts therefore converge to the
   weight ratio under sustained overload — proportional shedding, never
   silent starvation of the light tenants (pinned within ±10% by
   tests/test_fleet.py).

A fair-share shed REFUNDS the tenant's own token: contention is the
fleet's condition, and it must not also eat the tenant's quota.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Optional, Union

from paddle_tpu.serving.errors import InvalidRequestError, QuotaExceeded
from paddle_tpu.utils.error import ConfigError

__all__ = ["TenantSpec", "TokenBucket", "TenantAdmission"]


class TenantSpec:
    """One tenant's contract: ``rate`` requests/s refill up to ``burst``
    tokens of personal quota, and ``weight`` shares of the aggregate
    under contention.  A non-positive weight, rate, or burst is a
    configuration bug and is rejected typed at construction — a
    zero-weight tenant would be starved silently forever, which is
    exactly the failure mode this tier exists to make impossible."""

    def __init__(self, name: str, *, weight: float = 1.0,
                 rate: float = 100.0, burst: float = 10.0) -> None:
        if not name or not isinstance(name, str):
            raise ConfigError("tenant name must be a non-empty string")
        if weight <= 0:
            raise ConfigError(
                f"tenant {name!r}: weight must be > 0 (got {weight!r}) — "
                f"a zero-weight tenant would be silently starved under "
                f"any contention")
        if rate <= 0:
            raise ConfigError(
                f"tenant {name!r}: rate must be > 0 requests/s "
                f"(got {rate!r})")
        if burst < 1:
            raise ConfigError(
                f"tenant {name!r}: burst must be >= 1 (got {burst!r}) — "
                f"a zero-burst tenant could never admit anything")
        self.name = name
        self.weight = float(weight)
        self.rate = float(rate)
        self.burst = float(burst)


class TokenBucket:
    """Classic token bucket (float tokens, monotonic-clock refill).
    Not self-locking: :class:`TenantAdmission` serializes access."""

    def __init__(self, rate: float, burst: float, now: float) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = float(now)

    def _refill(self, now: float) -> None:
        if now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
            self._last = now

    def take(self, cost: float, now: float) -> bool:
        self._refill(now)
        if self.tokens + 1e-9 >= cost:
            self.tokens -= cost
            return True
        return False

    def refund(self, cost: float) -> None:
        self.tokens = min(self.burst, self.tokens + cost)

    def occupancy(self) -> float:
        """Fraction of the burst currently SPENT (1.0 = at quota)."""
        return round(1.0 - self.tokens / self.burst, 4) if self.burst else 0.0


class TenantAdmission:
    """Admission arbiter over a fixed tenant set.

    ``capacity_rate`` / ``capacity_burst`` size the aggregate bucket
    (defaults: the sums over tenants — i.e. contention only when the
    whole fleet is collectively over its configured rate).  ``credit``
    is the fair-queuing slack in admitted-request units per unit weight;
    1.0 means a tenant may run one weighted request ahead of the global
    virtual clock before it is shed.
    """

    def __init__(self, tenants: Iterable[Union[TenantSpec, dict]], *,
                 capacity_rate: Optional[float] = None,
                 capacity_burst: Optional[float] = None,
                 credit: float = 1.0,
                 active_window_s: float = 1.0,
                 clock=time.monotonic) -> None:
        specs = [t if isinstance(t, TenantSpec) else TenantSpec(**t)
                 for t in tenants]
        if not specs:
            raise ConfigError("TenantAdmission needs at least one tenant")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ConfigError(f"duplicate tenant names: {sorted(names)}")
        self._clock = clock
        now = clock()
        self.specs: Dict[str, TenantSpec] = {s.name: s for s in specs}
        self._buckets = {s.name: TokenBucket(s.rate, s.burst, now)
                         for s in specs}
        self._aggregate = TokenBucket(
            capacity_rate if capacity_rate is not None
            else sum(s.rate for s in specs),
            capacity_burst if capacity_burst is not None
            else sum(s.burst for s in specs), now)
        self.credit = float(credit)
        self.active_window_s = float(active_window_s)
        self._lock = threading.Lock()
        # start-time fair queuing state — guarded-by=_lock - vtime[t]
        # advances by cost/weight per admit; _vclock is the min over
        # RECENTLY ACTIVE tenants (monotone).  Idle tenants are excluded
        # from the min (they would freeze the clock and starve everyone
        # else) and rejoin at the current clock (no banked credit).
        self._vtime = {s.name: 0.0 for s in specs}
        self._vclock = 0.0
        self._last_seen = {s.name: float("-inf") for s in specs}
        # plain counters for healthz / chaos assertions
        self.admitted = {s.name: 0 for s in specs}
        self.quota_rejected = {s.name: 0 for s in specs}
        self.fair_share_shed = {s.name: 0 for s in specs}

    # ------------------------------------------------------------------

    def admit(self, tenant: Optional[str], cost: float = 1.0) -> None:
        """Admit one request for ``tenant`` or raise typed.  Unknown
        tenants are a client bug (``InvalidRequestError``); a tenant at
        its own quota — or past its weighted fair share under aggregate
        contention — gets :class:`QuotaExceeded` immediately."""
        if tenant is None:
            raise InvalidRequestError(
                "tenancy is configured: submit(..., tenant=NAME) is "
                "required")
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                raise InvalidRequestError(
                    f"unknown tenant {tenant!r} (configured: "
                    f"{sorted(self._buckets)})")
            now = self._clock()
            w = self.specs[tenant].weight
            if now - self._last_seen[tenant] > self.active_window_s:
                # rejoining after idleness: no banked credit, no debt
                self._vtime[tenant] = max(self._vtime[tenant], self._vclock)
            self._last_seen[tenant] = now
            if not bucket.take(cost, now):
                self.quota_rejected[tenant] += 1
                raise QuotaExceeded(
                    f"tenant {tenant!r} is at its quota "
                    f"({self.specs[tenant].rate:g} req/s, burst "
                    f"{self.specs[tenant].burst:g}) — retry after "
                    f"{cost / self.specs[tenant].rate:.3f}s",
                    tenant=tenant)
            if not self._aggregate.take(cost, now):
                # aggregate contention: start-time fair queuing decides.
                # The tenant's own token is REFUNDED on a fair-share shed
                # — fleet contention must not also burn personal quota.
                if self._vtime[tenant] - self._vclock > self.credit / w:
                    bucket.refund(cost)
                    self.fair_share_shed[tenant] += 1
                    raise QuotaExceeded(
                        f"tenant {tenant!r} shed over weighted fair share "
                        f"(weight {w:g}) under aggregate contention",
                        tenant=tenant, fair_share=True)
            self._vtime[tenant] = max(self._vtime[tenant],
                                      self._vclock) + cost / w
            active = [self._vtime[t] for t, seen in self._last_seen.items()
                      if now - seen <= self.active_window_s]
            self._vclock = max(self._vclock, min(active))
            self.admitted[tenant] += 1

    # ------------------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        """Per-tenant occupancy for ``healthz()['tenants']``."""
        with self._lock:
            now = self._clock()
            out = {}
            for name, bucket in self._buckets.items():
                bucket._refill(now)
                out[name] = {
                    "weight": self.specs[name].weight,
                    "rate": self.specs[name].rate,
                    "burst": self.specs[name].burst,
                    "tokens": round(bucket.tokens, 3),
                    "occupancy": bucket.occupancy(),
                    "admitted": self.admitted[name],
                    "quota_rejected": self.quota_rejected[name],
                    "fair_share_shed": self.fair_share_shed[name],
                }
            return out
