"""Host-paged slot state: decode capacity beyond the HBM slot table.

The slot table is a fixed-capacity HBM resident (``slots`` ×
per-slot state).  When every slot is occupied and requests queue, a
cold slot — an idle session, a deadline-parked request, the request
with the most remaining budget — can be *paged out*: its full decode
context (:func:`paddle_tpu.ops.decode.extract_slot` snapshot — token
buffer, scores, recurrent state rows, finished mask, step) moves to a
pinned host pool, the slot frees for an admission, and the parked
request is *paged back in* bit-for-bit later via
:func:`paddle_tpu.ops.decode.restore_slot`.  The d2h/h2d round trip
preserves every bit, so a paged request's completion is identical to
one that never left the table (pinned by tests).

The pool is byte-budgeted (``max_mb``); FIFO re-admission keeps parked
requests from starving.  ``pages`` counts round trips per record so the
scheduler can refuse to thrash one victim repeatedly.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["PagedSlot", "SlotPager"]


def _payload_bytes(payload) -> int:
    import jax

    return sum(int(np.asarray(leaf).nbytes)
               for leaf in jax.tree_util.tree_leaves(payload))


@dataclass
class PagedSlot:
    """One parked request: everything needed to re-admit it."""

    request: Any                      # serving.batching.Request
    row: int                          # which row of the request this was
    limit: int                        # per-request decode budget
    t_admit: float                    # original admission time (deadline!)
    history: List[int]                # draft-proposer emission history
    tokens_done: int                  # emissions so far (budget tracking)
    payload: Dict[str, Any]           # extract_slot snapshot, host-side
    nbytes: int = 0
    pages: int = 1                    # page-out round trips so far
    admit_step: int = 0


class SlotPager:
    """FIFO host pool of :class:`PagedSlot` records under a byte budget.

    Thread-safe; the scheduler holds its own lock across page-out/in
    *decisions*, the pager only guards its queue.
    """

    def __init__(self, max_mb: float = 256.0):
        self.max_bytes = int(max_mb * (1 << 20))
        self._lock = threading.Lock()
        self._queue: "deque[PagedSlot]" = deque()
        self._bytes = 0
        self.paged_out = 0
        self.paged_in = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def has_room(self, nbytes: int) -> bool:
        with self._lock:
            return self._bytes + nbytes <= self.max_bytes

    def park(self, record: PagedSlot) -> bool:
        """Enqueue; False (caller keeps the slot resident) when the
        record would bust the byte budget."""
        if record.nbytes <= 0:
            record.nbytes = _payload_bytes(record.payload)
        with self._lock:
            if self._bytes + record.nbytes > self.max_bytes:
                return False
            self._queue.append(record)
            self._bytes += record.nbytes
            self.paged_out += 1
            return True

    def pop(self) -> Optional[PagedSlot]:
        """Oldest parked record (FIFO — no starvation), or None."""
        with self._lock:
            if not self._queue:
                return None
            rec = self._queue.popleft()
            self._bytes -= rec.nbytes
            self.paged_in += 1
            return rec

    def sweep_expired(self, expired) -> List[PagedSlot]:
        """Remove and return records for which ``expired(record)`` is
        true — the paged half of the scheduler's deadline sweep."""
        out: List[PagedSlot] = []
        with self._lock:
            keep: "deque[PagedSlot]" = deque()
            for rec in self._queue:
                if expired(rec):
                    self._bytes -= rec.nbytes
                    out.append(rec)
                else:
                    keep.append(rec)
            self._queue = keep
        return out

    def drop_request(self, req) -> bool:
        """Purge a specific request (client abandon / server drop)."""
        dropped = self.sweep_expired(lambda rec: rec.request is req)
        return bool(dropped)

    def clear(self) -> List[PagedSlot]:
        with self._lock:
            out = list(self._queue)
            self._queue.clear()
            self._bytes = 0
        return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "parked": len(self._queue),
                "bytes": self._bytes,
                "paged_out": self.paged_out,
                "paged_in": self.paged_in,
            }
