"""`InferenceServer` — the overload-safe runtime in front of a compiled
forward (docs/serving.md).

The pipeline per request:

    submit() ── admission control ──> BatchQueue ──> supervised worker
      │   (closed? breaker open?          │    (coalesce to shape bucket,
      │    deadline feasible?             │     sweep expired, execute
      │    queue bounded?)                │     behind the breaker)
      └── typed rejection, immediately    └── reply or typed error

Guarantees (proven under chaos in tests/test_serving.py):

- **reply-or-typed-error** — every accepted request's future resolves to
  outputs or to one of ``serving.errors``; rejections raise immediately
  from ``submit``;
- **no fresh compiles on the hot path** — requests execute at the shape
  buckets primed by the warmup gate (sequence dims bucketed, batch dim a
  power of two, rows padded by replication);
- **deadline honesty** — a reply delivered after its deadline is
  converted to ``DeadlineExceeded``, so the success-latency p99 is
  bounded by the configured deadline *by construction*;
- **graceful degradation** — under queue pressure, generation-style
  models step down the configured tier ladder (e.g. beam -> greedy,
  shorter max_len) before anything is shed.

Two execution modes share that contract:

- ``mode="bucket"`` (default): one-shot compiled forwards, coalesced into
  precompiled shape buckets — state lives per call;
- ``mode="generation"``: continuous slot-based batching over a
  :class:`~paddle_tpu.serving.slots.SlotBackend` — a persistent decode
  table advanced one fused step at a time, finished requests' slots
  recycled to queued requests *between steps* (serving/slots.py;
  docs/serving.md "Continuous batching").  One long request no longer
  holds its batch hostage: short requests harvest and reply the moment
  their own beams finish.
"""

from __future__ import annotations

import inspect
import itertools
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.serving.batching import (BatchQueue, Request, ServingFuture,
                                         canonicalize_feed, merge_feeds,
                                         split_outputs)
from paddle_tpu.serving.breaker import CircuitBreaker
from paddle_tpu.serving.errors import (CircuitOpenError, DeadlineExceeded,
                                       InferenceFailed, InvalidRequestError,
                                       ServerClosed, ServingError, ShedError,
                                       WorkerCrashed)
from paddle_tpu.serving.metrics import ServerMetrics
from paddle_tpu.serving.worker import WorkerSupervisor
from paddle_tpu.obs.trace import get_tracer
from paddle_tpu.resilience.cluster import current_gang as _current_gang
from paddle_tpu.resilience.errors import GangError
from paddle_tpu.utils.log import logger

__all__ = ["InferenceServer"]


class _WorkerKilled(Exception):
    """Chaos-injected worker death (resilience.chaos.kill_worker)."""


#: request-trace ids (obs/trace.py): process-unique, allocated only when
#: tracing is armed — `obs merge --request=ID` / `future.req_id`
_REQ_SEQ = itertools.count(1)

#: failure statuses whose traces tail sampling must ALWAYS keep — the
#: incidents a p99 postmortem is about.  invalid_request is a client bug
#: (head-sampled like successes); everything else is the server's story.
_RETAIN_STATUSES = frozenset({
    "shed", "deadline_infeasible", "deadline_expired", "breaker_rejected",
    "inference_failed", "worker_crashed", "server_closed",
})

#: admission rejection -> (counter/status name, retained?)
_REJECT_STATUS = {
    "ShedError": "shed",
    "DeadlineExceeded": "deadline_infeasible",
    "CircuitOpenError": "breaker_rejected",
    "InvalidRequestError": "invalid_request",
    "ServerClosed": "server_closed",
}


def _has_nonfinite(outputs: Dict[str, Any]) -> bool:
    for v in outputs.values():
        a = np.asarray(v)
        if a.dtype.kind == "f" and a.size and not np.all(np.isfinite(a)):
            return True
    return False


class InferenceServer:
    """Serve a compiled forward with batching, shedding, deadlines, and a
    supervised worker.

    ``model`` is an :class:`~paddle_tpu.config.deploy.InferenceModel`, or
    any callable ``fn(feed) -> {name: array}``; a callable taking a
    second argument receives the active degradation-tier options dict
    (``fn(feed, tier_opts)``) — that is how generation backends accept
    ``{"greedy": True, "max_len": 32}`` style step-downs.

    With ``mode="generation"``, ``model`` is a
    :class:`~paddle_tpu.serving.slots.SlotBackend` and the worker runs
    the continuous slot loop (harvest -> admit -> one fused decode step)
    instead of one-shot bucket calls; ``slots`` bounds both the decode
    table and admission (a request's rows must fit the table), and the
    degradation ladder's ``{"max_len": n}`` tiers cap the decode budget
    of newly admitted requests under queue pressure.
    """

    RUNNING, FAILED, CLOSED = "running", "failed", "closed"

    def __init__(
        self,
        model,
        *,
        mode: str = "bucket",
        slots: int = 8,
        outputs: Optional[Sequence[str]] = None,
        max_batch: int = 8,
        batch_delay_ms: float = 2.0,
        max_queue: int = 64,
        default_deadline_ms: float = 1000.0,
        breaker_threshold: int = 5,
        breaker_cooldown_s: float = 5.0,
        breaker_probes: int = 1,
        max_restarts: int = 3,
        restart_backoff_s: float = 0.05,
        max_restart_backoff_s: float = 2.0,
        hang_timeout_s: float = 0.0,
        degrade: Optional[List[dict]] = None,
        degrade_at: Optional[List[int]] = None,
        nonfinite: str = "error",
        spec_k: int = 0,
        draft=None,
        prefix_cache_mb: float = 0.0,
        slot_page_pool_mb: float = 0.0,
        clock=time.monotonic,
        sleep=time.sleep,
    ) -> None:
        if nonfinite not in ("error", "allow"):
            raise ValueError("nonfinite must be 'error' or 'allow'")
        if mode not in ("bucket", "generation"):
            raise ValueError("mode must be 'bucket' or 'generation'")
        self.model = model
        self.mode = mode
        if mode == "generation":
            # the slot table bounds admission: a request's rows must fit it
            max_batch = int(slots)
        self.max_batch = int(max_batch)
        self.batch_delay_s = float(batch_delay_ms) / 1e3
        self.default_deadline_ms = float(default_deadline_ms)
        self.nonfinite = nonfinite
        self._clock = clock
        self._outputs = list(outputs) if outputs else None
        self.metrics = ServerMetrics()
        self.queue = BatchQueue(max_queue)
        self.breaker = CircuitBreaker(
            threshold=breaker_threshold, cooldown_s=breaker_cooldown_s,
            probes_to_close=breaker_probes, clock=clock)
        self._scheduler = None
        if mode == "generation":
            from paddle_tpu.serving.slots import SlotScheduler

            if not (hasattr(model, "prefill") and hasattr(model, "step_fn")):
                raise TypeError(
                    "mode='generation' needs a SlotBackend (prefill/"
                    "step_fn/readout — serving/slots.py), got "
                    f"{type(model).__name__}")
            self._scheduler = SlotScheduler(
                model, slots=slots, clock=clock, spec_k=spec_k,
                draft=draft, prefix_cache_mb=prefix_cache_mb,
                page_pool_mb=slot_page_pool_mb)
            self._runner = None
        else:
            self._runner = self._make_runner(model)
        # degradation ladder: tier 0 = full service; thresholds default to
        # evenly-spaced queue-depth watermarks
        self.degrade = list(degrade or [])
        if degrade_at is not None:
            if len(degrade_at) != len(self.degrade):
                raise ValueError("degrade_at must match degrade in length")
            self.degrade_at = [int(d) for d in degrade_at]
        else:
            n = len(self.degrade)
            self.degrade_at = [max(1, (max_queue * (i + 1)) // (n + 1))
                               for i in range(n)]
        self._service_ema: Optional[float] = None  # seconds per batch
        #: wall-clock of start() -> ready, the fleet cold-start metric
        #: (docs/deploy.md); None until the readiness gate passes
        self.cold_start_s: Optional[float] = None
        self._compile_cache = None
        self._feeder = None   # attach_feeder(): healthz surfaces its drops
        self._model_info: Optional[dict] = None   # set_model_info()
        self._model_loaded_at: Optional[float] = None
        self._gang = None     # healthz(): resolved once, lazily
        self._state = self.RUNNING
        self._ready = False
        self._fail_reason: Optional[str] = None
        self._in_flight: List[Request] = []
        self._kill_worker = False
        #: generation-mode hot-swap staging: (scheduler, model, info),
        #: flipped by the worker once the current table fully drains
        self._swap_next = None
        self.supervisor = WorkerSupervisor(
            (self._serve_generation_once if mode == "generation"
             else self._serve_once),
            max_restarts=max_restarts,
            backoff_s=restart_backoff_s,
            max_backoff_s=max_restart_backoff_s,
            hang_timeout_s=hang_timeout_s,
            on_crash=self._on_worker_crash,
            on_give_up=self._on_worker_give_up,
            # a relaunched generation worker starts from a FRESH table: the
            # crash may have left the carry poisoned, and its resident
            # requests were already failed typed by on_crash.  Late-bound
            # — a generation hot-swap replaces self._scheduler, and a
            # relaunch must reset the CURRENT table, not the retired one
            on_relaunch=((lambda: self._scheduler.reset())
                         if self._scheduler is not None else None),
            clock=clock,
            sleep=sleep,
        )

    # ------------------------------------------------------------------
    # model adapters
    # ------------------------------------------------------------------

    def _make_runner(self, model):
        """Normalize the backend to ``runner(feed, tier_opts)``."""
        infer = getattr(model, "infer", None)
        if infer is not None and hasattr(model, "topology"):
            def run(feed, tier_opts):
                outs = self._outputs
                if tier_opts.get("outputs"):
                    outs = list(tier_opts["outputs"])
                return infer(feed, outputs=outs)

            return run
        if not callable(model):
            raise TypeError(
                "model must be an InferenceModel or a callable "
                "fn(feed[, tier_opts]) -> {name: array}")
        try:
            takes_tier = len(inspect.signature(model).parameters) >= 2
        except (TypeError, ValueError):
            takes_tier = False
        if takes_tier:
            return lambda feed, tier_opts: model(feed, tier_opts)
        return lambda feed, tier_opts: model(feed)

    # ------------------------------------------------------------------
    # lifecycle: warmup/readiness gate -> running -> closed/failed
    # ------------------------------------------------------------------

    def start(self, *, warmup_feed=None, warmup: bool = True,
              preflight: bool = False,
              compile_cache=None) -> "InferenceServer":
        """Prime the compile caches, optionally run the lint preflight,
        then start the supervised worker.

        ``warmup_feed`` is one feed dict or a LIST of feed dicts.  Every
        batch bucket of every given feed's canonical shape is compiled
        before the server reports ready — a cold jit on the first user
        request would blow any reasonable deadline.  Coverage follows
        the feeds: a sequence model serves un-warmed sequence buckets
        with one cold compile on first use, so pass a representative
        feed per expected length bucket (e.g. T=16/64/256).

        ``compile_cache`` (config.compile_cache — a ``--compile_cache_dir``
        or the bundle's embedded ``aot/`` members) turns the warmup gate
        into a LOAD path: every bucket executable previously warmed
        anywhere in the fleet deserializes in milliseconds instead of
        re-running XLA, covering both the bucket forwards and the
        continuous-batching slot closures.  Hits/misses and the
        start->ready wall-clock surface in ``healthz()['cold_start']``."""
        t_start = self._clock()
        self._compile_cache = compile_cache
        feeds = (warmup_feed if isinstance(warmup_feed, (list, tuple))
                 else [warmup_feed] if warmup_feed is not None else [])
        if preflight:
            if self.mode == "generation":
                # generation preflight: the compiled decode_step closure is
                # the hot path — a host transfer there fires once per token
                # per request (same contract as `lint --serve` / audit_decode)
                from paddle_tpu.analysis import errors_summary
                from paddle_tpu.serving.slots import audit_slot_backend

                bad = errors_summary(audit_slot_backend(
                    self.model, slots=self._scheduler.slots,
                    spec_k=self._scheduler.spec_k))
                if bad:
                    raise ServingError(
                        f"slot decode_step failed the preflight audit: {bad}")
            else:
                from paddle_tpu.serving.preflight import check_serving

                check_serving(self.model,
                              example_feed=feeds[0] if feeds else None,
                              outputs=self._outputs)
        if warmup:
            if self.mode == "generation":
                self._warmup_generation(feeds)
            else:
                self._warmup(feeds)
        self.supervisor.start()
        self._ready = True
        self.cold_start_s = self._clock() - t_start
        return self

    def prime_model(self, model,
                    feeds: Optional[List[Dict[str, Any]]] = None
                    ) -> Optional[dict]:
        """Prime ``model``'s bucket compile surfaces — every batch bucket
        of every feed's canonical shape — against this server's compile
        cache.  This is the warmup gate of ``start()``, and the OFF-hot-
        path warm step of the hot-swap reload (serving/reload.py): the
        incoming model is primed here, in the caller's thread, while the
        worker keeps serving the current model; with a warm cache and an
        architecture-keyed fingerprint every executable loads instead of
        compiling.  Returns prime counts, or None when there is nothing
        to prime (plain callable without an example feed)."""
        if not feeds and hasattr(model, "topology"):
            from paddle_tpu.serving.feeds import example_feed

            feeds = [example_feed(model.topology)]
        if not feeds:
            return None  # plain callable without an example
        from paddle_tpu.serving.batching import (batch_bucket,
                                                 warmup_bucket_feeds)

        # derived from batch_bucket itself so warmup can never drift from
        # the hot path's bucket ladder: exactly the shapes merge_feeds
        # can produce for any row count
        buckets = sorted({batch_bucket(r, self.max_batch)
                          for r in range(1, self.max_batch + 1)})
        compiled = hits = 0
        # InferenceModel warms through prime(): the cache can swap the
        # compile for a deserialize, and the warmed AOT executables ARE
        # what infer() serves.  Plain callables keep the execute-once path.
        prime = getattr(model, "prime", None)
        runner = self._runner if model is self.model else None
        for feed in feeds:
            for padded in warmup_bucket_feeds(feed, buckets):
                if prime is not None:
                    r = prime(padded, outputs=self._outputs,
                              cache=self._compile_cache)
                    if r == "hit":
                        hits += 1
                        self.metrics.inc("compile_cache_hits")
                    elif r == "warm":
                        pass  # duplicate signature: no compile was paid
                    else:
                        compiled += 1
                        self.metrics.inc("warmup_compiles")
                        if r == "miss":
                            self.metrics.inc("compile_cache_misses")
                else:
                    if runner is None:
                        runner = self._make_runner(model)
                    runner(padded, {})
                    compiled += 1
                    self.metrics.inc("warmup_compiles")
        return {"compiled": compiled, "hits": hits,
                "feeds": len(feeds), "buckets": len(buckets)}

    def _warmup(self, feeds: List[Dict[str, Any]]) -> None:
        t0 = self._clock()
        counts = self.prime_model(self.model, feeds)
        if counts is None:
            return
        logger.info("serving warmup: %d bucket shape(s) over %d feed(s) — "
                    "%d compiled, %d cache-loaded in %.2fs",
                    counts["compiled"] + counts["hits"], counts["feeds"],
                    counts["compiled"], counts["hits"],
                    self._clock() - t0)

    def _warmup_generation(self, feeds: List[Dict[str, Any]]) -> None:
        """Prime the continuous path's whole compile surface before ready:
        prefill+write at every admission row bucket of every feed shape,
        plus one full admit -> step -> harvest cycle (step, finalize,
        release).  A cold compile between steps would stall every resident
        slot, not just the admitted request."""
        from paddle_tpu.serving.batching import batch_bucket

        sched = self._scheduler
        if not feeds:
            feeds = [self.model.example_feed(1)]
        buckets = sorted({batch_bucket(r, self.max_batch)
                          for r in range(1, self.max_batch + 1)})
        t0 = self._clock()
        counts = None
        if self._compile_cache is not None:
            # load-or-compile every slot closure (prefill per admission
            # bucket + step/write/release/finalize) from the persistent
            # cache FIRST: the synthetic admission cycle below then
            # exercises the loaded executables instead of compiling
            counts = sched.prime(self._compile_cache, feeds,
                                 buckets=buckets)
        if counts and not counts.get("skipped"):
            self.metrics.inc("compile_cache_hits", counts["hits"])
            self.metrics.inc("compile_cache_misses", counts["misses"])
            self.metrics.inc("warmup_compiles", counts["misses"])
        # DELTA, not absolute: jit caches are per-closure but this
        # process may have run earlier servers whose compiles must not
        # bleed into this boot's count
        jit_before = sched.compiled_programs()
        for feed in feeds:
            canon, _, sig = canonicalize_feed(feed)
            one = {
                name: (tuple(p[:1] for p in v) if isinstance(v, tuple)
                       else v[:1])
                for name, v in canon.items()
            }

            def synth(n):
                return [Request(feed=one, rows=1, signature=sig,
                                future=ServingFuture(), deadline=None,
                                t_submit=t0, max_len=1)
                        for _ in range(n)]

            for bucket in buckets:
                sched.admit(synth(min(bucket, sched.slots)))
                sched.reset()
        # one full cycle: step + finalize + release compile here
        sched.admit(synth(1))
        sched.step()
        sched.harvest()
        # gating routes step() by proposer confidence, so the cycle
        # above proved only one of the two step programs — warm both
        sched.prime_step_programs()
        sched.reset()
        # the synthetic traffic must not read as served traffic on healthz
        sched.admitted = sched.recycled = sched.steps_run = 0
        sched.spec_drafted = sched.spec_accepted = 0
        sched.last_spec = None
        if sched.prefix_cache is not None:
            # the synthetic feed's cache entry + its hit/miss counts are
            # warmup noise, not traffic
            sched.prefix_cache.clear()
            sched.prefix_cache.hits = sched.prefix_cache.misses = 0
            sched.prefix_cache.evictions = 0
        # report the compiles the jit closures ACTUALLY paid during the
        # cycle, not an estimate — warmup_compiles is the cold-start
        # acceptance surface.  On a fully-primed boot this is zero (the
        # cycle ran the AOT executables); any signature that slipped past
        # prime and fell back to a jit is counted honestly either way.
        self.metrics.inc("warmup_compiles",
                         max(0, sched.compiled_programs() - jit_before))
        logger.info("generation warmup: %d admission bucket(s) over %d "
                    "feed(s) + 1 step cycle compiled in %.2fs",
                    len(buckets), len(feeds), self._clock() - t0)

    @property
    def ready(self) -> bool:
        return self._ready and self._state == self.RUNNING

    # ------------------------------------------------------------------
    # zero-downtime hot-swap (docs/publish.md; serving/reload.py)
    # ------------------------------------------------------------------

    def swap_model(self, model, *, info: Optional[dict] = None):
        """Replace the serving backend between batches — the reload
        path's commit point.  The worker reads ``self._runner`` once per
        popped batch, so every batch is served entirely by exactly one
        model generation: a batch in flight finishes on the old version,
        the next pop serves the new one — no request is dropped or
        served by a half-loaded model.  Prime the incoming model FIRST
        (``prime_model``) or its first buckets pay cold compiles on the
        hot path.  Returns the previous model; the caller keeps it
        resident until the probation window passes (rollback swaps it
        straight back).

        Generation mode drains instead of cutting over: the incoming
        :class:`~paddle_tpu.serving.slots.SlotBackend` gets a fresh slot
        table built (and primed, when a compile cache is attached) in
        THIS caller's thread, then the swap is staged — the worker stops
        admitting, lets resident requests finish on the old table, and
        flips scheduler + model atomically once it is empty.  The old
        scheduler's prefix cache is cleared at the flip (its keys embed
        the old fingerprint; clearing frees the bytes immediately)."""
        if self.mode != "bucket":
            from paddle_tpu.serving.slots import SlotScheduler

            if not (hasattr(model, "prefill") and hasattr(model, "step_fn")):
                raise TypeError(
                    "generation swap needs a SlotBackend (prefill/step_fn/"
                    f"readout), got {type(model).__name__}")
            old = self._scheduler
            sched = SlotScheduler(
                model, slots=old.slots, clock=self._clock,
                spec_k=old.spec_k, draft=old.proposer,
                prefix_cache_mb=(0.0 if old.prefix_cache is None else
                                 old.prefix_cache.max_bytes / (1 << 20)),
                page_pool_mb=(0.0 if old.pager is None else
                              old.pager.max_bytes / (1 << 20)))
            if self._compile_cache is not None:
                sched.prime(self._compile_cache, [model.example_feed(1)])
            prev = self.model
            self._swap_next = (sched, model, info)
            return prev
        runner = self._make_runner(model)
        prev = self.model
        self.model = model
        self._runner = runner   # atomic attribute store: the swap point
        self.set_model_info(info)
        self.metrics.inc("model_swaps")
        return prev

    def set_model_info(self, info: Optional[dict]) -> None:
        """Attach the served artifact's identity to the health surface:
        ``healthz()['model']`` plus the registry gauges
        ``serving_model_version`` / ``serving_model_freshness_seconds``
        (the freshness SLO instrument — docs/publish.md)."""
        self._model_info = dict(info) if info else None
        self._model_loaded_at = time.time() if info else None
        if self._model_info is not None:
            v = self._model_info.get("version")
            if v is not None:
                self.metrics.gauge("model_version").set(float(v))

    def close(self, join_timeout: float = 2.0) -> None:
        if self._state == self.CLOSED:
            return
        self._state = self.CLOSED
        self._fail_requests(
            self.queue.close(),
            lambda: ServerClosed("server shut down"), "server_closed")
        self.supervisor.stop(join_timeout)
        # the worker generation is retired: a batch still executing will
        # discard its results instead of completing futures, so fail the
        # in-flight requests too (set-once: a no-op for any the worker
        # finished before the stop) — shutdown must not silently drop
        in_flight, self._in_flight = self._in_flight, []
        self._fail_requests(
            in_flight,
            lambda: ServerClosed("server shut down with the batch in flight"),
            "server_closed")
        # retire this server's series from the shared obs registry: the
        # scrape endpoint must not grow a dead server=sN label set per
        # restart (healthz() keeps reading the detached counters)
        self.metrics.unregister()

    # ------------------------------------------------------------------
    # admission control
    # ------------------------------------------------------------------

    def submit(self, feed: Dict[str, Any],
               deadline_ms: Optional[float] = None,
               max_len: Optional[int] = None,
               session_id: Optional[str] = None,
               trace_attrs: Optional[Dict[str, Any]] = None
               ) -> ServingFuture:
        """Admit one request (a dict feed with a leading batch dim on
        every part) or raise a typed rejection immediately.  Returns a
        :class:`ServingFuture` that is *guaranteed* to resolve.

        ``max_len`` (generation mode) is the request's own decode budget;
        it must fit the slot table's depth (the backend's ``max_len``).
        ``session_id`` scopes the request's prefix-cache entry to a chat
        session (docs/serving.md "Prefix/session caching"); without a
        prefix cache it is carried but unused.

        With request tracing armed (``--obs_journal``; obs/trace.py) the
        call opens a request trace whose child spans decompose the whole
        lifecycle — admission, queue wait, merge/prefill, every fused
        decode step the request participated in, harvest, reply — and the
        returned future carries ``req_id`` for ``obs merge --request=``.
        Typed rejections end the trace with their status; shed and
        deadline rejections are retained by tail sampling."""
        tracer = get_tracer()
        if not tracer.enabled:
            return self._submit(feed, deadline_ms, max_len, session_id,
                                None, "", 0.0)
        rid = f"req-{os.getpid()}-{next(_REQ_SEQ):06d}"
        t0 = time.time()
        root = tracer.start_trace("request", request=rid, mode=self.mode)
        if trace_attrs:
            # fleet routing identity (tenant / model / version —
            # serving/fleet.py): attached BEFORE _submit so even a typed
            # rejection's trace names who was rejected, and before
            # offer() so the worker can never flush an unlabeled root
            root.set(**trace_attrs)
        try:
            fut = self._submit(feed, deadline_ms, max_len, session_id,
                               root, rid, t0)
        except ServingError as e:
            status = _REJECT_STATUS.get(type(e).__name__,
                                        type(e).__name__)
            if "rows" not in root.attrs:
                # rejected before the accepted-path recording; a shed AT
                # offer() already carries its outcome=accepted admission
                # span — the root status says what happened next
                root.child_at("admission", t0, time.time(),
                              outcome=status)
            if status in _RETAIN_STATUSES:
                root.retain(status)
            root.end(status=status, error=str(e))
            raise
        fut.req_id = rid
        return fut

    def _submit(self, feed: Dict[str, Any],
                deadline_ms: Optional[float],
                max_len: Optional[int],
                session_id: Optional[str],
                root, rid: str, t_trace: float) -> ServingFuture:
        self.metrics.inc("submitted")
        if self._state != self.RUNNING:
            self.metrics.inc("server_closed")
            raise ServerClosed(self._fail_reason or "server is closed")
        if not self._ready:
            self.metrics.inc("shed")
            raise ShedError("server is still warming up (not ready)")
        if max_len is not None:
            depth = getattr(self.model, "max_len", None)
            if self.mode != "generation":
                self.metrics.inc("invalid_request")
                raise InvalidRequestError(
                    "max_len is a generation-mode request option")
            if max_len < 1 or (depth is not None and max_len > depth):
                self.metrics.inc("invalid_request")
                raise InvalidRequestError(
                    f"request max_len {max_len} outside the slot table's "
                    f"depth 1..{depth} — raise the backend's max_len")
        try:
            canon, rows, sig = canonicalize_feed(feed)
        except ValueError as e:
            # malformed feeds reject typed like every other admission
            # failure — a client's `except ServingError` accounting must
            # see them (InvalidRequestError is also a ValueError)
            self.metrics.inc("invalid_request")
            raise InvalidRequestError(str(e)) from e
        if rows > self.max_batch:
            # an oversized request could never be selected by the batcher:
            # admitting it would park it in the queue forever — reject it
            # immediately instead (the client should split it)
            self.metrics.inc("invalid_request")
            raise InvalidRequestError(
                f"request carries {rows} rows but the server batches at "
                f"most {self.max_batch} — split the request")
        if rows == 0:
            # a zero-row request must never reach the device: merged it
            # would break the warmed-bucket invariant (a B=0 compile on
            # the hot path), and its crash would count toward the breaker.
            # An InferenceModel replies empty WITHOUT executing (its
            # shape-inferred empty path); raw callables reject typed.
            if not hasattr(self.model, "topology"):
                self.metrics.inc("invalid_request")
                raise InvalidRequestError(
                    "zero-row request on a backend without shape "
                    "inference — nothing to execute")
            fut = ServingFuture()
            try:
                fut._complete(result=self._runner(canon, {}))
            except ValueError as e:
                # a request bug (missing slot, bad structure) rejects the
                # same way the populated admission path does — it is not
                # a model failure and must not read as one on dashboards
                self.metrics.inc("invalid_request")
                raise InvalidRequestError(
                    f"malformed empty request: {e}") from e
            except Exception as e:  # noqa: BLE001 — typed, not breaker-fed
                fut._complete(error=InferenceFailed(
                    f"empty-request shape inference failed: "
                    f"{type(e).__name__}: {e}"))
                self.metrics.inc("inference_failed")
                if root is not None:
                    root.retain("inference_failed")
                    root.end(status="inference_failed")
                return fut
            self.metrics.inc("accepted")
            self.metrics.inc("completed")
            if root is not None:
                # replied inline (shape-inferred empty outputs): the
                # whole lifecycle is the admission segment
                root.child_at("admission", t_trace, time.time(),
                              outcome="empty_inline")
                root.end(status="completed", rows=0)
            return fut
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        now = self._clock()
        deadline = now + deadline_ms / 1e3 if deadline_ms > 0 else None
        if not self.breaker.allow():
            self.metrics.inc("breaker_rejected")
            raise CircuitOpenError(
                "circuit breaker is open — backend failing; retry after "
                f"{self.breaker.cooldown_s:.1f}s")
        if deadline is not None and self._service_ema is not None:
            # feasibility estimate: one service time, plus the queue's
            # backlog in units of batches ahead of us
            depth = self.queue.depth()
            est = self._service_ema * (1.0 + depth / max(1, self.max_batch))
            if now + est > deadline:
                self.metrics.inc("deadline_infeasible")
                raise DeadlineExceeded(
                    f"infeasible deadline: {deadline_ms:.1f}ms budget vs "
                    f"~{est * 1e3:.1f}ms estimated queue+service time")
        req = Request(feed=canon, rows=rows, signature=sig,
                      future=ServingFuture(), deadline=deadline,
                      t_submit=now, deadline_ms=deadline_ms,
                      max_len=max_len, req_id=rid, span=root,
                      session_id=session_id)
        if root is not None:
            # every root mutation happens BEFORE offer(): the worker may
            # pop, serve, and FLUSH the trace the instant the request is
            # queued — attrs or spans attached after that land on a
            # flushed buffer and silently vanish
            root.set(rows=rows, deadline_ms=deadline_ms,
                     max_len=max_len)
            root.child_at("admission", t_trace, time.time(),
                          outcome="accepted",
                          queue_depth=self.queue.depth())
            # the queue span stays OPEN across the submit->worker thread
            # boundary; the worker ends it at pop (or expiry sweep), so
            # its duration IS the measured queue wait
            req.qspan = root.child("queue")
        try:
            self.queue.offer(req)
        except ShedError:
            self.metrics.inc("shed")
            if req.qspan is not None:   # never queued: close the segment
                req.qspan.end(status="shed", t_end=req.qspan.t_start)
            raise
        self.metrics.inc("accepted")
        return req.future

    def infer(self, feed: Dict[str, Any],
              deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None,
              max_len: Optional[int] = None) -> Dict[str, np.ndarray]:
        """Synchronous submit + wait."""
        fut = self.submit(feed, deadline_ms, max_len=max_len)
        if timeout is None and deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if timeout is None:
            timeout = (deadline_ms / 1e3 + 30.0) if deadline_ms > 0 else None
        return fut.result(timeout)

    # ------------------------------------------------------------------
    # the worker side
    # ------------------------------------------------------------------

    def _pick_tier(self, depth: int) -> int:
        tier = 0
        for i, watermark in enumerate(self.degrade_at):
            if depth >= watermark:
                tier = i + 1
        return tier

    def _fail_requests(self, reqs: List[Request], exc_factory,
                       counter: str) -> None:
        n = 0
        for r in reqs:
            if r.future._complete(error=exc_factory()):
                n += 1
                if r.span is not None:
                    # every typed-failure path funnels here: end the
                    # request trace with the counter as its status, and
                    # retain the incidents tail sampling must keep
                    if r.qspan is not None:
                        r.qspan.end(status=counter)
                    if counter in _RETAIN_STATUSES:
                        r.span.retain(counter)
                    r.span.end(status=counter)
        if n:
            self.metrics.inc(counter, n)

    def _serve_once(self, gen: int) -> None:
        batch, expired = self.queue.pop_batch(
            max_rows=self.max_batch,
            batch_delay_s=self.batch_delay_s,
            timeout=0.05,
            est_service_s=self._service_ema or 0.0,
            clock=self._clock)
        self._fail_requests(
            expired,
            lambda: DeadlineExceeded("deadline expired while queued"),
            "deadline_expired")
        if not batch:
            return
        if not self.breaker.allow():
            self._fail_requests(
                batch, lambda: CircuitOpenError("circuit breaker is open"),
                "breaker_rejected")
            return
        tier = self._pick_tier(self.queue.depth())
        tier_opts = self.degrade[tier - 1] if tier else {}
        if tier:
            for r in batch:
                r.tier = tier
            self.metrics.inc("degraded", len(batch))
        rows = sum(r.rows for r in batch)
        now_w = time.time()
        for r in batch:
            if r.qspan is not None:     # the measured queue wait ends here
                r.qspan.end(status="popped", t_end=now_w,
                            batch_mates=len(batch) - 1)
        # the batch is in flight from the moment it leaves the queue: a
        # failure ANYWHERE past this point (merge included) must reach
        # the crash handler with these futures still attributed
        self._in_flight = batch
        try:
            merged, slices, _ = merge_feeds(batch, self.max_batch)
        except Exception as e:  # noqa: BLE001 — structural mismatch
            self._fail_requests(
                batch,
                lambda: InvalidRequestError(
                    f"requests could not be merged into one batch: "
                    f"{type(e).__name__}: {e}"),
                "invalid_request")
            self._in_flight = []
            return
        self.supervisor.note_busy(gen)
        try:
            self._execute(gen, batch, merged, slices, rows, tier_opts)
        except BaseException:
            # crash/kill path: leave _in_flight populated — the monitor's
            # crash handler fails those futures with WorkerCrashed; clearing
            # here would turn a worker death into a silent drop
            self.supervisor.note_idle(gen)
            raise
        if self.supervisor.current(gen):
            self._in_flight = []
        self.supervisor.note_idle(gen)

    def _record_failure(self, gen: int) -> None:
        # breaker state belongs to the LIVE worker: an abandoned (hung,
        # replaced) worker that finally un-wedges must not pin failures
        # or successes on the healthy backend serving current traffic
        if not self.supervisor.current(gen):
            return
        trips_before = self.breaker.trips
        self.breaker.record_failure()
        if self.breaker.trips > trips_before:
            self.metrics.inc("breaker_trips")
            # a trip is an incident: it joins the cross-rank causal
            # timeline next to the gang/checkpoint records (no-op when
            # --obs_journal is unarmed)
            from paddle_tpu.obs import journal_event

            journal_event("breaker_trip", trips=self.breaker.trips)

    # ------------------------------------------------------------------
    # the generation worker: continuous slot loop (serving/slots.py)
    # ------------------------------------------------------------------

    def _complete_harvested(self, gen: int, req: Request, outputs,
                            steps: int) -> None:
        """Reply to one harvested request with the bucket path's exact
        deadline/nonfinite honesty."""
        now = self._clock()
        if (self.nonfinite == "error"
                and not np.all(np.isfinite(outputs["scores"]))):
            # rows are independent in the slot table, so poison stays in
            # its own request — co-resident slots are unaffected
            self._record_failure(gen)
            if req.future._complete(error=InferenceFailed(
                    "decode produced non-finite scores (poisoned "
                    "request?)")):
                self.metrics.inc("inference_failed")
                if req.span is not None:
                    req.span.retain("inference_failed")
                    req.span.end(status="inference_failed")
            return
        if self.supervisor.current(gen):
            self.breaker.record_success()
        if req.deadline is not None and now > req.deadline:
            if req.future._complete(error=DeadlineExceeded(
                    f"completed {1e3 * (now - req.deadline):.1f}ms past "
                    f"the {req.deadline_ms:.1f}ms deadline")):
                self.metrics.inc("deadline_expired")
                if req.span is not None:
                    req.span.retain("deadline_expired")
                    req.span.end(status="deadline_expired", steps=steps)
        elif req.future._complete(result=outputs):
            self.metrics.inc("completed")
            dt = now - req.t_submit
            # root end decides keep/drop FIRST: only a kept trace may be
            # the bucket's exemplar (see _execute)
            kept = (req.span.end(status="completed", steps=steps)
                    if req.span is not None else False)
            self.metrics.observe_latency(
                dt, trace_id=(req.span.trace_id if kept else None))
            self.metrics.observe_request_steps(steps)
            if self.supervisor.current(gen):
                self._service_ema = (dt if self._service_ema is None
                                     else 0.8 * self._service_ema + 0.2 * dt)

    def _serve_generation_once(self, gen: int) -> None:
        """One cycle of the continuous loop: evict expired slots, harvest
        finished ones, admit queued requests into the freed slots, run ONE
        fused decode step for every occupied slot.  Every phase keeps the
        bucket path's reply-or-typed-error guarantees."""
        sched = self._scheduler
        # staged hot-swap: admission is paused while a swap is pending
        # (free=0 below), so the table drains; once empty — host page
        # pool included — flip scheduler + model atomically and clear
        # the old prefix cache (its keys embed the retired fingerprint)
        if (self._swap_next is not None and sched.occupied() == 0
                and (sched.pager is None or len(sched.pager) == 0)):
            new_sched, new_model, info = self._swap_next
            self._swap_next = None
            if sched.prefix_cache is not None:
                sched.prefix_cache.clear()
            self.model = new_model
            self._scheduler = sched = new_sched
            self.set_model_info(info)
            self.metrics.inc("model_swaps")
        live = lambda: self.supervisor.current(gen)  # noqa: E731
        # deadline plane first: an expired resident can never reply in
        # time, and its slot is capacity short requests are waiting on
        evicted = sched.evict_expired(self._clock(), commit=live)
        if evicted:
            for r, n in evicted:
                if r.span is not None:
                    # eviction is mid-generation deadline death: mark the
                    # trace before _fail_requests ends+retains it, so a
                    # postmortem can split "expired queued" from "evicted
                    # while decoding"
                    r.span.event("evicted", slots_freed=n)
                    r.span.set(evicted=True)
            self._fail_requests(
                [r for r, _ in evicted],
                lambda: DeadlineExceeded("deadline expired mid-generation "
                                         "(slot evicted)"),
                "deadline_expired")
            freed = sum(n for _, n in evicted)
            self.metrics.inc("slot_evicted", freed)
            self.metrics.inc("slot_recycled", freed)
        # harvest synchronizes on the device (the previous step's async
        # dispatch materializes here, not in step()) — it must sit inside
        # the busy window or a wedged device never trips hang detection
        self.supervisor.note_busy(gen)
        hw0 = time.time()
        try:
            harvested = sched.harvest(commit=live)
        finally:
            self.supervisor.note_idle(gen)
        hw1 = time.time()
        for req, outputs, steps in harvested:
            if not live():
                return  # abandoned worker: its results are unwanted
            self.metrics.inc("slot_recycled", req.rows)
            if req.span is not None:
                req.span.child_at("harvest", hw0, hw1, steps=steps)
            self._complete_harvested(gen, req, outputs, steps)
        # admit into freed slots (the PR 5 queue/deadline/shed machinery,
        # at slot granularity): with residents decoding, the pop must not
        # block — the coalescing window only applies to an idle table.
        # The pop runs even with a FULL table (max_rows=0 selects
        # nothing): its sweep must keep evicting already-expired queued
        # requests, or dead work occupies the bounded queue and sheds
        # live traffic for up to a straggler's whole decode
        if sched.pager is not None and self._swap_next is None:
            # re-admit parked slots FIRST — paged work predates anything
            # in the queue and must not be overtaken indefinitely
            paged_in = sched.page_in(commit=live)
            if paged_in:
                self.metrics.inc("slots_paged_in", paged_in)
        free = (0 if self._swap_next is not None
                else sched.free_count())  # draining: admission paused
        occupied = sched.occupied()
        batch, expired = self.queue.pop_batch(
            max_rows=free,
            batch_delay_s=self.batch_delay_s if occupied == 0 else 0.0,
            timeout=0.05 if occupied == 0 else 0.0,
            est_service_s=self._service_ema or 0.0,
            clock=self._clock)
        self._fail_requests(
            expired,
            lambda: DeadlineExceeded("deadline expired while queued"),
            "deadline_expired")
        if batch and not self.breaker.allow():
            self._fail_requests(
                batch,
                lambda: CircuitOpenError("circuit breaker is open"),
                "breaker_rejected")
            batch = []
        if batch:
            tier = self._pick_tier(self.queue.depth())
            tier_opts = self.degrade[tier - 1] if tier else {}
            if tier:
                for r in batch:
                    r.tier = tier
                self.metrics.inc("degraded", len(batch))
            aw0 = time.time()
            for r in batch:
                if r.qspan is not None:    # queue wait ends at admission
                    r.qspan.end(status="popped", t_end=aw0,
                                batch_mates=len(batch) - 1)
            # the popped batch joins the in-flight set BEFORE the
            # device-bound prefill: a crash or hang inside admit must
            # fail these futures too, never silently drop them
            self._in_flight = sched.resident_requests() + batch
            self.supervisor.note_busy(gen)
            try:
                sched.admit(batch,
                            limit_cap=tier_opts.get("max_len"),
                            commit=live)
                if any(r.span is not None for r in batch):
                    aw1 = time.time()
                    slots_of = {id(req): s for req, s, _
                                in sched.resident_view()}
                    for r in batch:
                        if r.span is not None:
                            r.span.child_at(
                                "prefill", aw0, aw1,
                                slots=slots_of.get(id(r), []),
                                tier=r.tier,
                                limit_cap=tier_opts.get("max_len"))
            except _WorkerKilled:
                raise
            except ValueError as e:
                # a malformed admitted feed (e.g. source longer than the
                # table's fixed src_len) is a CLIENT bug: reject typed
                # like bucket-mode merge failures, never feed the breaker
                # (a retrying client could otherwise trip it and take
                # down healthy traffic)
                self._fail_requests(
                    batch,
                    lambda: InvalidRequestError(
                        f"request cannot enter the slot table: {e}"),
                    "invalid_request")
            except Exception as e:  # noqa: BLE001 — a model fault
                self._record_failure(gen)

                def _mk(e=e):
                    err = InferenceFailed(
                        f"prefill failed: {type(e).__name__}: {e}")
                    err.__cause__ = e
                    return err

                self._fail_requests(batch, _mk, "inference_failed")
            finally:
                self.supervisor.note_idle(gen)
        # paging: with the table full and work still queued, host-evict
        # ONE cold victim per cycle so next cycle's admission has a slot
        # (one per cycle bounds the d2h cost and self-limits churn)
        if (sched.pager is not None and self._swap_next is None
                and self.queue.depth() > 0 and sched.free_count() == 0):
            if sched.page_out_victim(commit=live):
                self.metrics.inc("slots_paged_out")
        # the table's residents are the in-flight set: a worker death past
        # this point must fail exactly these futures (WorkerCrashed)
        self._in_flight = sched.resident_requests()
        if not self._in_flight:
            return
        if self._kill_worker:
            self._kill_worker = False
            raise _WorkerKilled("chaos: worker killed mid-step")
        self.supervisor.note_busy(gen)
        sw0 = time.time()
        try:
            ran = sched.step(commit=live)
        except _WorkerKilled:
            self.supervisor.note_idle(gen)
            raise
        except Exception as e:  # noqa: BLE001 — a model fault, not a crash
            self.supervisor.note_idle(gen)
            self._record_failure(gen)
            residents = sched.reset()

            def _mk(e=e):
                err = InferenceFailed(
                    f"decode step failed: {type(e).__name__}: {e}")
                err.__cause__ = e
                return err

            self._fail_requests(residents, _mk, "inference_failed")
            self._in_flight = []
            return
        except BaseException:
            # crash/kill path: leave _in_flight populated for the crash
            # handler (reply-or-typed-error through worker death)
            self.supervisor.note_idle(gen)
            raise
        self.supervisor.note_idle(gen)
        if ran:
            self.metrics.inc("gen_steps")
            occupied = sched.occupied()
            self.metrics.observe_slots(occupied, sched.slots)
            if any(r.span is not None for r in self._in_flight):
                # every resident request's trace gets this fused step as a
                # child span — slot ids, its own step index, and the
                # co-residency it shared the table at.  This is the
                # attribution that turns "slow request" into "60 steps
                # sharing the table at 0.9 occupancy behind a straggler".
                sw1 = time.time()
                occ = round(occupied / sched.slots, 3)
                spec = sched.last_spec if sched.spec_k > 0 else None
                for req, slots_, nsteps in sched.resident_view():
                    if req.span is not None:
                        attrs = dict(slots=slots_, step=nsteps,
                                     occupancy=occ)
                        if spec is not None:
                            # the speculation win, attributed per
                            # request: tokens this wide step emitted
                            # for it, and how many were accepted drafts
                            attrs["spec_emitted"] = int(
                                sum(spec[0][s] for s in slots_))
                            attrs["spec_accepted"] = int(
                                sum(spec[1][s] for s in slots_))
                        req.span.child_at("decode_step", sw0, sw1,
                                          **attrs)

    def _execute(self, gen: int, batch: List[Request], merged, slices,
                 rows: int, tier_opts: dict) -> None:
        if self._kill_worker:
            self._kill_worker = False
            raise _WorkerKilled("chaos: worker killed mid-batch")
        t0 = self._clock()
        tw0 = time.time()
        try:
            outputs = self._runner(merged, tier_opts)
        except _WorkerKilled:
            raise
        except Exception as e:  # noqa: BLE001 — a model fault, not a crash
            self._record_failure(gen)

            def _mk(e=e):
                err = InferenceFailed(
                    f"model call failed: {type(e).__name__}: {e}")
                err.__cause__ = e
                return err

            self._fail_requests(batch, _mk, "inference_failed")
            return
        dt = self._clock() - t0
        tw1 = time.time()
        for r in batch:
            if r.span is not None:
                # one compiled forward served the whole merged batch: each
                # co-batched request gets the segment with its sharing
                # context (who it paid the batch with)
                r.span.child_at("execute", tw0, tw1, rows=r.rows,
                                batch_rows=rows, tier=r.tier)
        if self.supervisor.current(gen):
            self._service_ema = (dt if self._service_ema is None
                                 else 0.8 * self._service_ema + 0.2 * dt)
            self.metrics.observe_batch(rows)
        if self.nonfinite == "error" and _has_nonfinite(outputs):
            self._record_failure(gen)
            self._fail_requests(
                batch,
                lambda: InferenceFailed(
                    "model produced non-finite outputs (poisoned batch?)"),
                "inference_failed")
            return
        if self.supervisor.current(gen):
            self.breaker.record_success()
        per_req = split_outputs(outputs, slices)
        now = self._clock()
        for r, out in zip(batch, per_req):
            if not self.supervisor.current(gen):
                return  # abandoned worker: its results are unwanted
            if r.deadline is not None and now > r.deadline:
                if r.future._complete(error=DeadlineExceeded(
                        f"completed {1e3 * (now - r.deadline):.1f}ms past "
                        f"the {r.deadline_ms:.1f}ms deadline")):
                    self.metrics.inc("deadline_expired")
                    if r.span is not None:
                        r.span.retain("deadline_expired")
                        r.span.end(status="deadline_expired")
            elif r.future._complete(result=out):
                self.metrics.inc("completed")
                # the root ends BEFORE the latency observation: only a
                # trace tail sampling actually KEPT may ride the
                # histogram bucket as an exemplar — a dashboard must
                # never link to a trace the journal doesn't have
                kept = (r.span.end(status="completed")
                        if r.span is not None else False)
                self.metrics.observe_latency(
                    now - r.t_submit,
                    trace_id=(r.span.trace_id if kept else None))

    # ------------------------------------------------------------------
    # supervision callbacks + chaos hooks
    # ------------------------------------------------------------------

    def _on_worker_crash(self, exc: Exception) -> None:
        in_flight, self._in_flight = self._in_flight, []
        self._fail_requests(
            in_flight,
            lambda: WorkerCrashed(f"worker died mid-batch: {exc}"),
            "worker_crashed")

    def _on_worker_give_up(self, exc: Exception) -> None:
        self._state = self.FAILED
        self._fail_reason = (f"worker restart budget exhausted "
                             f"({self.supervisor.max_restarts}): {exc}")
        self._fail_requests(
            self.queue.close(),
            lambda: WorkerCrashed(self._fail_reason), "worker_crashed")

    def chaos_kill_worker(self) -> None:
        """Chaos hook (``resilience.chaos.kill_worker``): the worker dies
        with the next popped batch in flight — the mid-batch crash model
        the supervisor must recover from."""
        self._kill_worker = True

    # ------------------------------------------------------------------
    # health surface
    # ------------------------------------------------------------------

    def attach_feeder(self, feeder) -> None:
        """Register the DataFeeder converting raw rows for this server so
        ``healthz()`` surfaces its ``dropped_features`` counter — sparse-bag
        truncation (max_len/max_nnz caps) is silent data loss otherwise."""
        self._feeder = feeder

    def healthz(self) -> dict:
        # the supervisor owns the relaunch count (it alone knows whether a
        # crash led to a restart or exhausted the budget) — mirror it into
        # the registry view FIRST so healthz, /metrics, and
        # worker.restarts can never disagree
        self.metrics.set_count("worker_restarts", self.supervisor.restarts)
        if self._scheduler is not None:
            # the scheduler owns the decode-speed counters (speculation,
            # prefix cache, paging) — mirror them into the registry
            # BEFORE the snapshot so healthz and /metrics agree
            _s = self._scheduler
            if _s.pager is not None:
                _p = _s.pager.stats()
                self.metrics.set_count("slots_paged_out", _p["paged_out"])
                self.metrics.set_count("slots_paged_in", _p["paged_in"])
            if _s.spec_k > 0:
                self.metrics.set_count("spec_draft_tokens_total",
                                       _s.spec_drafted)
                self.metrics.set_count("spec_accepted_tokens_total",
                                       _s.spec_accepted)
                self.metrics.gauge("spec_accept_rate").set(round(
                    _s.spec_accepted / _s.spec_drafted
                    if _s.spec_drafted else 0.0, 4))
            if _s.prefix_cache is not None:
                _c = _s.prefix_cache.stats()
                self.metrics.set_count("prefix_cache_hits", _c["hits"])
                self.metrics.set_count("prefix_cache_misses", _c["misses"])
        snap = self.metrics.snapshot()
        out = {
            "ready": self.ready,
            "state": self._state,
            "mode": self.mode,
            "queue_depth": self.queue.depth(),
            "breaker": self.breaker.snapshot(),
            "worker": {"alive": self.supervisor.alive(),
                       "restarts": self.supervisor.restarts,
                       "max_restarts": self.supervisor.max_restarts},
            "service_ema_ms": (round(self._service_ema * 1e3, 3)
                               if self._service_ema is not None else None),
            # fleet cold-start surface (docs/deploy.md): how long this
            # replica took to reach ready, and whether the warmup gate
            # compiled (cache misses) or loaded (hits) its executables —
            # a warm fleet rollout is pinned by compile_cache_misses == 0
            "cold_start": {
                "cold_start_s": (round(self.cold_start_s, 3)
                                 if self.cold_start_s is not None else None),
                "compile_cache_hits": self.metrics.count(
                    "compile_cache_hits"),
                "compile_cache_misses": self.metrics.count(
                    "compile_cache_misses"),
                "warmup_compiles": self.metrics.count("warmup_compiles"),
            },
            **snap,
        }
        if self._feeder is not None:
            out["dropped_features"] = int(
                getattr(self._feeder, "dropped_features", 0))
        info = self._model_info
        if info is not None:
            # the served artifact's identity + the freshness SLO
            # (docs/publish.md): wall-clock age of the train commit the
            # served weights came from.  Schema pinned by
            # tests/test_serving.py; the gauge mirrors healthz so a
            # --metrics_port scrape tells the same story.
            tct = info.get("train_commit_time")
            fresh = (round(time.time() - float(tct), 3)
                     if tct is not None else None)
            self.metrics.gauge("model_freshness_seconds").set(fresh)
            out["model"] = {
                "bundle": info.get("bundle"),
                "version": info.get("version"),
                "fingerprint": info.get("fingerprint"),
                "quantize": info.get("quantize"),
                "loaded_at": self._model_loaded_at,
                "freshness_s": fresh,
            }
        if self._gang is None:
            # resolved ONCE and cached: for an elastic-joiner replica
            # (epoch env > 0) GangContext.__init__ re-validates world.json
            # and raises when the attempt dir was swept — the health probe
            # must report that, never throw it.  A failed resolve retries
            # on the next call (the file may be momentarily unreadable).
            try:
                self._gang = _current_gang()
            except GangError as e:
                out["gang"] = {"error": f"{type(e).__name__}: {e}"}
        gang = self._gang
        if gang is not None:
            # a supervised serving replica surfaces its gang's elastic
            # state: how big the live world is, whether it is running
            # degraded, and which epoch it lives in.  peek_world() folds
            # in a published-but-not-adopted shrink/grow — a replica
            # never runs the resize protocol itself, but its healthz
            # must not report the construction-time world forever.
            view = gang.peek_world()
            out["gang"] = {
                "world_size": len(view["ranks"]),
                "configured_size": gang.size,
                "degraded": len(view["ranks"]) < gang.size,
                "epoch": view["epoch"],
                "coordinator": view["coordinator"],
            }
        if self._scheduler is not None:
            sched = self._scheduler
            occupied = sched.occupied()
            out["slots"] = {
                "capacity": sched.slots,
                "occupied": occupied,
                "free": sched.free_count(),
                "admitted": sched.admitted,
                "recycled": sched.recycled,
                "steps": sched.steps_run,
            }
            if sched.pager is not None:
                pstats = sched.pager.stats()
                out["slots"]["paged_out"] = pstats["paged_out"]
                out["slots"]["paged_in"] = pstats["paged_in"]
                out["slots"]["parked"] = pstats["parked"]
            if sched.spec_k > 0:
                # speculation efficiency: accepted drafts / offered
                # drafts — the knob to tune --spec_k against
                rate = (sched.spec_accepted / sched.spec_drafted
                        if sched.spec_drafted else 0.0)
                out["spec"] = {
                    "k": sched.spec_k,
                    "draft_tokens_total": sched.spec_drafted,
                    "accepted_tokens_total": sched.spec_accepted,
                    "accept_rate": round(rate, 4),
                }
            if sched.prefix_cache is not None:
                out["prefix_cache"] = sched.prefix_cache.stats()
        return out

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
