"""Fleet router: spread tenants across servers, health-gated.

:class:`FleetRouter` fronts N serving backends (typically
:class:`~paddle_tpu.serving.fleet.ModelFleet` instances, but anything
with ``submit()`` / ``healthz()`` / ``close()`` routes) and assigns each
tenant a home server by **rendezvous hashing**: every (tenant, server)
pair gets a score ``sha256(tenant|server)`` and the tenant lands on its
highest-scoring HEALTHY server.  Rendezvous beats modulo here because
membership changes move only the tenants whose winner died — no global
reshuffle, so session affinity and per-entry warm state survive a single
server's funeral (docs/serving.md "Fleet serving").

Membership is health-gated with the gang heartbeat discipline
(resilience/cluster.py): a server must fail ``probe_budget`` CONSECUTIVE
health probes before it is marked dead (one slow probe is weather, a
streak is a death), and must pass ``probes_to_join`` consecutive probes
to rejoin.  A dead or unready server drains TYPED — requests that would
have routed to it fail with :class:`RouterDrainingError` naming the
server, or re-route when ``failover=True`` — never a black hole.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

from paddle_tpu.serving.errors import ServingError
from paddle_tpu.utils.error import ConfigError
from paddle_tpu.utils.log import logger

__all__ = ["FleetRouter", "RouterDrainingError", "rendezvous_rank"]


class RouterDrainingError(ServingError):
    """The tenant's home server is dead/unready and failover is off —
    the request is refused typed, naming the draining server."""

    def __init__(self, message: str, *, server: str = "") -> None:
        super().__init__(message)
        self.server = server


def rendezvous_rank(tenant: str, servers: List[str]) -> List[str]:
    """Servers ranked by rendezvous (highest-random-weight) score for
    ``tenant`` — deterministic, and removing one server only reassigns
    the tenants it was winning."""
    def score(s: str) -> int:
        h = hashlib.sha256(f"{tenant}|{s}".encode()).digest()
        return int.from_bytes(h[:8], "big")

    return sorted(servers, key=score, reverse=True)


class _Member:
    """One backend's membership record."""

    def __init__(self, name: str, backend) -> None:
        self.name = name
        self.backend = backend
        # alive | dead — flips only on full probe streaks;
        # tpu-lint: guarded-by=FleetRouter._lock - routing must read a settled verdict
        self.state = "alive"
        self.fail_streak = 0
        self.pass_streak = 0
        self.last_error: Optional[str] = None


class FleetRouter:
    """Tenant-sharded router over named serving backends.

    ``servers`` maps name -> backend.  ``probe_budget`` consecutive
    failed probes mark a member dead; ``probes_to_join`` consecutive
    passes bring it back.  ``failover=True`` re-routes a drained
    tenant to its next rendezvous choice instead of refusing typed.
    """

    def __init__(self, servers: Dict[str, Any], *,
                 probe_budget: int = 3, probes_to_join: int = 2,
                 failover: bool = True,
                 clock=time.monotonic) -> None:
        if not servers:
            raise ConfigError("FleetRouter needs at least one server")
        if probe_budget < 1 or probes_to_join < 1:
            raise ConfigError("probe_budget and probes_to_join must be >= 1")
        self._clock = clock
        self._lock = threading.Lock()
        # membership table — tpu-lint: guarded-by=_lock - probe verdicts and routing reads interleave
        self._members = {n: _Member(n, b) for n, b in servers.items()}
        self.probe_budget = int(probe_budget)
        self.probes_to_join = int(probes_to_join)
        self.failover = failover
        self.routed = {n: 0 for n in servers}
        self.drained = 0

    # ------------------------------------------------------------------
    # health-gated membership
    # ------------------------------------------------------------------

    def probe(self) -> Dict[str, str]:
        """One probe round over every member: a backend probe passes iff
        ``healthz()`` returns with ``ready=True``.  State flips only on
        full streaks (the heartbeat discipline: one miss is weather, a
        streak is a verdict).  Returns the post-probe states."""
        verdicts = {}
        for name, member in list(self._members.items()):
            ok, err = self._probe_one(member.backend)
            with self._lock:
                if ok:
                    member.pass_streak += 1
                    member.fail_streak = 0
                    member.last_error = None
                    if (member.state == "dead"
                            and member.pass_streak >= self.probes_to_join):
                        member.state = "alive"
                        logger.info("router: server %s rejoined after %d "
                                    "clean probes", name, member.pass_streak)
                else:
                    member.fail_streak += 1
                    member.pass_streak = 0
                    member.last_error = err
                    if (member.state == "alive"
                            and member.fail_streak >= self.probe_budget):
                        member.state = "dead"
                        logger.warning(
                            "router: server %s marked dead after %d "
                            "consecutive probe failures (%s) — draining "
                            "typed", name, member.fail_streak, err)
                verdicts[name] = member.state
        return verdicts

    @staticmethod
    def _probe_one(backend) -> tuple:
        try:
            h = backend.healthz()
        except Exception as e:  # noqa: BLE001 — a throwing probe is a miss
            return False, f"{type(e).__name__}: {e}"
        if not h.get("ready", False):
            return False, "not ready"
        return True, None

    def members(self) -> Dict[str, dict]:
        with self._lock:
            return {n: {"state": m.state, "fail_streak": m.fail_streak,
                        "pass_streak": m.pass_streak,
                        "last_error": m.last_error,
                        "routed": self.routed[n]}
                    for n, m in self._members.items()}

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------

    def server_for(self, tenant: str) -> str:
        """The tenant's current home: its best-ranked ALIVE server
        (or, with ``failover=False``, its best-ranked server
        unconditionally — the caller sees the drain typed)."""
        with self._lock:
            ranked = rendezvous_rank(tenant, sorted(self._members))
            if not self.failover:
                return ranked[0]
            for name in ranked:
                if self._members[name].state == "alive":
                    return name
            return ranked[0]

    def submit(self, feed, *, tenant: str, **kw):
        """Route one request to the tenant's home server, typed end to
        end: a dead home either fails with :class:`RouterDrainingError`
        (``failover=False``) or re-routes down the tenant's rendezvous
        order — a request is NEVER queued on a server known to be dead."""
        if not tenant:
            raise ConfigError("router routes by tenant: tenant= is required")
        name = self.server_for(tenant)
        with self._lock:
            member = self._members[name]
            if member.state != "alive":
                self.drained += 1
                raise RouterDrainingError(
                    f"tenant {tenant!r}: home server {name!r} is draining "
                    f"({member.last_error or 'dead'}) and no healthy "
                    f"failover exists", server=name)
            self.routed[name] += 1
        return member.backend.submit(feed, tenant=tenant, **kw)

    def healthz(self) -> dict:
        members = self.members()
        return {
            "ready": any(m["state"] == "alive" for m in members.values()),
            "servers": members,
            "drained": self.drained,
        }

    def close(self, join_timeout: float = 2.0) -> None:
        for member in self._members.values():
            try:
                member.backend.close(join_timeout)
            except TypeError:
                member.backend.close()
            except Exception:  # noqa: BLE001 — close the rest anyway
                logger.warning("router: closing %s failed", member.name)
