"""Supervised inference worker — the serving-tier sibling of
``resilience.cluster.GangSupervisor``.

One worker thread drains the batch queue; one monitor thread supervises
it with the same discipline the gang supervisor applies to ranks:

- **crash** — an exception escaping the serve loop kills the worker; the
  monitor fails the in-flight batch with a typed :class:`WorkerCrashed`
  (reply-or-error, never a silent drop) and relaunches after exponential
  backoff (``backoff_s * 2^attempt``, capped), bounded by
  ``max_restarts``;
- **hang** — a batch stuck on the device past ``hang_timeout_s`` (Python
  threads cannot be killed) gets *abandoned*: its generation counter is
  retired so a later wake-up finds its results unwanted (futures are
  set-once and already failed), and a fresh worker takes over;
- **budget exhausted** — ``on_give_up`` flips the server into its failed
  state, draining the queue with typed errors, exactly as
  ``GangFailedError`` ends a gang.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from paddle_tpu.utils.log import logger

__all__ = ["WorkerSupervisor"]


class WorkerSupervisor:
    def __init__(
        self,
        serve_once: Callable[[int], None],   # serve_once(generation)
        *,
        max_restarts: int = 3,
        backoff_s: float = 0.05,
        max_backoff_s: float = 2.0,
        hang_timeout_s: float = 0.0,         # 0 = hang detection off
        poll_s: float = 0.01,
        on_crash: Callable[[Exception], None],
        on_give_up: Callable[[Exception], None],
        on_relaunch: Optional[Callable[[], None]] = None,
        clock=time.monotonic,
        sleep=time.sleep,
    ) -> None:
        self._serve_once = serve_once
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.hang_timeout_s = float(hang_timeout_s)
        self.poll_s = float(poll_s)
        self._on_crash = on_crash
        self._on_give_up = on_give_up
        self._on_relaunch = on_relaunch
        self._clock = clock
        self._sleep = sleep
        self.restarts = 0
        self._generation = 0  # tpu-lint: guarded-by=none - monotonic int bumped only under _lock; lock-free == probes are advisory: an abandoned worker runs at most one extra loop, and every state COMMIT re-checks under the slot table's lock
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._worker: Optional[threading.Thread] = None  # tpu-lint: guarded-by=none - swapped under _lock; readers snapshot the reference once (atomic under the GIL) and at worst see the previous generation's thread for one poll
        self._monitor: Optional[threading.Thread] = None
        self._crash_exc: Optional[Exception] = None  # tpu-lint: guarded-by=none - written only by the dying worker thread; the monitor reads it only after alive() goes False, and thread death publishes the write
        self._busy_since: Optional[float] = None  # tpu-lint: guarded-by=none - atomic reference swap by the live worker only; the monitor snapshots once per poll, so a stale value shifts hang detection by at most one poll

    # -- the worker side ----------------------------------------------------

    def _worker_main(self, gen: int) -> None:
        try:
            while not self._stop.is_set() and gen == self._generation:
                self._serve_once(gen)
        except Exception as e:  # noqa: BLE001 — any escape is a crash
            if gen == self._generation:
                self._crash_exc = e

    def note_busy(self, gen: int) -> None:
        if gen == self._generation:
            self._busy_since = self._clock()

    def note_idle(self, gen: int) -> None:
        if gen == self._generation:
            self._busy_since = None

    def current(self, gen: int) -> bool:
        """Is ``gen`` still the live worker generation?  An abandoned
        (hung-then-replaced) worker uses this to stop touching shared
        state when it finally wakes up."""
        return gen == self._generation and not self._stop.is_set()

    # -- the supervisor side ------------------------------------------------

    def start(self) -> None:
        self._spawn_worker()
        self._monitor = threading.Thread(
            target=self._monitor_main, name="serving-monitor", daemon=True)
        self._monitor.start()

    def _spawn_worker(self) -> None:
        with self._lock:
            self._generation += 1
            gen = self._generation
            self._crash_exc = None
            self._busy_since = None
        if gen > 1 and self._on_relaunch is not None:
            # stateful workers (the generation-mode slot table) rebuild
            # their state BEFORE the replacement starts serving: a crashed
            # step may have left the carry poisoned, and the in-flight
            # requests it held were already failed typed by on_crash
            self._on_relaunch()
        with self._lock:
            if gen != self._generation:
                return  # stop() raced the relaunch: stay down
            self._worker = threading.Thread(
                target=self._worker_main, args=(gen,),
                name=f"serving-worker-{gen}", daemon=True)
            self._worker.start()

    def alive(self) -> bool:
        w = self._worker
        return w is not None and w.is_alive()

    def _monitor_main(self) -> None:
        while not self._stop.is_set():
            crashed: Optional[Exception] = None
            busy_since = self._busy_since  # single read: the worker's
            # note_idle may null the field between a test and a subtract
            if not self.alive():
                crashed = self._crash_exc or RuntimeError("worker died")
            elif (self.hang_timeout_s > 0 and busy_since is not None
                  and self._clock() - busy_since > self.hang_timeout_s):
                crashed = TimeoutError(
                    f"worker hung: batch in flight for more than "
                    f"{self.hang_timeout_s:.3f}s")
            if crashed is not None:
                if self._stop.is_set():  # shutdown, not a crash
                    return
                # retire the generation FIRST: a hung worker that
                # un-wedges during the backoff below must find itself
                # abandoned immediately — if it could still pop a batch
                # before _spawn_worker bumps the generation, that batch
                # would be silently dropped when the bump lands mid-run
                with self._lock:
                    self._generation += 1
                self._on_crash(crashed)
                if self.restarts >= self.max_restarts:
                    # no relaunch happens for the budget-exhausting crash:
                    # `restarts` counts relaunches actually performed
                    logger.error(
                        "serving worker burned its restart budget "
                        "(%d restarts): %s", self.max_restarts, crashed)
                    self._on_give_up(crashed)
                    return
                self.restarts += 1
                backoff = min(self.backoff_s * (2 ** (self.restarts - 1)),
                              self.max_backoff_s)
                logger.warning(
                    "serving worker %s (%s); restart %d/%d after %.3fs",
                    "hung" if isinstance(crashed, TimeoutError) else "crashed",
                    crashed, self.restarts, self.max_restarts, backoff)
                self._sleep(backoff)
                if self._stop.is_set():
                    return
                self._spawn_worker()
            self._sleep(self.poll_s)

    def stop(self, join_timeout: float = 2.0) -> None:
        self._stop.set()
        with self._lock:
            self._generation += 1  # retire the live worker generation
        for t in (self._worker, self._monitor):
            if t is not None and t is not threading.current_thread():
                t.join(join_timeout)
