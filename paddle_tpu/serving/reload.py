"""Zero-downtime hot-swap of published model versions (docs/publish.md).

``HotSwapManager`` watches a publish directory (paddle_tpu/publish) from
the serving side and drives the reload state machine::

    poll() ──> newest valid version > current?
                 │  corrupt version: journaled + skipped, previous
                 │  version keeps serving
                 v
               load (architecture fingerprint) ──> audit (preflight)
                 │                                   │ fail: rollback
                 v                                   v (never swapped)
               prime OFF the hot path ──────────> swap_model()
                 │ warm cache ⇒ zero XLA compiles    │
                 v                                   v
               pserver tables ride along         PROBATION window
               (TableReader.hot_reload)              │
                                     ┌───────────────┴──────────────┐
                                     v                              v
                               probation_passed              publish_rollback
                               (prev released)               (prev swapped back)

Rollback signals (each journaled as ``publish_rollback`` naming the
signal): ``warmup_failure``, ``audit_failure``, ``breaker_trip``,
``error_rate_regression`` (NaN-poisoned weights fail requests typed —
``nonfinite='error'`` — so a poisoned version regresses the error rate
within its first probation requests), and ``table_reload_stalled``
(the typed :class:`~paddle_tpu.pserver.snapshot.ReloadStopped` accessor).
The previous model stays resident until probation passes, so a rollback
is one attribute swap — no reload, no compile, no downtime.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.utils.log import logger

__all__ = ["HotSwapManager", "error_baseline", "load_published"]


def error_baseline(server) -> Dict[str, float]:
    """Snapshot one server's error counters as a probation baseline.

    Shared by the single-server :class:`HotSwapManager` probation and
    the per-entry fleet probation (serving/fleet.py): each candidate is
    judged against a baseline captured at ITS swap/rollout moment, so a
    fleet of entries can run independent probation windows."""
    m = server.metrics
    baseline = {
        "completed": m.count("completed"),
        "inference_failed": m.count("inference_failed"),
        "worker_crashed": m.count("worker_crashed"),
        "breaker_trips": server.breaker.trips,
    }
    done = baseline["completed"] + baseline["inference_failed"]
    baseline["error_rate"] = (baseline["inference_failed"] / done
                              if done else 0.0)
    return baseline


def _version_info(model, manifest: Dict[str, Any], vdir: str) -> dict:
    return {
        "bundle": os.path.join(vdir, "model.ptz"),
        "version": int(manifest.get("version", 0)),
        "fingerprint": model.fingerprint,
        "quantize": manifest.get("quantize"),
        "train_commit_time": manifest.get("train_commit_time"),
        "pass_id": manifest.get("pass_id"),
    }


def load_published(publish_dir: str, *, max_version: Optional[int] = None):
    """Load the newest VALID published version (newest-first walk):
    a version that fails its CRC manifest is journaled
    (``publish_skipped_corrupt``) and skipped — a torn or bit-rotted
    publish must never take a booting replica down when an older good
    version exists.  Returns ``(model, info, version)``."""
    from paddle_tpu.config.deploy import (BundleCorruptError,
                                          load_inference_model)
    from paddle_tpu.obs import journal_event
    from paddle_tpu.publish import (list_versions, read_version_manifest,
                                    validate_version, version_dir)

    for v in reversed(list_versions(publish_dir)):
        if max_version is not None and v > max_version:
            continue
        vdir = version_dir(publish_dir, v)
        bad = validate_version(vdir)
        if bad is None:
            try:
                model = load_inference_model(
                    os.path.join(vdir, "model.ptz"), arch_fingerprint=True)
            except (BundleCorruptError, ValueError) as e:
                bad = str(e)
        if bad is not None:
            journal_event("publish_skipped_corrupt", version=v, reason=bad)
            logger.warning("publish v%d is corrupt (%s) — skipped", v, bad)
            continue
        return model, _version_info(model, read_version_manifest(vdir),
                                    vdir), v
    raise FileNotFoundError(
        f"no valid published version under {publish_dir!r}")


class HotSwapManager:
    """Drive gated hot-reloads of one :class:`InferenceServer` from a
    publish directory.  ``poll()`` discovers/loads/primes/swaps new
    versions; ``tick()`` advances the probation window (both are cheap
    no-ops when there is nothing to do, so a serve loop can call them on
    its heartbeat).  All device-bound work (load, prime) happens in the
    CALLER's thread — the worker keeps serving the current model
    throughout; only the final attribute swap touches the hot path."""

    def __init__(self, server, publish_dir: str, *,
                 probation_requests: int = 32,
                 probation_seconds: float = 0.0,
                 error_rate_margin: float = 0.25,
                 min_probation_samples: int = 4,
                 preflight: bool = False,
                 table_reader=None,
                 clock=time.monotonic) -> None:
        self.server = server
        self.publish_dir = publish_dir
        self.probation_requests = int(probation_requests)
        self.probation_seconds = float(probation_seconds)
        self.error_rate_margin = float(error_rate_margin)
        self.min_probation_samples = int(min_probation_samples)
        self.preflight = preflight
        self.table_reader = table_reader
        self._clock = clock
        #: the committed (serving, past-probation) version
        self.current_version = 0
        #: versions that failed load/audit/warmup/probation — never retried
        #: (a fixed model is REPUBLISHED as a new version)
        self.rejected: Dict[int, str] = {}
        self._probation: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------------

    def attach_current(self, version: int, info: Optional[dict]) -> None:
        """Adopt the version the server was booted with (no probation:
        the boot's warmup gate already vouched for it)."""
        self.current_version = int(version)
        if info:
            self.server.set_model_info(info)

    @property
    def in_probation(self) -> bool:
        return self._probation is not None

    @property
    def probation_version(self) -> Optional[int]:
        return self._probation["version"] if self._probation else None

    # ------------------------------------------------------------------
    # discovery + swap
    # ------------------------------------------------------------------

    def _candidate(self) -> Optional[Tuple[int, str]]:
        from paddle_tpu.obs import journal_event
        from paddle_tpu.publish import (list_versions, validate_version,
                                        version_dir)

        floor = max(self.current_version,
                    self._probation["version"] if self._probation else 0)
        for v in reversed(list_versions(self.publish_dir)):
            if v <= floor:
                return None
            if v in self.rejected:
                continue
            vdir = version_dir(self.publish_dir, v)
            bad = validate_version(vdir)
            if bad is not None:
                # corrupt publish: skipped for good, previous version
                # keeps serving (chaos.corrupt_publish acceptance)
                self.rejected[v] = f"corrupt: {bad}"
                journal_event("publish_skipped_corrupt", version=v,
                              reason=bad)
                self.server.metrics.inc("reload_skipped_corrupt")
                logger.warning("publish v%d is corrupt (%s) — skipped, "
                               "v%d keeps serving", v, bad, floor)
                continue
            return v, vdir
        return None

    def poll(self) -> Optional[dict]:
        """One reload cycle: advance probation, then — if a newer valid
        version exists — load + audit + prime it off the hot path and
        swap.  Returns an action dict (``swapped`` / ``rolled_back`` /
        ``committed`` / ``rejected``) or None when nothing changed."""
        action = self.tick()
        if self._probation is not None:
            # one version in flight at a time: a newer publish waits for
            # the probation verdict (it will be picked up next poll)
            return action
        cand = self._candidate()
        if cand is None:
            return action
        v, vdir = cand
        return self._load_and_swap(v, vdir)

    def _load_and_swap(self, v: int, vdir: str) -> dict:
        from paddle_tpu.config.deploy import (BundleCorruptError,
                                              load_inference_model)
        from paddle_tpu.obs import journal_event
        from paddle_tpu.publish import read_version_manifest

        t0 = time.time()
        try:
            manifest = read_version_manifest(vdir)
            model = load_inference_model(os.path.join(vdir, "model.ptz"),
                                         arch_fingerprint=True)
        except (BundleCorruptError, ValueError, OSError) as e:
            self.rejected[v] = f"load: {e}"
            journal_event("publish_skipped_corrupt", version=v,
                          reason=str(e))
            self.server.metrics.inc("reload_skipped_corrupt")
            logger.warning("publish v%d failed to load (%s) — skipped",
                           v, e)
            return {"action": "rejected", "version": v, "signal": "load"}
        if self.preflight:
            from paddle_tpu.serving.preflight import check_serving

            try:
                check_serving(model, outputs=self.server._outputs)
            except Exception as e:  # noqa: BLE001 — any audit failure
                return self._refuse(v, "audit_failure", str(e))
        # prime the new model's whole bucket surface OFF the hot path;
        # with the publish dir's warm cache + architecture fingerprint
        # this is pure deserialization — zero XLA compiles
        try:
            counts = self.server.prime_model(model)
        except Exception as e:  # noqa: BLE001 — a bad model must not swap
            return self._refuse(v, "warmup_failure",
                                f"{type(e).__name__}: {e}")
        # pserver-backed tables ride along: replay the snapshot delta
        # before the swap so the new model never serves stale rows
        if self.table_reader is not None:
            try:
                self.table_reader.hot_reload()
            except Exception as e:  # noqa: BLE001
                return self._refuse(v, "table_reload_failed", str(e))
            stop = getattr(self.table_reader, "last_stop", None)
            if stop is not None:
                return self._refuse(v, "table_reload_stalled", str(stop))
        baseline = error_baseline(self.server)
        prev_info = self.server._model_info
        info = _version_info(model, manifest, vdir)
        prev_model = self.server.swap_model(model, info=info)
        journal_event("reload_commit", fsync=True, version=v,
                      pass_id=info.get("pass_id"),
                      fingerprint=model.fingerprint,
                      train_commit_time=info.get("train_commit_time"),
                      prime=counts, swap_s=round(time.time() - t0, 3))
        self._probation = {
            "version": v,
            "started": self._clock(),
            "baseline": baseline,
            "prev_model": prev_model,
            "prev_info": prev_info,
            "prev_version": self.current_version,
        }
        logger.info("hot-swapped to publish v%d (probation: %d requests"
                    "%s)", v, self.probation_requests,
                    f" / {self.probation_seconds:.0f}s"
                    if self.probation_seconds else "")
        return {"action": "swapped", "version": v, "prime": counts}

    def _refuse(self, v: int, signal: str, detail: str) -> dict:
        """A version that failed BEFORE the swap: the previous bundle
        keeps serving (the 'revert' is a no-op) — journaled under the
        same ``publish_rollback`` kind so the timeline names every
        version that never reached committed, with its failing signal."""
        from paddle_tpu.obs import journal_event

        self.rejected[v] = f"{signal}: {detail}"
        journal_event("publish_rollback", fsync=True, version=v,
                      signal=signal, detail=detail,
                      rolled_back_to=self.current_version)
        self.server.metrics.inc("reload_rollbacks")
        logger.warning("publish v%d refused before swap (%s): %s",
                       v, signal, detail)
        return {"action": "rolled_back", "version": v, "signal": signal}

    # ------------------------------------------------------------------
    # probation
    # ------------------------------------------------------------------

    def tick(self) -> Optional[dict]:
        """Advance the probation window: check the rollback signals
        against the pre-swap baseline, commit when the window closes."""
        p = self._probation
        if p is None:
            return None
        m = self.server.metrics
        base = p["baseline"]
        if self.server.breaker.trips > base["breaker_trips"]:
            return self._rollback("breaker_trip")
        if self.table_reader is not None and \
                getattr(self.table_reader, "last_stop", None) is not None:
            return self._rollback("table_reload_stalled")
        completed = m.count("completed") - base["completed"]
        failed = m.count("inference_failed") - base["inference_failed"]
        resolved = completed + failed
        if resolved >= self.min_probation_samples:
            rate = failed / resolved
            if rate > base["error_rate"] + self.error_rate_margin:
                return self._rollback(
                    "error_rate_regression",
                    detail=f"probation error rate {rate:.3f} vs "
                           f"baseline {base['error_rate']:.3f}")
        elapsed = self._clock() - p["started"]
        if (resolved >= self.probation_requests
                or (self.probation_seconds > 0
                    and elapsed >= self.probation_seconds)):
            return self._commit(resolved)
        return None

    def _commit(self, resolved: int) -> dict:
        from paddle_tpu.obs import journal_event

        p, self._probation = self._probation, None
        self.current_version = p["version"]
        # release the previous bundle: probation passed, rollback can no
        # longer need it resident
        journal_event("probation_passed", fsync=True, version=p["version"],
                      requests=resolved)
        self.server.metrics.inc("reload_probation_passed")
        logger.info("publish v%d committed (probation passed after %d "
                    "requests)", p["version"], resolved)
        return {"action": "committed", "version": p["version"]}

    def _rollback(self, signal: str, detail: str = "") -> dict:
        from paddle_tpu.obs import journal_event

        p, self._probation = self._probation, None
        v = p["version"]
        self.rejected[v] = f"{signal}: {detail}" if detail else signal
        # the previous model stayed resident for exactly this moment:
        # rollback is one attribute swap, zero compiles, zero downtime
        self.server.swap_model(p["prev_model"], info=p["prev_info"])
        self.current_version = p["prev_version"]
        journal_event("publish_rollback", fsync=True, version=v,
                      signal=signal, detail=detail,
                      rolled_back_to=p["prev_version"])
        self.server.metrics.inc("reload_rollbacks")
        logger.warning("publish v%d rolled back to v%d (%s)%s",
                       v, p["prev_version"], signal,
                       f": {detail}" if detail else "")
        return {"action": "rolled_back", "version": v, "signal": signal,
                "rolled_back_to": p["prev_version"]}

    # ------------------------------------------------------------------

    def watch(self, stop_event, *, poll_s: float = 2.0,
              tick_s: float = 0.2) -> None:
        """Blocking watch loop for the serve CLI: poll the publish dir
        every ``poll_s``, advance probation every ``tick_s``, until
        ``stop_event`` is set."""
        next_poll = 0.0
        while not stop_event.is_set():
            now = self._clock()
            try:
                if now >= next_poll:
                    next_poll = now + poll_s
                    self.poll()
                else:
                    self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                logger.warning("reload watch: %s: %s", type(e).__name__, e)
            stop_event.wait(tick_s)
