"""Observable health surface of the serving runtime.

One ``ServerMetrics`` instance per server, now a VIEW over the shared
``paddle_tpu.obs`` metrics registry (docs/observability.md): every
counter is a registry counter ``serving_<name>{server=<id>}``, and
completed-request latency additionally feeds the registry histogram
``serving_latency_seconds`` — so a ``--metrics_port`` scrape and
``healthz()`` read the SAME monotonic series and can never tell
different stories.  Counters are named after the typed error that
produced them, so the health surface and the exception surface agree
too.

The ``snapshot()`` schema is pinned by tests/test_serving.py: every
``_COUNTERS`` key is pre-seeded (a dashboard sees ``shed=0``, not a
missing key, before the first shed) and the percentile definition is the
same nearest-rank rule ``percentile_ms`` uses.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Dict, Optional

__all__ = ["ServerMetrics"]

#: counter names pre-seeded so a snapshot always carries the full schema
#: (a dashboard should see shed=0, not a missing key, before the first shed)
_COUNTERS = (
    "submitted",        # every submit() call, accepted or not
    "accepted",         # admitted to the queue
    "completed",        # replied with outputs, inside the deadline
    "shed",             # ShedError at admission (queue overflow / warming)
    "invalid_request",      # InvalidRequestError (malformed / oversized)
    "deadline_infeasible",  # DeadlineExceeded at admission
    "deadline_expired",     # DeadlineExceeded after acceptance
    "breaker_rejected",     # CircuitOpenError (admission or execution)
    "breaker_trips",        # CLOSED -> OPEN transitions
    "inference_failed",     # model raised / non-finite outputs
    "worker_crashed",       # requests failed by a worker death/hang
    "server_closed",        # requests drained by shutdown (queued/in-flight)
    "worker_restarts",      # supervisor relaunches
    "degraded",             # requests executed at a degraded tier (>0)
    "batches",              # model invocations
    # continuous batching (generation mode; serving/slots.py)
    "gen_steps",            # fused decode_step calls over the slot table
    "slot_recycled",        # slots freed (harvest or eviction) for reuse
    "slot_evicted",         # slots released by mid-generation deadline expiry
    # fleet cold-start (docs/deploy.md; config/compile_cache.py)
    "compile_cache_hits",    # warmup executables LOADED from the cache
    "compile_cache_misses",  # warmup executables compiled + stored
    "warmup_compiles",       # XLA compiles paid by the readiness gate
    # decode raw speed (docs/decode.md "Speculative decoding";
    # serving/prefix_cache.py; serving/paging.py)
    "spec_draft_tokens_total",     # draft tokens offered to wide verify
    "spec_accepted_tokens_total",  # draft tokens the model confirmed
    "prefix_cache_hits",           # admissions served from cached prefill
    "prefix_cache_misses",         # admissions that ran the encoder
    "slots_paged_out",             # slot carries host-evicted to the pool
    "slots_paged_in",              # parked carries restored bit-for-bit
)

#: distinguishes the registry children of servers sharing one process
_server_ids = itertools.count()


class ServerMetrics:
    def __init__(self, window: int = 512, registry=None) -> None:
        from paddle_tpu.obs import get_registry

        reg = registry if registry is not None else get_registry()
        self._label = f"s{next(_server_ids)}"
        self._counters = {
            name: reg.counter("serving_" + name,
                              "serving counter (docs/serving.md)",
                              labels=("server",), server=self._label)
            for name in _COUNTERS
        }
        self._registry = reg
        self._gauges = {}
        self._latency_hist = reg.histogram(
            "serving_latency_seconds",
            "completed-request latency", labels=("server",),
            server=self._label)
        self._lock = threading.Lock()
        self._latencies = deque(maxlen=window)  # seconds, completed only
        self._batch_rows = deque(maxlen=window)
        self._occupancy = deque(maxlen=window)  # occupied/capacity per step
        self._req_steps = deque(maxlen=window)  # decode steps per request

    def gauge(self, name: str):
        """Per-server registry gauge ``serving_<name>{server=...}`` —
        the model-freshness / version surface of the hot-reload path
        (docs/publish.md).  Created on first use; retired with the
        counters by ``unregister``."""
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.get(name)
                if g is None:
                    g = self._gauges[name] = self._registry.gauge(
                        "serving_" + name, "serving gauge (docs/serving.md)",
                        labels=("server",), server=self._label)
        return g

    def _counter(self, name: str):
        c = self._counters.get(name)
        if c is None:
            # unknown names keep working (the old dict accepted any key);
            # insertion under the lock so a concurrent snapshot() never
            # iterates a dict changing size
            with self._lock:
                c = self._counters.get(name)
                if c is None:
                    c = self._counters[name] = self._registry.counter(
                        "serving_" + name, "serving counter (dynamic)",
                        labels=("server",), server=self._label)
        return c

    def inc(self, name: str, n: int = 1) -> None:
        self._counter(name).inc(n)

    def observe_latency(self, seconds: float,
                        trace_id: Optional[str] = None) -> None:
        """``trace_id`` (when request tracing is armed) rides the latency
        histogram bucket as an EXEMPLAR: a p99 spike on a dashboard links
        straight to a concrete retained trace (`obs trace --trace=ID`)."""
        self._latency_hist.observe(seconds, exemplar=trace_id)
        with self._lock:
            self._latencies.append(seconds)

    def observe_batch(self, rows: int) -> None:
        self._counter("batches").inc()
        with self._lock:
            self._batch_rows.append(rows)

    def observe_slots(self, occupied: int, capacity: int) -> None:
        """Slot-table occupancy at one fused step (generation mode) — the
        utilization the recycle loop exists to maximize."""
        with self._lock:
            self._occupancy.append(occupied / max(1, capacity))

    def observe_request_steps(self, steps: int) -> None:
        """Decode steps one completed request consumed (its slot-residency
        in step units)."""
        with self._lock:
            self._req_steps.append(int(steps))

    def count(self, name: str) -> int:
        c = self._counters.get(name)
        return 0 if c is None else int(c.value)

    def unregister(self) -> None:
        """Drop this server's series from the shared registry exposition
        (called on server close): a process that creates and retires many
        servers must not scrape dead servers' counters forever.  The
        local child objects keep working — a closed server's
        ``healthz()`` still reads its final numbers."""
        with self._lock:
            names = list(self._counters)
            gnames = list(self._gauges)
        for name in names:
            self._registry.remove_series("serving_" + name,
                                         server=self._label)
        for name in gnames:
            self._registry.remove_series("serving_" + name,
                                         server=self._label)
        self._registry.remove_series("serving_latency_seconds",
                                     server=self._label)

    def set_count(self, name: str, value: int) -> None:
        """Force a counter to an externally-owned value (the supervisor
        owns worker_restarts — healthz mirrors it, and the registry view
        must agree).  Atomic: concurrent healthz probes mirroring the
        same value must not race a read-then-inc into a wrong total."""
        self._counter(name).set_to(value)

    @staticmethod
    def _pct_ms(lat_sorted, p: float) -> Optional[float]:
        """Nearest-rank percentile of a sorted seconds list, in ms — THE
        percentile definition; healthz and percentile_ms must agree."""
        if not lat_sorted:
            return None
        n = len(lat_sorted)
        idx = min(n - 1, max(0, int(round(p / 100.0 * n)) - 1))
        return lat_sorted[idx] * 1e3

    def percentile_ms(self, p: float) -> Optional[float]:
        with self._lock:
            lat = sorted(self._latencies)
        return self._pct_ms(lat, p)

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._counters.items())
        counters = {name: int(c.value) for name, c in items}
        with self._lock:
            lat = sorted(self._latencies)
            rows = list(self._batch_rows)
            occ = list(self._occupancy)
            steps = list(self._req_steps)

        def pct(p):
            ms = self._pct_ms(lat, p)
            return None if ms is None else round(ms, 3)

        return {
            "counters": counters,
            "p50_ms": pct(50),
            "p99_ms": pct(99),
            "mean_batch_rows": (round(sum(rows) / len(rows), 2)
                                if rows else None),
            "mean_slot_occupancy": (round(sum(occ) / len(occ), 4)
                                    if occ else None),
            "mean_request_steps": (round(sum(steps) / len(steps), 2)
                                   if steps else None),
        }
