"""Observable health surface of the serving runtime.

One lock-protected ``ServerMetrics`` instance per server: monotonic
counters for every admission/ completion/ failure path, a rolling latency
window with p50/p99, and the ``snapshot()`` dict that backs
``InferenceServer.healthz()``.  Counters are named after the typed error
that produced them so the health surface and the exception surface can
never tell different stories.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

__all__ = ["ServerMetrics"]

#: counter names pre-seeded so a snapshot always carries the full schema
#: (a dashboard should see shed=0, not a missing key, before the first shed)
_COUNTERS = (
    "submitted",        # every submit() call, accepted or not
    "accepted",         # admitted to the queue
    "completed",        # replied with outputs, inside the deadline
    "shed",             # ShedError at admission (queue overflow / warming)
    "invalid_request",      # InvalidRequestError (malformed / oversized)
    "deadline_infeasible",  # DeadlineExceeded at admission
    "deadline_expired",     # DeadlineExceeded after acceptance
    "breaker_rejected",     # CircuitOpenError (admission or execution)
    "breaker_trips",        # CLOSED -> OPEN transitions
    "inference_failed",     # model raised / non-finite outputs
    "worker_crashed",       # requests failed by a worker death/hang
    "server_closed",        # requests drained by shutdown (queued/in-flight)
    "worker_restarts",      # supervisor relaunches
    "degraded",             # requests executed at a degraded tier (>0)
    "batches",              # model invocations
    # continuous batching (generation mode; serving/slots.py)
    "gen_steps",            # fused decode_step calls over the slot table
    "slot_recycled",        # slots freed (harvest or eviction) for reuse
    "slot_evicted",         # slots released by mid-generation deadline expiry
)


class ServerMetrics:
    def __init__(self, window: int = 512) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {k: 0 for k in _COUNTERS}
        self._latencies = deque(maxlen=window)  # seconds, completed only
        self._batch_rows = deque(maxlen=window)
        self._occupancy = deque(maxlen=window)  # occupied/capacity per step
        self._req_steps = deque(maxlen=window)  # decode steps per request

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(seconds)

    def observe_batch(self, rows: int) -> None:
        with self._lock:
            self._counters["batches"] += 1
            self._batch_rows.append(rows)

    def observe_slots(self, occupied: int, capacity: int) -> None:
        """Slot-table occupancy at one fused step (generation mode) — the
        utilization the recycle loop exists to maximize."""
        with self._lock:
            self._occupancy.append(occupied / max(1, capacity))

    def observe_request_steps(self, steps: int) -> None:
        """Decode steps one completed request consumed (its slot-residency
        in step units)."""
        with self._lock:
            self._req_steps.append(int(steps))

    def count(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    @staticmethod
    def _pct_ms(lat_sorted, p: float) -> Optional[float]:
        """Nearest-rank percentile of a sorted seconds list, in ms — THE
        percentile definition; healthz and percentile_ms must agree."""
        if not lat_sorted:
            return None
        n = len(lat_sorted)
        idx = min(n - 1, max(0, int(round(p / 100.0 * n)) - 1))
        return lat_sorted[idx] * 1e3

    def percentile_ms(self, p: float) -> Optional[float]:
        with self._lock:
            lat = sorted(self._latencies)
        return self._pct_ms(lat, p)

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            lat = sorted(self._latencies)
            rows = list(self._batch_rows)
            occ = list(self._occupancy)
            steps = list(self._req_steps)

        def pct(p):
            ms = self._pct_ms(lat, p)
            return None if ms is None else round(ms, 3)

        return {
            "counters": counters,
            "p50_ms": pct(50),
            "p99_ms": pct(99),
            "mean_batch_rows": (round(sum(rows) / len(rows), 2)
                                if rows else None),
            "mean_slot_occupancy": (round(sum(occ) / len(occ), 4)
                                    if occ else None),
            "mean_request_steps": (round(sum(steps) / len(steps), 2)
                                   if steps else None),
        }
