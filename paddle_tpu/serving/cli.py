"""``python -m paddle_tpu serve`` — the serving-runtime CLI.

Usage:

    python -m paddle_tpu serve --serve_bundle=model.ptz [--serve_* ...]
    python -m paddle_tpu serve --serve_bundle=model.ptz --serve_smoke=16
    python -m paddle_tpu serve --serve_continuous --serve_smoke=16
    python -m paddle_tpu serve --serve_fleet --serve_smoke=16

Loads a deploy bundle (quantized bundles dequantize on load —
docs/deploy.md), builds an :class:`InferenceServer` from the
``--serve_*`` flags, runs the warmup/readiness gate (plus the
``--serve_preflight`` lint audit) — ``--compile_cache_dir`` defaults to
``auto``, a per-bundle cache next to the artifact (``<bundle>.ccache``),
so every boot after the first LOADS persisted executables instead of
compiling and a warm replica is ready in seconds (opt out with an
explicit ``--compile_cache_dir=``; bundle-embedded ``aot/`` members
layer underneath either way) — then either serves until
SIGTERM/SIGINT (printing a ``healthz()`` line periodically) or — with
``--serve_smoke=N`` — pushes N synthetic requests through the full
queue/batcher/worker path and exits 0 only if every one got a reply
(the CI self-test mode used by tests/test_cli.py).

``--serve_continuous`` exercises the continuous slot-batching path
(docs/serving.md "Continuous batching") and is a smoke-only surface for
now: a compact in-process seq2seq backend is admitted N mixed-length
requests (short decode budgets interleaved with full-``max_len``
stragglers — the hostage trace) through the slot scheduler, and the run
exits 0 only on zero silent drops.  Bundle-based continuous serving
needs a generation head on the bundle; production deployments build a
``SlotBackend`` and an ``InferenceServer(mode="generation")`` in-process.
"""

from __future__ import annotations

import json
import signal
import threading
from typing import List, Optional

__all__ = ["run"]


def _resolve_cache_dir(bundle: Optional[str]) -> str:
    """The serve CLI's ``--compile_cache_dir`` resolution (ROADMAP item 5
    follow-up): the default ``auto`` derives a per-bundle cache NEXT TO
    the artifact (``<bundle>.ccache``) so a replica's second boot is warm
    by default; an explicit empty value (``--compile_cache_dir=``) opts
    out, and any other value is the shared fleet cache as before.  The
    bundle-less continuous smoke has no artifact to key a default cache
    on, so ``auto`` resolves to off there.

    The derived default DEGRADES to off when the bundle's directory is
    not writable (a read-only artifact mount): a cache the operator
    never asked for must not turn a boot that worked yesterday into a
    startup crash.  An EXPLICIT cache dir keeps failing loudly — the
    operator asked for it."""
    import os

    from paddle_tpu.utils import FLAGS, logger

    d = FLAGS.compile_cache_dir
    if d != "auto":
        return d
    if not bundle:
        return ""
    derived = bundle + ".ccache"
    try:
        os.makedirs(derived, exist_ok=True)
    except OSError as e:
        logger.warning(
            "serve: per-bundle compile cache %r unavailable (%s) — "
            "booting without a cache (pass --compile_cache_dir=DIR for "
            "a writable location)", derived, e)
        return ""
    return derived


def _continuous_smoke() -> int:
    """The ``--serve_continuous --serve_smoke=N`` CI self-test: N
    mixed-length requests through the full admit/step/harvest loop —
    every one must resolve (reply or typed error) and none may be
    silently dropped; short requests must not be held hostage by the
    co-resident stragglers."""
    import numpy as np

    from paddle_tpu.serving.server import InferenceServer
    from paddle_tpu.serving.slots import example_slot_backend
    from paddle_tpu.utils import FLAGS, logger

    # --spec_decode rides the greedy-verify proof: the smoke backend
    # drops to beam_size=1 so the wide-verify path actually engages
    backend = example_slot_backend(
        beam_size=1 if FLAGS.spec_decode else 2, src_len=8, max_len=16,
        vocab=256, dim=32)
    server = InferenceServer(
        backend,
        mode="generation",
        slots=FLAGS.serve_slots,
        batch_delay_ms=FLAGS.serve_batch_delay_ms,
        max_queue=FLAGS.serve_queue_depth,
        default_deadline_ms=FLAGS.serve_deadline_ms,
        breaker_threshold=FLAGS.serve_breaker_threshold,
        breaker_cooldown_s=FLAGS.serve_breaker_cooldown_s,
        max_restarts=FLAGS.serve_max_restarts,
        restart_backoff_s=FLAGS.serve_backoff_s,
        hang_timeout_s=FLAGS.serve_hang_timeout_s,
        nonfinite=FLAGS.serve_nonfinite,
        spec_k=FLAGS.spec_k if FLAGS.spec_decode else 0,
        prefix_cache_mb=FLAGS.prefix_cache_mb,
        slot_page_pool_mb=FLAGS.slot_page_pool,
    )
    from paddle_tpu.config.compile_cache import open_cache

    server.start(preflight=FLAGS.serve_preflight,
                 compile_cache=open_cache(
                     cache_dir=_resolve_cache_dir(None)))
    print(json.dumps({"ready": server.ready, **server.healthz()},
                     default=str))
    rng = np.random.RandomState(0)
    failures = dropped = 0
    try:
        futs = []
        for i in range(FLAGS.serve_smoke):
            ids = rng.randint(3, 256, (1, 8)).astype(np.int32)
            lens = np.asarray([4 + (i % 5)], np.int32)
            # 90% short budgets, every 10th a full-max_len straggler
            max_len = backend.max_len if i % 10 == 9 else 3
            futs.append(server.submit(
                {"src": (ids, lens)},
                deadline_ms=FLAGS.serve_deadline_ms, max_len=max_len))
        for i, f in enumerate(futs):
            try:
                err = f.error(FLAGS.serve_deadline_ms / 1e3 + 60.0)
            except TimeoutError:
                dropped += 1   # a future that never resolves IS a drop
                logger.error("continuous smoke request %d never resolved", i)
                continue
            if err is not None:
                failures += 1
                logger.warning("continuous smoke request %d failed: %s",
                               i, err)
        print(json.dumps(server.healthz(), default=str))
        return 1 if (failures or dropped) else 0
    finally:
        server.close()


def _parse_tenant_spec(s: str):
    """``--tenant_spec`` grammar: ``name:weight:rate:burst`` entries,
    comma-separated; trailing fields optional (defaults from TenantSpec).
    Bad entries are ConfigError — a misconfigured tenant table must
    never boot into silent starvation."""
    from paddle_tpu.serving.tenancy import TenantSpec
    from paddle_tpu.utils.error import ConfigError

    specs = []
    for item in filter(None, (p.strip() for p in s.split(","))):
        parts = item.split(":")
        try:
            kw = {}
            if len(parts) > 1:
                kw["weight"] = float(parts[1])
            if len(parts) > 2:
                kw["rate"] = float(parts[2])
            if len(parts) > 3:
                kw["burst"] = float(parts[3])
            specs.append(TenantSpec(parts[0], **kw))
        except ValueError as e:
            raise ConfigError(
                f"--tenant_spec entry {item!r} is not "
                f"name:weight:rate:burst ({e})") from None
    return specs


def _fleet_smoke() -> int:
    """The ``--serve_fleet --serve_smoke=N`` CI self-test: two models,
    two tenants, one deliberate flood.  A 'gold' tenant streams N
    requests against model A while a 'free' tenant (tiny quota) floods
    model B past its bucket.  Exits 0 only if BOTH models served, the
    flood was rejected TYPED (QuotaExceeded observed — quotas are real),
    and the gold tenant took zero errors (cross-tenant isolation is
    real).  Pinned by tests/test_cli.py."""
    import numpy as np

    from paddle_tpu.serving.errors import QuotaExceeded
    from paddle_tpu.serving.fleet import ModelFleet
    from paddle_tpu.serving.tenancy import TenantSpec
    from paddle_tpu.utils import FLAGS, logger

    n = FLAGS.serve_smoke
    tenants = (_parse_tenant_spec(FLAGS.tenant_spec)
               if FLAGS.tenant_spec else
               [TenantSpec("gold", weight=3.0, rate=1000.0, burst=4 * n),
                TenantSpec("free", weight=1.0, rate=0.5, burst=2.0)])
    fleet = ModelFleet(
        tenants=tenants,
        probation_requests=FLAGS.serve_probation_requests,
        clock=__import__("time").monotonic)
    server_opts = dict(max_batch=FLAGS.serve_max_batch,
                       batch_delay_ms=FLAGS.serve_batch_delay_ms,
                       max_queue=FLAGS.serve_queue_depth,
                       default_deadline_ms=FLAGS.serve_deadline_ms,
                       restart_backoff_s=FLAGS.serve_backoff_s,
                       nonfinite=FLAGS.serve_nonfinite)
    feed = {"x": np.ones((1, 4), np.float32)}
    try:
        fleet.add_model("add1", lambda f, *r: {"y": f["x"] + 1},
                        server_opts=server_opts, warmup_feed=feed)
        fleet.add_model("mul2", lambda f, *r: {"y": f["x"] * 2},
                        server_opts=server_opts, warmup_feed=feed)
        gold_name, free_name = tenants[0].name, tenants[-1].name
        gold_errors = quota_rejections = served_a = served_b = 0
        for i in range(n):
            try:
                out = fleet.infer(feed, model="add1", tenant=gold_name,
                                  timeout=30.0)
                if np.allclose(out["y"], 2.0):
                    served_a += 1
            except Exception as e:  # noqa: BLE001 — every error indicts
                gold_errors += 1
                logger.warning("fleet smoke gold request %d failed: %s",
                               i, e)
            # the free tenant floods: 3 submits per gold request blows
            # its 2-token bucket — overflow must come back typed
            for _ in range(3):
                try:
                    out = fleet.infer(feed, model="mul2", tenant=free_name,
                                      timeout=30.0)
                    if np.allclose(out["y"], 2.0):
                        served_b += 1
                except QuotaExceeded:
                    quota_rejections += 1
                except Exception as e:  # noqa: BLE001
                    logger.warning("fleet smoke free request failed: %s", e)
        hz = fleet.healthz()
        print(json.dumps(hz, default=str))
        problems = []
        if not served_a:
            problems.append("model add1 never served its tenant")
        if not served_b:
            problems.append("model mul2 never served its tenant")
        if not quota_rejections:
            problems.append("the flood was never quota-rejected — "
                            "tenancy is not enforcing")
        if gold_errors:
            problems.append(f"gold tenant took {gold_errors} error(s) "
                            f"from the free tenant's flood")
        for p in problems:
            logger.error("fleet smoke: %s", p)
        return 1 if problems else 0
    finally:
        fleet.close()


def _build_server(model):
    """One InferenceServer from the ``--serve_*`` flags (bucket mode) —
    shared by the bundle, watch, and watch-smoke paths."""
    from paddle_tpu.serving.server import InferenceServer
    from paddle_tpu.utils import FLAGS

    return InferenceServer(
        model,
        max_batch=FLAGS.serve_max_batch,
        batch_delay_ms=FLAGS.serve_batch_delay_ms,
        max_queue=FLAGS.serve_queue_depth,
        default_deadline_ms=FLAGS.serve_deadline_ms,
        breaker_threshold=FLAGS.serve_breaker_threshold,
        breaker_cooldown_s=FLAGS.serve_breaker_cooldown_s,
        max_restarts=FLAGS.serve_max_restarts,
        restart_backoff_s=FLAGS.serve_backoff_s,
        hang_timeout_s=FLAGS.serve_hang_timeout_s,
        nonfinite=FLAGS.serve_nonfinite,
    )


def _watch_serve() -> int:
    """``--serve_watch``: boot from the newest valid version under
    ``--publish_dir`` (corrupt versions journaled + skipped) with the
    publish dir's SHARED warm compile cache, then hot-reload newer
    publishes as they land — zero-downtime swap, probation window,
    automatic rollback (docs/publish.md)."""
    import os

    from paddle_tpu.config.compile_cache import open_cache
    from paddle_tpu.serving.reload import HotSwapManager, load_published
    from paddle_tpu.utils import FLAGS, logger
    from paddle_tpu.utils.error import ConfigError

    if not FLAGS.publish_dir:
        raise ConfigError("serve: --serve_watch needs --publish_dir=DIR")
    model, info, version = load_published(FLAGS.publish_dir)
    server = _build_server(model)
    logger.info("serve: watching %r from v%d (probation %d requests)",
                FLAGS.publish_dir, version, FLAGS.reload_probation)
    cache = open_cache(
        bundle=info["bundle"],
        cache_dir=os.path.join(FLAGS.publish_dir, "ccache"))
    server.start(preflight=FLAGS.serve_preflight, compile_cache=cache)
    mgr = HotSwapManager(server, FLAGS.publish_dir,
                         probation_requests=FLAGS.reload_probation,
                         preflight=FLAGS.serve_preflight)
    mgr.attach_current(version, info)
    print(json.dumps({"ready": server.ready, **server.healthz()},
                     default=str))
    stop = threading.Event()

    def _stop(signum, frame):
        stop.set()

    prev = {s: signal.signal(s, _stop)
            for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        last_hz = 0.0
        import time as _time

        while not stop.is_set():
            stop.wait(1.0)
            try:
                mgr.poll()
            except Exception as e:  # noqa: BLE001 — serving must survive
                logger.warning("serve watch: %s: %s", type(e).__name__, e)
            now = _time.monotonic()
            if now - last_hz >= 10.0:
                last_hz = now
                print(json.dumps(server.healthz(), default=str), flush=True)
            if server._state != server.RUNNING:
                logger.error("serve: server left RUNNING state; exiting")
                return 1
        return 0
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
        server.close()


def _watch_smoke() -> int:
    """The ``--serve_watch --serve_smoke=N`` CI self-test: the whole
    continuous train->publish->reload loop in one process.  Train a tiny
    model, publish v1, boot the watcher from it, publish v2 from a later
    checkpoint, and stream N requests ACROSS the reload.  Exits 0 only
    if every request resolved (zero shed, zero drops), the server ended
    up serving v2, and the reload paid ZERO fresh compiles
    (``compile_cache_misses`` unchanged — the publish-time warmup plus
    the architecture fingerprint make the swap pure deserialization)."""
    import os
    import tempfile

    import numpy as np

    import paddle_tpu.nn as nn
    from paddle_tpu.config.compile_cache import open_cache
    from paddle_tpu.param.optimizers import Adam
    from paddle_tpu.publish import publish_from_checkpoints
    from paddle_tpu.serving.feeds import example_feed
    from paddle_tpu.serving.reload import HotSwapManager, load_published
    from paddle_tpu.trainer import SGDTrainer
    from paddle_tpu.utils import FLAGS, logger

    root = tempfile.mkdtemp(prefix="serve-watch-smoke-")
    save_dir = os.path.join(root, "ckpt")
    pub = FLAGS.publish_dir or os.path.join(root, "publish")

    x = nn.data("x", size=6, is_seq=True)
    pool = nn.pooling(nn.fc(x, 8, act="relu", name="h"),
                      pooling_type="max", name="pool")
    logits = nn.fc(pool, 3, act="linear", name="logits")
    label = nn.data("label", size=1, dtype="int32")
    cost = nn.classification_cost(logits, label, name="cost")
    tr = SGDTrainer(cost, Adam(learning_rate=0.05), seed=0)
    rng = np.random.RandomState(0)
    xs = rng.randn(4, 5, 6).astype(np.float32)
    lens = np.array([5, 3, 4, 5], np.int32)
    batch = {"x": (xs, lens), "label": np.zeros((4, 1), np.int32)}
    feed = example_feed(tr.topology)  # covers every data layer

    tr.train_batch(batch)
    tr.save(save_dir, 0)
    publish_from_checkpoints(pub, tr.topology, save_dir, example_feed=feed,
                             warm_max_batch=FLAGS.serve_max_batch)
    model, info, v1 = load_published(pub)
    server = _build_server(model)
    server.start(preflight=FLAGS.serve_preflight,
                 compile_cache=open_cache(
                     bundle=info["bundle"],
                     cache_dir=os.path.join(pub, "ccache")))
    n = FLAGS.serve_smoke
    mgr = HotSwapManager(server, pub,
                         probation_requests=min(FLAGS.reload_probation,
                                                max(1, n // 4)))
    mgr.attach_current(v1, info)
    print(json.dumps({"ready": server.ready, **server.healthz()},
                     default=str))
    try:
        # train on -> publish v2 while v1 serves
        tr.train_batch(batch)
        tr.save(save_dir, 1)
        publish_from_checkpoints(pub, tr.topology, save_dir,
                                 example_feed=feed,
                                 warm_max_batch=FLAGS.serve_max_batch)
        miss0 = server.metrics.count("compile_cache_misses")
        failures = 0
        for i in range(n):
            try:
                server.infer(feed, deadline_ms=FLAGS.serve_deadline_ms)
            except Exception as e:  # noqa: BLE001 — typed reply counts
                failures += 1
                logger.warning("watch smoke request %d failed: %s", i, e)
            mgr.poll()  # reload + probation ride the request stream
        for _ in range(64):  # drain probation if the stream was short
            if mgr.poll() is None and not mgr.in_probation:
                break
            server.infer(feed, deadline_ms=FLAGS.serve_deadline_ms)
        hz = server.healthz()
        print(json.dumps(hz, default=str))
        miss_delta = server.metrics.count("compile_cache_misses") - miss0
        problems = []
        if failures:
            problems.append(f"{failures} request(s) failed")
        if hz["counters"]["shed"]:
            problems.append(f"shed={hz['counters']['shed']}")
        if (hz.get("model") or {}).get("version") != 2:
            problems.append(f"still serving {hz.get('model')}")
        if mgr.current_version != 2:
            problems.append(f"v2 not committed (at v{mgr.current_version})")
        if miss_delta:
            problems.append(f"reload paid {miss_delta} fresh compile(s)")
        for p in problems:
            logger.error("watch smoke: %s", p)
        return 1 if problems else 0
    finally:
        server.close()


def run(argv: Optional[List[str]] = None) -> int:
    from paddle_tpu.config.deploy import load_inference_model
    from paddle_tpu.serving.feeds import example_feed
    from paddle_tpu.utils import FLAGS, logger
    from paddle_tpu.utils.devices import init
    from paddle_tpu.utils.error import ConfigError

    rest = init(list(argv or []))
    if rest:
        raise ConfigError(f"serve: unrecognized arguments: {rest}")
    # --metrics_port exposes the shared registry ServerMetrics now lives
    # in (docs/observability.md): /metrics + /metrics.json
    from paddle_tpu.obs import ensure_metrics_server

    ensure_metrics_server()
    if FLAGS.serve_fleet:
        if FLAGS.serve_smoke <= 0:
            raise ConfigError(
                "serve: --serve_fleet is a smoke-only CLI surface "
                "(pass --serve_smoke=N); production fleets build "
                "ModelFleet/FleetRouter in-process — docs/serving.md "
                "'Fleet serving'")
        return _fleet_smoke()
    if FLAGS.serve_watch:
        # continuous publishing consumer (docs/publish.md): smoke mode is
        # the CI self-test of the whole train->publish->reload loop
        return _watch_smoke() if FLAGS.serve_smoke > 0 else _watch_serve()
    if FLAGS.serve_continuous:
        if FLAGS.serve_smoke <= 0:
            raise ConfigError(
                "serve: --serve_continuous is a smoke-only CLI surface "
                "(pass --serve_smoke=N); production continuous serving "
                "builds InferenceServer(mode='generation') over a "
                "SlotBackend in-process — docs/serving.md")
        return _continuous_smoke()
    if not FLAGS.serve_bundle:
        raise ConfigError("serve: --serve_bundle=<model.ptz> is required")

    model = load_inference_model(FLAGS.serve_bundle)  # BundleCorruptError is typed
    server = _build_server(model)
    logger.info("serve: warming up %r (batch buckets up to %d)",
                FLAGS.serve_bundle, FLAGS.serve_max_batch)
    # persistent compiled executables (docs/deploy.md): bundle-embedded
    # aot/ members (read-only — the fleet shares the artifact) layered
    # over a shared --compile_cache_dir; a warm cache turns the whole
    # readiness gate into deserialization
    from paddle_tpu.config.compile_cache import open_cache

    cache = open_cache(bundle=FLAGS.serve_bundle,
                       cache_dir=_resolve_cache_dir(FLAGS.serve_bundle))
    server.start(preflight=FLAGS.serve_preflight, compile_cache=cache)
    print(json.dumps({"ready": server.ready, **server.healthz()},
                     default=str))

    try:
        if FLAGS.serve_smoke > 0:
            feed = example_feed(model.topology)
            failures = 0
            for i in range(FLAGS.serve_smoke):
                try:
                    server.infer(feed, deadline_ms=FLAGS.serve_deadline_ms)
                except Exception as e:  # noqa: BLE001 — typed reply counts
                    failures += 1
                    logger.warning("serve smoke request %d failed: %s", i, e)
            print(json.dumps(server.healthz(), default=str))
            return 1 if failures else 0

        # serve until SIGTERM/SIGINT (the preemption contract the training
        # tier already follows: a signal ends the loop cleanly)
        stop = threading.Event()

        def _stop(signum, frame):
            stop.set()

        prev = {s: signal.signal(s, _stop)
                for s in (signal.SIGTERM, signal.SIGINT)}
        try:
            while not stop.is_set():
                stop.wait(10.0)
                print(json.dumps(server.healthz(), default=str), flush=True)
                if server._state != server.RUNNING:
                    logger.error("serve: server left RUNNING state; exiting")
                    return 1
        finally:
            for s, h in prev.items():
                signal.signal(s, h)
        return 0
    finally:
        server.close()
