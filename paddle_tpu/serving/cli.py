"""``python -m paddle_tpu serve`` — the serving-runtime CLI.

Usage:

    python -m paddle_tpu serve --serve_bundle=model.ptz [--serve_* ...]
    python -m paddle_tpu serve --serve_bundle=model.ptz --serve_smoke=16
    python -m paddle_tpu serve --serve_continuous --serve_smoke=16

Loads a deploy bundle (quantized bundles dequantize on load —
docs/deploy.md), builds an :class:`InferenceServer` from the
``--serve_*`` flags, runs the warmup/readiness gate (plus the
``--serve_preflight`` lint audit) — ``--compile_cache_dir`` defaults to
``auto``, a per-bundle cache next to the artifact (``<bundle>.ccache``),
so every boot after the first LOADS persisted executables instead of
compiling and a warm replica is ready in seconds (opt out with an
explicit ``--compile_cache_dir=``; bundle-embedded ``aot/`` members
layer underneath either way) — then either serves until
SIGTERM/SIGINT (printing a ``healthz()`` line periodically) or — with
``--serve_smoke=N`` — pushes N synthetic requests through the full
queue/batcher/worker path and exits 0 only if every one got a reply
(the CI self-test mode used by tests/test_cli.py).

``--serve_continuous`` exercises the continuous slot-batching path
(docs/serving.md "Continuous batching") and is a smoke-only surface for
now: a compact in-process seq2seq backend is admitted N mixed-length
requests (short decode budgets interleaved with full-``max_len``
stragglers — the hostage trace) through the slot scheduler, and the run
exits 0 only on zero silent drops.  Bundle-based continuous serving
needs a generation head on the bundle; production deployments build a
``SlotBackend`` and an ``InferenceServer(mode="generation")`` in-process.
"""

from __future__ import annotations

import json
import signal
import threading
from typing import List, Optional

__all__ = ["run"]


def _resolve_cache_dir(bundle: Optional[str]) -> str:
    """The serve CLI's ``--compile_cache_dir`` resolution (ROADMAP item 5
    follow-up): the default ``auto`` derives a per-bundle cache NEXT TO
    the artifact (``<bundle>.ccache``) so a replica's second boot is warm
    by default; an explicit empty value (``--compile_cache_dir=``) opts
    out, and any other value is the shared fleet cache as before.  The
    bundle-less continuous smoke has no artifact to key a default cache
    on, so ``auto`` resolves to off there.

    The derived default DEGRADES to off when the bundle's directory is
    not writable (a read-only artifact mount): a cache the operator
    never asked for must not turn a boot that worked yesterday into a
    startup crash.  An EXPLICIT cache dir keeps failing loudly — the
    operator asked for it."""
    import os

    from paddle_tpu.utils import FLAGS, logger

    d = FLAGS.compile_cache_dir
    if d != "auto":
        return d
    if not bundle:
        return ""
    derived = bundle + ".ccache"
    try:
        os.makedirs(derived, exist_ok=True)
    except OSError as e:
        logger.warning(
            "serve: per-bundle compile cache %r unavailable (%s) — "
            "booting without a cache (pass --compile_cache_dir=DIR for "
            "a writable location)", derived, e)
        return ""
    return derived


def _continuous_smoke() -> int:
    """The ``--serve_continuous --serve_smoke=N`` CI self-test: N
    mixed-length requests through the full admit/step/harvest loop —
    every one must resolve (reply or typed error) and none may be
    silently dropped; short requests must not be held hostage by the
    co-resident stragglers."""
    import numpy as np

    from paddle_tpu.serving.server import InferenceServer
    from paddle_tpu.serving.slots import example_slot_backend
    from paddle_tpu.utils import FLAGS, logger

    backend = example_slot_backend(beam_size=2, src_len=8, max_len=16,
                                   vocab=256, dim=32)
    server = InferenceServer(
        backend,
        mode="generation",
        slots=FLAGS.serve_slots,
        batch_delay_ms=FLAGS.serve_batch_delay_ms,
        max_queue=FLAGS.serve_queue_depth,
        default_deadline_ms=FLAGS.serve_deadline_ms,
        breaker_threshold=FLAGS.serve_breaker_threshold,
        breaker_cooldown_s=FLAGS.serve_breaker_cooldown_s,
        max_restarts=FLAGS.serve_max_restarts,
        restart_backoff_s=FLAGS.serve_backoff_s,
        hang_timeout_s=FLAGS.serve_hang_timeout_s,
        nonfinite=FLAGS.serve_nonfinite,
    )
    from paddle_tpu.config.compile_cache import open_cache

    server.start(preflight=FLAGS.serve_preflight,
                 compile_cache=open_cache(
                     cache_dir=_resolve_cache_dir(None)))
    print(json.dumps({"ready": server.ready, **server.healthz()},
                     default=str))
    rng = np.random.RandomState(0)
    failures = dropped = 0
    try:
        futs = []
        for i in range(FLAGS.serve_smoke):
            ids = rng.randint(3, 256, (1, 8)).astype(np.int32)
            lens = np.asarray([4 + (i % 5)], np.int32)
            # 90% short budgets, every 10th a full-max_len straggler
            max_len = backend.max_len if i % 10 == 9 else 3
            futs.append(server.submit(
                {"src": (ids, lens)},
                deadline_ms=FLAGS.serve_deadline_ms, max_len=max_len))
        for i, f in enumerate(futs):
            try:
                err = f.error(FLAGS.serve_deadline_ms / 1e3 + 60.0)
            except TimeoutError:
                dropped += 1   # a future that never resolves IS a drop
                logger.error("continuous smoke request %d never resolved", i)
                continue
            if err is not None:
                failures += 1
                logger.warning("continuous smoke request %d failed: %s",
                               i, err)
        print(json.dumps(server.healthz(), default=str))
        return 1 if (failures or dropped) else 0
    finally:
        server.close()


def run(argv: Optional[List[str]] = None) -> int:
    from paddle_tpu.config.deploy import load_inference_model
    from paddle_tpu.serving.feeds import example_feed
    from paddle_tpu.serving.server import InferenceServer
    from paddle_tpu.utils import FLAGS, logger
    from paddle_tpu.utils.devices import init
    from paddle_tpu.utils.error import ConfigError

    rest = init(list(argv or []))
    if rest:
        raise ConfigError(f"serve: unrecognized arguments: {rest}")
    # --metrics_port exposes the shared registry ServerMetrics now lives
    # in (docs/observability.md): /metrics + /metrics.json
    from paddle_tpu.obs import ensure_metrics_server

    ensure_metrics_server()
    if FLAGS.serve_continuous:
        if FLAGS.serve_smoke <= 0:
            raise ConfigError(
                "serve: --serve_continuous is a smoke-only CLI surface "
                "(pass --serve_smoke=N); production continuous serving "
                "builds InferenceServer(mode='generation') over a "
                "SlotBackend in-process — docs/serving.md")
        return _continuous_smoke()
    if not FLAGS.serve_bundle:
        raise ConfigError("serve: --serve_bundle=<model.ptz> is required")

    model = load_inference_model(FLAGS.serve_bundle)  # BundleCorruptError is typed
    server = InferenceServer(
        model,
        max_batch=FLAGS.serve_max_batch,
        batch_delay_ms=FLAGS.serve_batch_delay_ms,
        max_queue=FLAGS.serve_queue_depth,
        default_deadline_ms=FLAGS.serve_deadline_ms,
        breaker_threshold=FLAGS.serve_breaker_threshold,
        breaker_cooldown_s=FLAGS.serve_breaker_cooldown_s,
        max_restarts=FLAGS.serve_max_restarts,
        restart_backoff_s=FLAGS.serve_backoff_s,
        hang_timeout_s=FLAGS.serve_hang_timeout_s,
        nonfinite=FLAGS.serve_nonfinite,
    )
    logger.info("serve: warming up %r (batch buckets up to %d)",
                FLAGS.serve_bundle, FLAGS.serve_max_batch)
    # persistent compiled executables (docs/deploy.md): bundle-embedded
    # aot/ members (read-only — the fleet shares the artifact) layered
    # over a shared --compile_cache_dir; a warm cache turns the whole
    # readiness gate into deserialization
    from paddle_tpu.config.compile_cache import open_cache

    cache = open_cache(bundle=FLAGS.serve_bundle,
                       cache_dir=_resolve_cache_dir(FLAGS.serve_bundle))
    server.start(preflight=FLAGS.serve_preflight, compile_cache=cache)
    print(json.dumps({"ready": server.ready, **server.healthz()},
                     default=str))

    try:
        if FLAGS.serve_smoke > 0:
            feed = example_feed(model.topology)
            failures = 0
            for i in range(FLAGS.serve_smoke):
                try:
                    server.infer(feed, deadline_ms=FLAGS.serve_deadline_ms)
                except Exception as e:  # noqa: BLE001 — typed reply counts
                    failures += 1
                    logger.warning("serve smoke request %d failed: %s", i, e)
            print(json.dumps(server.healthz(), default=str))
            return 1 if failures else 0

        # serve until SIGTERM/SIGINT (the preemption contract the training
        # tier already follows: a signal ends the loop cleanly)
        stop = threading.Event()

        def _stop(signum, frame):
            stop.set()

        prev = {s: signal.signal(s, _stop)
                for s in (signal.SIGTERM, signal.SIGINT)}
        try:
            while not stop.is_set():
                stop.wait(10.0)
                print(json.dumps(server.healthz(), default=str), flush=True)
                if server._state != server.RUNNING:
                    logger.error("serve: server left RUNNING state; exiting")
                    return 1
        finally:
            for s, h in prev.items():
                signal.signal(s, h)
        return 0
    finally:
        server.close()
